"""`det` — the CLI command tree.

≈ the reference's argparse-declarative CLI (harness/determined/cli/cli.py:200
and the per-domain modules experiment.py, trial.py, checkpoint.py, model.py,
notebook.py, shell.py, tensorboard.py, user.py, workspace.py, template.py,
agent.py, job.py), collapsed into one module: every subcommand is a thin
wrapper over MasterSession/SDK calls, printing tables or JSON.

Master address: -m/--master host:port, or DCT_MASTER env, default
127.0.0.1:8080. Login tokens persist per master in ~/.dct/auth.json
(≈ ~/.determined TokenStore, common/api/authentication.py).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from determined_clone_tpu.api.client import MasterError, MasterSession


# ---------------------------------------------------------------------------
# session + auth store
# ---------------------------------------------------------------------------

def auth_store_path() -> str:
    return os.path.join(os.path.expanduser("~"), ".dct", "auth.json")


def load_auth_store() -> Dict[str, str]:
    try:
        with open(auth_store_path()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def save_auth_store(store: Dict[str, str]) -> None:
    path = auth_store_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(store, f)
    os.chmod(path, 0o600)


def make_session(args: argparse.Namespace) -> MasterSession:
    master = args.master or os.environ.get("DCT_MASTER", "127.0.0.1:8080")
    host, _, port = master.partition(":")
    session = MasterSession(host or "127.0.0.1", int(port or "8080"))
    token = load_auth_store().get(master)
    if token:
        session.token = token
    return session


def fetch_cluster_view(args: argparse.Namespace, path: str, *,
                       fold_fallback: bool = True):
    """Shared master-fetch plumbing for the observability subcommands
    (metrics, goodput, slo, query, alerts, top): ``GET path`` on the
    configured master. With ``fold_fallback`` a 404 — a master (e.g.
    the C++ one) that exposes ``/metrics`` but not this JSON route —
    fetches the exposition text instead and folds it through a fresh
    aggregator so the caller can re-derive its view. Returns
    ``(session, payload, agg)``; exactly one of payload/agg is
    non-None.
    """
    session = make_session(args)
    try:
        return session, session.get(path), None
    except MasterError as e:
        if e.status != 404 or not fold_fallback:
            raise
        from determined_clone_tpu.telemetry.aggregate import (
            ClusterMetricsAggregator,
        )
        import urllib.request

        url = f"http://{session.host}:{session.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            text = resp.read().decode("utf-8")
        agg = ClusterMetricsAggregator()
        agg.ingest_prometheus_text("master", text)
        return session, None, agg


# ---------------------------------------------------------------------------
# output helpers
# ---------------------------------------------------------------------------

def print_table(rows: List[Dict[str, Any]], columns: Sequence[str]) -> None:
    if not rows:
        print("(none)")
        return
    widths = {c: len(c) for c in columns}
    rendered = []
    for row in rows:
        r = {c: str(row.get(c, "")) for c in columns}
        for c in columns:
            widths[c] = max(widths[c], len(r[c]))
        rendered.append(r)
    header = " | ".join(c.ljust(widths[c]) for c in columns)
    print(header)
    print("-+-".join("-" * widths[c] for c in columns))
    for r in rendered:
        print(" | ".join(r[c].ljust(widths[c]) for c in columns))


def print_json(obj: Any) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True))


def load_config_file(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict):
        raise SystemExit(f"config {path} must be a YAML mapping")
    return cfg


# ---------------------------------------------------------------------------
# command implementations
# ---------------------------------------------------------------------------

def cmd_master_config(args) -> int:
    print_json(make_session(args).get("/api/v1/master/config"))
    return 0


def cmd_master_info(args) -> int:
    print_json(make_session(args).master_info())
    return 0


def cmd_experiment_create(args) -> int:
    session = make_session(args)
    config = load_config_file(args.config)
    if args.config_override:
        for override in args.config_override:
            key, _, value = override.partition("=")
            node = config
            parts = key.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            try:
                node[parts[-1]] = json.loads(value)
            except json.JSONDecodeError:
                node[parts[-1]] = value
    body: Dict[str, Any] = {"config": config}
    if args.model_dir:
        from determined_clone_tpu.sdk import read_context_dir

        body["context"] = read_context_dir(args.model_dir)
    exp = session.post("/api/v1/experiments", body)["experiment"]
    print(f"Created experiment {exp['id']}")
    if args.follow:
        from determined_clone_tpu.sdk import ExperimentRef

        state = ExperimentRef(session, exp["id"]).wait(timeout=args.timeout)
        print(f"Experiment {exp['id']} finished: {state}")
        return 0 if state == "COMPLETED" else 1
    return 0


def cmd_experiment_list(args) -> int:
    exps = make_session(args).list_experiments()
    if not args.show_archived:
        exps = [e for e in exps if not e.get("archived")]
    print_table(exps, ["id", "name", "state", "archived", "owner",
                       "workspace", "project"])
    return 0


def cmd_experiment_describe(args) -> int:
    print_json(make_session(args).get_experiment(args.experiment_id))
    return 0


def cmd_experiment_pause(args) -> int:
    exp = make_session(args).pause_experiment(args.experiment_id)
    print(f"Experiment {exp['id']} is {exp['state']}")
    return 0


def cmd_experiment_activate(args) -> int:
    exp = make_session(args).activate_experiment(args.experiment_id)
    print(f"Experiment {exp['id']} is {exp['state']}")
    return 0


def cmd_experiment_archive(args) -> int:
    exp = make_session(args).archive_experiment(
        args.experiment_id, archive=not args.unarchive)
    print(f"Experiment {exp['id']} archived={exp['archived']}")
    return 0


def cmd_experiment_delete(args) -> int:
    make_session(args).delete_experiment(args.experiment_id)
    print(f"Deleted experiment {args.experiment_id}")
    return 0


def cmd_experiment_kill(args) -> int:
    make_session(args).kill_experiment(args.experiment_id)
    print(f"Killed experiment {args.experiment_id}")
    return 0


def cmd_trial_kill(args) -> int:
    trial = make_session(args).kill_trial(args.trial_id)
    print(f"Trial {trial['id']} is {trial['state']}")
    return 0


def cmd_trial_describe(args) -> int:
    print_json(make_session(args).get_trial(args.trial_id))
    return 0


def cmd_trial_metrics(args) -> int:
    print_json(make_session(args).trial_metrics(args.trial_id, args.limit))
    return 0


def cmd_trial_logs(args) -> int:
    session = make_session(args)
    legs = session.trial_log_allocations(args.trial_id)
    if not getattr(args, "follow", False):
        for alloc_id in legs:
            for rec in session.task_logs(alloc_id):
                print(rec.get("log", ""))
        return 0
    # follow: drain earlier legs from their cursors, then live-tail the
    # newest; a restart creates a new leg, so on end-of-stream re-list and
    # keep going until the trial is terminal with no new leg. Per-leg
    # cursors stop a re-entered leg (e.g. followed live, then superseded
    # by a restart) from reprinting what was already shown.
    import time as _time

    cursors: Dict[str, int] = {}

    def emit(alloc_id: str, follow_seconds: int) -> None:
        n = cursors.get(alloc_id, 0)
        try:
            for rec in session.follow_task_logs(
                    alloc_id, offset=n, follow_seconds=follow_seconds):
                print(rec.get("log", ""), flush=True)
                n += 1
        except MasterError as err:
            # a QUEUED trial's leg (or a restart's fresh leg) may be
            # listed before its allocation registers: wait, don't crash
            if err.status != 404:
                raise
            _time.sleep(1.0)
        cursors[alloc_id] = n

    while True:
        for alloc_id in legs[:-1]:
            emit(alloc_id, 0)   # dead leg: just drain past the cursor
        if legs:
            emit(legs[-1], 30)  # live leg: block for new lines
        state = session.get_trial(args.trial_id).get("state", "")
        new_legs = session.trial_log_allocations(args.trial_id)
        if new_legs == legs and state in ("COMPLETED", "ERRORED",
                                          "CANCELED"):
            return 0
        if new_legs == legs:
            # e.g. PAUSED with a drained terminal leg: don't spin
            _time.sleep(1.0)
        legs = new_legs


def cmd_checkpoint_list(args) -> int:
    records = make_session(args).get(
        f"/api/v1/experiments/{args.experiment_id}/checkpoints")["checkpoints"]
    print_table(records, ["uuid", "trial_id", "reported_at"])
    return 0


def cmd_checkpoint_describe(args) -> int:
    print_json(make_session(args).get(f"/api/v1/checkpoints/{args.uuid}"))
    return 0


def cmd_checkpoint_download(args) -> int:
    from determined_clone_tpu.sdk import CheckpointRef

    session = make_session(args)
    path = CheckpointRef(session, args.uuid).download(args.output_dir)
    print(f"Downloaded checkpoint {args.uuid} to {path}")
    return 0


def cmd_checkpoint_stats(args) -> int:
    """Dedup ratio + chunk-cache hit rate of a content-addressed store.

    Reads the `checkpoint_storage:` block from an experiment config yaml
    (--config), or builds one from --host-path/--cache-path directly —
    this talks straight to storage, no master needed.
    """
    from determined_clone_tpu.config.experiment import (
        CheckpointStorageConfig,
    )
    from determined_clone_tpu.storage import CASStorageManager, build

    if args.config:
        import yaml

        with open(args.config) as f:
            doc = yaml.safe_load(f) or {}
        raw = doc.get("checkpoint_storage") or doc
    elif args.host_path:
        raw = {"type": "cas",
               "inner": {"type": "shared_fs", "host_path": args.host_path}}
        if args.cache_path:
            raw["cache_path"] = args.cache_path
    else:
        print("checkpoint stats needs --config or --host-path",
              file=sys.stderr)
        return 2
    manager = build(CheckpointStorageConfig.from_dict(raw))
    if not isinstance(manager, CASStorageManager):
        print(f"checkpoint_storage type {raw.get('type')!r} is not "
              "content-addressed; stats need `type: cas`", file=sys.stderr)
        return 2
    # storage_stats() includes the per-namespace split (checkpoint
    # chunks vs cached executables) under "namespaces"
    print_json(manager.storage_stats())
    return 0


def cmd_exec_cache_stats(args) -> int:
    """Persistent executable cache readout: entries, bytes, per-program
    breakdown, session hit rate (docs/checkpoint_storage.md, "Executable
    cache").

    Accepts the same storage addressing as `checkpoint stats` (--config /
    --host-path with a cas block) or --dir, a bare shared_fs root — the
    DCT_EXEC_CACHE_DIR convention the serving warm-start harness uses.
    """
    from determined_clone_tpu.config.experiment import (
        CheckpointStorageConfig,
    )
    from determined_clone_tpu.storage import (
        CASStorageManager,
        ExecutableCache,
        SharedFSStorageManager,
        build,
    )

    if args.dir:
        cache = ExecutableCache(SharedFSStorageManager(args.dir))
    else:
        if args.config:
            import yaml

            with open(args.config) as f:
                doc = yaml.safe_load(f) or {}
            raw = doc.get("checkpoint_storage") or doc
        elif args.host_path:
            raw = {"type": "cas", "inner": {
                "type": "shared_fs", "host_path": args.host_path}}
        else:
            print("exec-cache stats needs --config, --host-path or --dir",
                  file=sys.stderr)
            return 2
        manager = build(CheckpointStorageConfig.from_dict(raw))
        if not isinstance(manager, CASStorageManager):
            print(f"checkpoint_storage type {raw.get('type')!r} is not "
                  "content-addressed; the executable cache lives on "
                  "`type: cas`", file=sys.stderr)
            return 2
        cache = manager.exec_cache()
    print_json(cache.stats())
    return 0


def cmd_kv_stats(args) -> int:
    """KV memory-hierarchy readout (docs/serving.md, "KV memory
    hierarchy"): from a live fleet front door (--url → the ``kv_tier``
    block of GET /v1/fleet — host tier counters plus nested CAS stats)
    or straight off a CAS store's ``cas/kv/`` namespace (--config /
    --host-path, same addressing as `exec-cache stats`)."""
    if args.url:
        import urllib.request

        with urllib.request.urlopen(f"{args.url.rstrip('/')}/v1/fleet",
                                    timeout=10) as resp:
            view = json.loads(resp.read().decode("utf-8"))
        kv = view.get("kv_tier")
        if kv is None:
            print("fleet has no KV memory hierarchy (kv_store off)",
                  file=sys.stderr)
            return 2
        print_json(kv)
        return 0
    from determined_clone_tpu.config.experiment import (
        CheckpointStorageConfig,
    )
    from determined_clone_tpu.storage import CASStorageManager, build

    if args.config:
        import yaml

        with open(args.config) as f:
            doc = yaml.safe_load(f) or {}
        raw = doc.get("checkpoint_storage") or doc
    elif args.host_path:
        raw = {"type": "cas", "inner": {
            "type": "shared_fs", "host_path": args.host_path}}
    else:
        print("kv stats needs --url, --config or --host-path",
              file=sys.stderr)
        return 2
    manager = build(CheckpointStorageConfig.from_dict(raw))
    if not isinstance(manager, CASStorageManager):
        print(f"checkpoint_storage type {raw.get('type')!r} is not "
              "content-addressed; spilled KV blocks live on `type: cas`",
              file=sys.stderr)
        return 2
    print_json(manager.kv_store().stats())
    return 0


def cmd_task_list(args) -> int:
    tasks = make_session(args).list_tasks(args.type)
    print_table(tasks, ["id", "task_type", "name", "state", "proxy_address"])
    return 0


def cmd_task_kill(args) -> int:
    make_session(args).kill_task(args.task_id)
    print(f"Killed task {args.task_id}")
    return 0


def cmd_task_logs(args) -> int:
    session = make_session(args)
    if getattr(args, "follow", False):
        for rec in session.follow_task_logs(args.task_id):
            print(rec.get("log", ""), flush=True)
        return 0
    for rec in session.task_logs(args.task_id):
        print(rec.get("log", ""))
    return 0


def _start_ntsc(args, task_type: str, **extra: Any) -> int:
    # typed roots (LaunchNotebook/LaunchShell/... RPCs) rather than the
    # generic CreateTask — the type is pinned server-side
    session = make_session(args)
    kwargs: Dict[str, Any] = dict(extra)
    if getattr(args, "name", None):
        kwargs["name"] = args.name
    if getattr(args, "idle_timeout", None):
        kwargs["idle_timeout"] = args.idle_timeout
    task = session.post(f"/api/v1/{task_type}s", kwargs)[task_type]
    print(f"Started {task_type} {task['id']}")
    return 0


def _list_ntsc(args, task_type: str) -> int:
    tasks = make_session(args).get(f"/api/v1/{task_type}s")[task_type + "s"]
    print_table(tasks, ["id", "name", "state", "owner", "proxy_address"])
    return 0


def cmd_notebook_start(args) -> int:
    return _start_ntsc(args, "notebook")


def cmd_shell_start(args) -> int:
    return _start_ntsc(args, "shell")


def cmd_shell_exec(args) -> int:
    session = make_session(args)
    out = session.proxy(args.task_id, "/exec", "POST", {"cmd": args.cmd})
    if out.get("stdout"):
        sys.stdout.write(out["stdout"])
    if out.get("stderr"):
        sys.stderr.write(out["stderr"])
    return int(out.get("code", 1))


def cmd_command_run(args) -> int:
    return _start_ntsc(args, "command", cmd=args.cmd)


def cmd_tensorboard_start(args) -> int:
    ids = [int(x) for x in args.experiment_ids.split(",") if x]
    return _start_ntsc(args, "tensorboard", experiment_ids=ids)


def cmd_master_logs(args) -> int:
    out = make_session(args).get(
        f"/api/v1/master/logs?limit={args.limit}&offset={args.offset}")
    for rec in out["logs"]:
        print(f"[{rec['level']}] {rec['log']}")
    return 0


def cmd_trial_summary(args) -> int:
    rows = make_session(args).trial_metric_summary(args.trial_id)
    print_table(rows, ["group", "name", "count", "min", "max", "mean",
                       "last", "last_step"])
    return 0


def cmd_experiment_move(args) -> int:
    out = make_session(args).post(
        f"/api/v1/experiments/{args.experiment_id}/move",
        {"project_id": args.project_id})
    e = out["experiment"]
    print(f"Moved experiment {e['id']} to {e['workspace']}/{e['project']}")
    return 0


def cmd_experiment_label(args) -> int:
    labels = [x for x in args.labels.split(",") if x]
    out = make_session(args).request(
        "PATCH", f"/api/v1/experiments/{args.experiment_id}",
        {"labels": labels})
    print(f"Labels: {out['experiment']['labels']}")
    return 0


def cmd_experiment_progress(args) -> int:
    out = make_session(args).get(
        f"/api/v1/experiments/{args.experiment_id}/progress")
    print(f"{out['progress'] * 100:.1f}% "
          f"({out['units_done']:.0f}/{out['units_target']:.0f} units, "
          f"{out['state']})")
    return 0


def cmd_project_move(args) -> int:
    out = make_session(args).post(
        f"/api/v1/projects/{args.project_id}/move",
        {"workspace_id": args.workspace_id})
    print(f"Moved project {out['project']['id']} to workspace "
          f"{out['project']['workspace_id']}")
    return 0


def cmd_user_settings(args) -> int:
    session = make_session(args)
    if args.key is not None and args.value is not None:
        try:
            value = json.loads(args.value)
        except json.JSONDecodeError:
            value = args.value
        out = session.post("/api/v1/users/settings",
                           {"key": args.key, "value": value})
        print_json(out["settings"])
        return 0
    settings = session.get("/api/v1/users/settings")["settings"]
    if args.key is not None:
        # read one key; missing is a visible error, not a silent full dump
        if args.key not in settings:
            print(f"no setting {args.key!r}", file=sys.stderr)
            return 1
        print_json(settings[args.key])
        return 0
    print_json(settings)
    return 0


def cmd_agent_list(args) -> int:
    agents = make_session(args).list_agents()
    print_table(agents, ["id", "resource_pool", "slots", "topology",
                         "enabled", "address"])
    return 0


def cmd_job_list(args) -> int:
    queue = make_session(args).job_queue()
    print_table(queue, ["id", "task_type", "state", "slots", "priority",
                        "resource_pool"])
    return 0


def cmd_job_move(args) -> int:
    job = make_session(args).move_job(
        args.allocation_id, ahead_of=args.ahead_of, behind=args.behind)
    print(f"Moved {job['id']} (queued_at {job['queued_at']})")
    return 0


def cmd_job_set_priority(args) -> int:
    job = make_session(args).set_job_priority(args.allocation_id,
                                              args.priority)
    print(f"Set {job['id']} priority to {job['priority']}")
    return 0


def cmd_trace_export(args) -> int:
    """Convert shipped telemetry spans (a trial's, a whole experiment's,
    or a local span-record JSONL) into a Perfetto-loadable Chrome
    trace-event JSON file. ``--experiment`` stitches every component lane
    (runner + trials) sharing the experiment's trace_id into one file."""
    from determined_clone_tpu.telemetry.chrome_trace import (
        spans_from_profiler_samples,
        stitch_chrome_trace,
        to_chrome_trace,
        validate_chrome_trace,
    )

    stitched = args.experiment is not None
    if args.from_file:
        with open(args.from_file) as f:
            samples = [json.loads(line) for line in f if line.strip()]
    elif stitched:
        samples = make_session(args).get(
            f"/api/v1/experiments/{args.experiment}/trace")["samples"]
    else:
        if args.trial_id is None:
            print("error: give a trial id, --experiment, or --from-file",
                  file=sys.stderr)
            return 2
        samples = make_session(args).trial_profiler_samples(
            args.trial_id, limit=args.limit)
    spans = spans_from_profiler_samples(samples)
    if not spans:
        print("no span samples found — the trial must run with "
              "observability: {enabled: true, ship_spans: true}",
              file=sys.stderr)
        return 1
    if stitched or any(s.get("process") for s in spans):
        trace = stitch_chrome_trace(spans)
    else:
        trace = to_chrome_trace(spans)
    problems = validate_chrome_trace(trace)
    if problems:  # can only come from malformed shipped records
        print("warning: trace has structural problems:\n  " +
              "\n  ".join(problems), file=sys.stderr)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    lanes = trace.get("otherData", {}).get("processes")
    lane_note = f" across lanes {lanes}" if lanes else ""
    print(f"wrote {len(spans)} spans to {args.output}{lane_note} "
          f"(load at ui.perfetto.dev or chrome://tracing)")
    return 0


def cmd_trace_request(args) -> int:
    """Pull one request's stitched multi-process trace (front door →
    router → replica legs) out of a fleet's request archive
    (docs/observability.md "Request tracing & SLOs"). The archive's live
    ring survives kill -9, so partial legs of a request a replica died
    on are still retrievable."""
    from determined_clone_tpu.telemetry.chrome_trace import (
        validate_chrome_trace,
    )
    from determined_clone_tpu.telemetry.flight import (
        request_archive_summary,
        request_chrome_trace,
    )

    directory = args.archive_dir or os.environ.get(
        "DCT_REQUEST_ARCHIVE_DIR")
    if not directory:
        print("error: give --archive-dir (or set DCT_REQUEST_ARCHIVE_DIR)",
              file=sys.stderr)
        return 2
    try:
        trace = request_chrome_trace(directory, args.request_id)
    except KeyError:
        print(f"no spans for request {args.request_id!r} under "
              f"{directory}", file=sys.stderr)
        summary = request_archive_summary(directory)
        known = sorted(summary.get("live_request_ids") or [])
        if known:
            preview = ", ".join(known[:10])
            more = f" (+{len(known) - 10} more)" if len(known) > 10 else ""
            print(f"archived requests: {preview}{more}", file=sys.stderr)
        return 1
    problems = validate_chrome_trace(trace)
    if problems:  # only malformed records on disk can cause this
        print("warning: trace has structural problems:\n  " +
              "\n  ".join(problems), file=sys.stderr)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    other = trace.get("otherData", {})
    trace_ids = other.get("trace_ids") or []
    tid_note = f" trace_id {trace_ids[0]}" if trace_ids else ""
    print(f"wrote {len(trace.get('traceEvents', []))} trace events for "
          f"request {args.request_id}{tid_note} to {args.output} "
          f"(load at ui.perfetto.dev or chrome://tracing)")
    return 0


def cmd_slo(args) -> int:
    """Multi-window burn-rate SLO readout (docs/observability.md
    "Request tracing & SLOs"): availability and latency objectives over
    the serving fleet, fast (5m/1h) and slow (6h/3d) windows. Reads the
    master's ``GET /api/v1/cluster/slo`` or, with ``--url``, a fleet
    front door's ``GET /v1/slo``."""
    from determined_clone_tpu.telemetry.slo import format_slo

    if args.url:
        import urllib.request

        with urllib.request.urlopen(f"{args.url.rstrip('/')}/v1/slo",
                                    timeout=10) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
    else:
        _, payload, _ = fetch_cluster_view(args, "/api/v1/cluster/slo",
                                           fold_fallback=False)
    evaluation = payload.get("slo")
    if evaluation is None:
        print("no SLO engine attached (serving fleets attach one when "
              "tracing is enabled)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(evaluation, indent=2, default=str))
    else:
        print(format_slo(evaluation))
    return 0


def _timeseries_path(name: Optional[str], *, labels: Optional[str] = None,
                     window: float = 300.0, reduce: str = "raw",
                     q: float = 0.95) -> str:
    """Build the ``/api/v1/timeseries`` request path for one query."""
    from urllib.parse import urlencode

    if not name:
        return "/api/v1/timeseries"
    params = {"name": name, "window": f"{window:g}", "reduce": reduce,
              "q": f"{q:g}"}
    if labels:
        params["labels"] = labels
    return "/api/v1/timeseries?" + urlencode(params)


def _format_series_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def cmd_query(args) -> int:
    """Windowed reductions over the master's embedded TSDB
    (docs/observability.md "Time series, queries & alert rules").
    Without a series name, lists what the TSDB holds; with one, runs
    ``GET /api/v1/timeseries`` and prints per-series reductions
    (``--reduce rate`` over a counter gives per-second throughput the
    aggregator's latest-wins gauges cannot)."""
    path = _timeseries_path(args.name, labels=args.labels,
                            window=args.window, reduce=args.reduce,
                            q=args.q)
    _, payload, _ = fetch_cluster_view(args, path, fold_fallback=False)
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0
    if not args.name:
        stats = payload.get("stats") or {}
        budget = stats.get("memory_budget_bytes") or 0
        print(f"{stats.get('series', 0)} series, "
              f"{stats.get('samples', 0)} samples, "
              f"{stats.get('bytes_estimate', 0) / 1024.0:.0f} KiB of "
              f"{budget / 1024.0:.0f} KiB budget "
              f"({stats.get('scrapes_total', 0)} scrapes)")
        for name in payload.get("series") or []:
            print(f"  {name}")
        return 0
    series = payload.get("series") or []
    if not series:
        print(f"no series named {args.name!r} in the window",
              file=sys.stderr)
        return 1
    for s in series:
        label_s = _format_series_labels(s.get("labels") or {})
        head = (f"{args.name}{label_s} [{s.get('kind', 'gauge')}] "
                f"{args.reduce} over {args.window:g}s")
        if args.reduce == "raw":
            print(f"{head}: {s.get('n', 0)} samples")
            for t, v in s.get("samples") or []:
                print(f"  {t:.3f} {v:g}")
        else:
            v = s.get("value")
            v_s = f"{v:g}" if v is not None else "n/a (need ≥2 samples)"
            print(f"{head}: {v_s}")
    return 0


def cmd_alerts(args) -> int:
    """Alert-rule readout (docs/observability.md "Time series, queries
    & alert rules"): every configured rule with its state machine
    position (inactive/pending/firing/resolved), measured value, and
    hold-down. Reads the master's ``GET /api/v1/alerts``."""
    from determined_clone_tpu.telemetry.rules import format_alerts

    _, payload, _ = fetch_cluster_view(args, "/api/v1/alerts",
                                       fold_fallback=False)
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        print(format_alerts(payload))
    return 0


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[float], width: int = 32) -> str:
    vals = [v for v in values if v == v][-width:]
    if not vals:
        return "(no data)"
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_BLOCKS[0] * len(vals)
    top = len(_SPARK_BLOCKS) - 1
    return "".join(_SPARK_BLOCKS[int((v - lo) / span * top)]
                   for v in vals)


def _top_frame(args, session) -> str:
    """One rendering of the ``dct top`` dashboard, built entirely from
    the master's query API so it shows exactly what the TSDB stored."""
    def query(name: str, reduce: str = "last",
              labels: Optional[str] = None) -> List[Dict[str, Any]]:
        path = _timeseries_path(name, labels=labels, window=args.window,
                                reduce=reduce)
        try:
            return session.get(path).get("series") or []
        except MasterError:
            return []

    def one(name: str, reduce: str = "last",
            labels: Optional[str] = None) -> Optional[float]:
        for s in query(name, reduce, labels):
            if s.get("value") is not None:
                return float(s["value"])
        return None

    def fmt(v: Optional[float], spec: str = "g") -> str:
        return format(v, spec) if v is not None else "n/a"

    def fmt_s(v: Optional[float]) -> str:
        return f"{v:.3f}s" if v is not None else "n/a"

    lines = [f"dct top — window {args.window:g}s"]
    replicas = one("dct_fleet_replicas")
    tps_now = one("dct_fleet_tokens_per_sec")
    lines.append(f"fleet: {fmt(replicas, '.0f')} replicas, "
                 f"{fmt(tps_now, '.1f')} tokens/s, "
                 f"queue {fmt(one('dct_fleet_queue_depth'), '.0f')}, "
                 f"p99 {fmt_s(one('dct_fleet_max_replica_p99_seconds'))}")
    tps_series = query("dct_fleet_tokens_per_sec", reduce="raw")
    tps_points = [v for s in tps_series
                  for _, v in s.get("samples") or []]
    lines.append(f"tokens/s  {_sparkline(tps_points)}")
    goodput = one("dct_goodput_cluster_fraction")
    hit = one("dct_exec_cache_hit_rate")
    lines.append(f"goodput {fmt(goodput, '.1%')}   "
                 f"exec-cache hit {fmt(hit, '.1%')}")
    queues = {(s.get("labels") or {}).get("component"): s.get("value")
              for s in query("serving_queue_depth")
              if (s.get("labels") or {}).get("component")}
    p99s = {(s.get("labels") or {}).get("component"): s.get("value")
            for s in query("serving_request_total_seconds",
                           labels="quantile=0.99")
            if (s.get("labels") or {}).get("component")}
    if queues or p99s:
        lines.append("replicas:")
        for comp in sorted(set(queues) | set(p99s)):
            lines.append(f"  {comp:<24} queue {fmt(queues.get(comp), '.0f'):>5}"
                         f"   p99 {fmt_s(p99s.get(comp))}")
    try:
        alerts = session.get("/api/v1/alerts")
    except MasterError:
        alerts = None
    if alerts is not None:
        firing = alerts.get("firing") or []
        if firing:
            lines.append(f"ALERTS FIRING: {', '.join(firing)}")
        else:
            lines.append(f"alerts: {len(alerts.get('rules') or [])} rules, "
                         "none firing")
    return "\n".join(lines) + "\n"


def cmd_top(args) -> int:
    """Live terminal dashboard over the master's time-series query API
    (docs/observability.md "Time series, queries & alert rules"):
    fleet throughput sparkline, per-replica queue/p99, goodput, exec
    cache hit rate, firing alerts. ``--once`` prints a single frame
    (tests and scripts); otherwise redraws every ``--interval``
    seconds until interrupted."""
    import time as _time

    session, _, _ = fetch_cluster_view(args, "/api/v1/timeseries",
                                       fold_fallback=False)
    if args.once:
        sys.stdout.write(_top_frame(args, session))
        return 0
    try:
        while True:
            frame = _top_frame(args, session)
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            _time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        print()
        return 0


def cmd_debug_flight(args) -> int:
    """Post-mortem dump of a flight-recorder ring (docs/observability.md):
    merge the surviving segments — including the ones a kill -9 left
    behind — into a validated Chrome trace plus a one-screen summary of
    what the process was doing when it died."""
    from determined_clone_tpu.telemetry.chrome_trace import (
        validate_chrome_trace,
    )
    from determined_clone_tpu.telemetry.flight import (
        flight_summary,
        flight_to_chrome_trace,
    )

    summary = flight_summary(args.directory)
    if not summary["segments"]:
        print(f"no flight segments found under {args.directory}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(f"flight ring: {summary['segments']} segments, "
              f"{summary['spans']} spans, "
              f"{summary['metric_snapshots']} metric snapshots")
        if summary["processes"]:
            print(f"processes: {', '.join(summary['processes'])}")
        if summary["last_batches_trained"] is not None:
            print(f"last recorded batches_trained: "
                  f"{summary['last_batches_trained']}")
        for name, n in sorted(summary["span_names"].items(),
                              key=lambda kv: -kv[1]):
            print(f"  {name}: {n}")
    trace = flight_to_chrome_trace(args.directory)
    problems = validate_chrome_trace(trace)
    if problems:  # only malformed records on disk can cause this
        print("warning: trace has structural problems:\n  " +
              "\n  ".join(problems), file=sys.stderr)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace.get('traceEvents', []))} trace events to "
          f"{args.output} (load at ui.perfetto.dev or chrome://tracing)")
    return 0


def cmd_metrics(args) -> int:
    """Cluster-wide metrics view (`GET /metrics` + the master's summary
    endpoint): top trials by throughput, cluster quantiles, restart/
    fallback/retry counters — docs/observability.md."""
    from determined_clone_tpu.telemetry.aggregate import format_summary

    if args.raw:
        master = args.master or os.environ.get("DCT_MASTER",
                                               "127.0.0.1:8080")
        url = f"http://{master}/metrics"
        import urllib.request

        with urllib.request.urlopen(url, timeout=10) as resp:
            sys.stdout.write(resp.read().decode("utf-8"))
        return 0
    session, summary, agg = fetch_cluster_view(args,
                                               "/api/v1/cluster/metrics")
    if agg is not None:
        # C++ masters have /metrics but no JSON summary route: the
        # folded exposition puts the scheduler's dct_master_sched_*
        # families in the same summary view
        print(format_summary(agg.summary()))
        try:
            sched = session.get("/api/v1/cluster/scheduler")
        except MasterError:
            return 0
        c = sched.get("counters") or {}
        print(f"scheduler: {int(c.get('submitted', 0))} submitted / "
              f"{int(c.get('scheduled', 0))} scheduled / "
              f"{int(c.get('running', 0))} running / "
              f"{int(c.get('completed', 0))} completed; "
              f"queue depth {int((sched.get('gauges') or {}).get('queue_depth', 0))}")
        return 0
    print(format_summary(summary))
    return 0


def cmd_goodput(args) -> int:
    """Goodput readout (docs/observability.md): what fraction of each
    trial's wall-clock trained the model, and where the badput went.
    Reads the master's rollup (``GET /api/v1/cluster/goodput``), falling
    back to the exposition text for masters without the JSON route; or
    merges an on-disk journal directory offline (``--dir``), restart legs
    folded into trial-lifetime accounts."""
    from determined_clone_tpu.telemetry.goodput import (
        format_goodput,
        merge_goodput,
    )

    if args.dir:
        accounts = merge_goodput(args.dir)
        if args.experiment is not None:
            print("note: --experiment is ignored with --dir (journals are "
                  "keyed by trial id only)", file=sys.stderr)
        if not accounts:
            print(f"no goodput journals found under {args.dir}",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(accounts, indent=2, default=str))
        else:
            print(format_goodput(accounts))
        return 0

    # masters without the JSON route still expose the goodput_* gauge
    # families in /metrics: the folded text re-derives the rollup
    _, roll, agg = fetch_cluster_view(args, "/api/v1/cluster/goodput")
    if agg is not None:
        roll = agg.goodput_rollup()
    by_trial = roll.get("by_trial") or {}
    if args.experiment is not None:
        by_trial = {tid: acct for tid, acct in by_trial.items()
                    if acct.get("experiment_id") == args.experiment}
        roll = dict(roll, by_trial=by_trial)
    if args.json:
        print(json.dumps(roll, indent=2, default=str))
        return 0
    if not by_trial:
        print("no trials reporting goodput", file=sys.stderr)
        return 1
    cf = roll.get("cluster_fraction")
    cf_s = f"{cf:.1%}" if cf is not None else "n/a"
    print(f"cluster goodput (time-weighted): {cf_s} over "
          f"{roll.get('wall_total_s', 0.0):.1f}s wall")
    for tid in sorted(by_trial, key=lambda t: int(t) if str(t).isdigit()
                      else 0):
        acct = by_trial[tid]
        frac = acct.get("goodput_fraction")
        frac_s = f"{frac:.1%}" if frac is not None else "n/a"
        print(f"trial {tid}: goodput {frac_s} over "
              f"{acct.get('wall_s', 0.0):.2f}s wall")
        cats = acct.get("categories") or {}
        wall = max(float(acct.get("wall_s") or 0.0), 1e-9)
        for cat, secs in sorted(cats.items(), key=lambda kv: -kv[1]):
            if secs > 0:
                print(f"  {cat:<18} {secs:>9.3f}s  {secs / wall:6.1%}")
    return 0


def cmd_mesh(args) -> int:
    """Mesh observability readout (docs/parallelism.md): collective
    op/byte counts per (kind, axis), straggler events, and the worst
    comm-vs-compute fraction from the cluster metrics plane; or a
    MULTICHIP scaling artifact rendered from a file (``--file``) or
    measured fresh on a simulated mesh (``--run N``)."""
    from determined_clone_tpu.telemetry.mesh import (
        format_multichip,
        validate_multichip,
    )

    if args.run is not None:
        # device count is fixed at backend init — measure in a subprocess
        # that steers itself to a forced-device-count CPU mesh
        import subprocess
        proc = subprocess.run(
            [sys.executable, "-m",
             "determined_clone_tpu.parallel.scaling_bench",
             "--devices", str(args.run), "--json"],
            capture_output=True, text=True, timeout=600)
        artifact = None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    artifact = json.loads(line)
                except ValueError:
                    continue
        if proc.returncode != 0 or not isinstance(artifact, dict):
            print(f"scaling bench failed (rc={proc.returncode}): "
                  f"{proc.stderr.strip()[-400:]}", file=sys.stderr)
            return 1
    elif args.file:
        with open(args.file) as f:
            obj = json.load(f)
        artifact = obj
        if isinstance(obj, dict) and "tail" in obj and "meshes" not in obj:
            # driver MULTICHIP_rN.json wrapper: the artifact is the last
            # JSON line of the round's stdout tail
            artifact = None
            for line in str(obj["tail"]).splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        artifact = json.loads(line)
                    except ValueError:
                        continue
            if artifact is None:
                print(f"{args.file}: no artifact line in wrapper tail",
                      file=sys.stderr)
                return 1
    else:
        # cluster plane: fold the master's /metrics exposition through the
        # aggregator and print the mesh rollup
        from determined_clone_tpu.telemetry.aggregate import (
            ClusterMetricsAggregator,
        )
        import urllib.request

        session = make_session(args)
        url = f"http://{session.host}:{session.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            text = resp.read().decode("utf-8")
        agg = ClusterMetricsAggregator()
        agg.ingest_prometheus_text("master", text)
        roll = agg.mesh_rollup()
        if roll is None:
            print("no mesh metrics reported (no sharded program has "
                  "exported collective accounting yet)", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(roll, indent=2, default=str))
            return 0
        for kind, axes in sorted((roll.get("collective_ops") or {}).items()):
            for ax, cnt in sorted(axes.items()):
                b = (roll.get("collective_bytes") or {}).get(
                    kind, {}).get(ax)
                b_s = f", {b:.0f} B/exec" if isinstance(b, (int, float)) \
                    else ""
                print(f"collective {kind}[{ax}]: {cnt:.0f} ops{b_s}")
        for dev, cnt in sorted((roll.get("straggler_events") or {}).items()):
            print(f"straggler events {dev}: {cnt:.0f}")
        worst = roll.get("worst_comm_fraction")
        if isinstance(worst, dict):
            print(f"worst comm/compute fraction: "
                  f"{worst.get('fraction'):.1%} ({worst.get('program')})")
        return 0

    problems = validate_multichip(artifact)
    if args.json:
        print(json.dumps(artifact, indent=2, default=str))
    else:
        print(format_multichip(artifact))
    if problems:
        print("schema problems: " + "; ".join(problems[:5]),
              file=sys.stderr)
        return 1
    return 0


def cmd_serve(args) -> int:
    """Serve a GPT checkpoint over HTTP with continuous batching over a
    paged KV cache (docs/serving.md). `--selftest` binds an ephemeral
    port, drives a few generations through the HTTP surface, prints the
    engine stats as JSON, and exits — the smoke path CI runs."""
    import dataclasses
    import time

    from determined_clone_tpu.config.experiment import ServingConfig
    from determined_clone_tpu.models import gpt as gpt_model
    from determined_clone_tpu.serving import InferenceEngine
    from determined_clone_tpu.serving.http import (
        ServingHTTPServer,
        generate_over_http,
    )

    scfg = ServingConfig()
    if args.config:
        raw = load_config_file(args.config)
        if raw.get("serving"):
            scfg = ServingConfig.from_dict(raw["serving"])
    if args.port is not None:
        scfg = dataclasses.replace(scfg, port=args.port)
    if args.host is not None:
        scfg = dataclasses.replace(scfg, host=args.host)

    if args.model != "tiny":
        print(f"error: unknown model preset {args.model!r} (have: tiny)",
              file=sys.stderr)
        return 2
    model_cfg = gpt_model.GPTConfig.tiny()
    import jax

    params = gpt_model.init(jax.random.PRNGKey(args.seed), model_cfg)
    if args.checkpoint:
        from determined_clone_tpu.core._serialization import load_pytree

        params = load_pytree(args.checkpoint, like=params)
    with InferenceEngine.from_serving_config(params, model_cfg,
                                             scfg) as engine:
        # precompile the full bucket ladder before taking traffic: the
        # first request to hit a cold bucket would otherwise stall the
        # scheduler (and everyone behind it) on an XLA compile
        t0 = time.monotonic()
        n_programs = engine.warmup()
        print(f"warmup: {n_programs} programs compiled "
              f"in {time.monotonic() - t0:.1f}s", file=sys.stderr)
        port = 0 if args.selftest else scfg.port
        with ServingHTTPServer(engine, host=scfg.host, port=port) as server:
            if args.selftest:
                for prompt in ([1, 2, 3], [5, 6, 7, 8, 9], [11]):
                    out = generate_over_http(server.url, prompt,
                                             max_new_tokens=4)
                    if len(out["tokens"]) != 4:
                        print(f"error: selftest got {out}", file=sys.stderr)
                        return 1
                print(json.dumps(
                    {"selftest": "ok", "url": server.url,
                     "stats": dataclasses.asdict(engine.stats())}))
                return 0
            print(f"serving {args.model} on {server.url} "
                  f"(buckets: batch {engine.buckets.batch_buckets}, "
                  f"prefill {engine.buckets.prefill_len_buckets}; "
                  f"{engine.cache.num_blocks}x{engine.cache.block_size} "
                  f"KV blocks)")
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                return 0


def cmd_fleet_up(args) -> int:
    """Run a serving fleet: N engine replicas behind the least-loaded
    router with an HTTP front door (docs/serving.md). With --with-master
    the replicas are gang allocations of the master's `serving` type
    (they occupy scheduler slots and show up in dct_master_sched_*);
    standalone otherwise. `--selftest` drives traffic through the HTTP
    surface, prints fleet stats as JSON, and exits."""
    import dataclasses as _dc
    import time

    import jax

    from determined_clone_tpu.models import gpt as gpt_model
    from determined_clone_tpu.serving import MasterLink, ServingFleet
    from determined_clone_tpu.serving.http import (
        FleetHTTPServer,
        generate_over_http,
    )

    if args.model != "tiny":
        print(f"error: unknown model preset {args.model!r} (have: tiny)",
              file=sys.stderr)
        return 2
    model_cfg = gpt_model.GPTConfig.tiny()
    params = gpt_model.init(jax.random.PRNGKey(args.seed), model_cfg)
    if args.checkpoint:
        from determined_clone_tpu.core._serialization import load_pytree

        params = load_pytree(args.checkpoint, like=params)
    fleet = ServingFleet(params, model_cfg, name=args.name,
                         iteration_floor_s=args.iteration_floor)
    link = None
    try:
        if args.with_master:
            session = make_session(args)
            if session.host not in ("127.0.0.1", "localhost"):
                print("error: --with-master needs a local master "
                      "(the fleet link speaks the loopback agent "
                      "protocol)", file=sys.stderr)
                return 2
            link = MasterLink(fleet, session.port, replicas=args.replicas)
            link.wait_replicas(args.replicas, timeout=120)
        else:
            fleet.scale_up(args.replicas)
        port = 0 if args.selftest else (args.port or 8085)
        with FleetHTTPServer(fleet, host=args.host or "127.0.0.1",
                             port=port) as server:
            if args.selftest:
                outs = [generate_over_http(server.url, [1, 2, 3],
                                           max_new_tokens=4)
                        for _ in range(2 * args.replicas)]
                if any(len(o["tokens"]) != 4 for o in outs):
                    print(f"error: selftest got {outs}", file=sys.stderr)
                    return 1
                print(json.dumps({
                    "selftest": "ok", "url": server.url,
                    "replicas": fleet.replica_ids(),
                    "with_master": bool(link),
                    "stats": _dc.asdict(fleet.stats())}))
                return 0
            print(f"fleet {fleet.name!r}: {args.replicas} replicas on "
                  f"{server.url}"
                  + (" (master-managed)" if link else " (standalone)"))
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                return 0
    finally:
        if link is not None:
            link.close(kill_fleet=True)
        fleet.close()


def cmd_fleet_status(args) -> int:
    """Fleet health: from a fleet front door (--url → GET /v1/fleet) or
    from the master's serving-fleet records (GET /api/v1/serving/fleets)."""
    import urllib.request

    if args.url:
        with urllib.request.urlopen(f"{args.url.rstrip('/')}/v1/fleet",
                                    timeout=10) as resp:
            view = json.loads(resp.read().decode("utf-8"))
        if args.json:
            print(json.dumps(view, indent=2))
            return 0
        st = view["stats"]
        print(f"fleet {view['name']!r}: {st['healthy']}/{st['replicas']} "
              f"healthy, queue depth {st['queue_depth']}, "
              f"{st['free_blocks']} free KV blocks, "
              f"{st['completed']} completed, "
              f"{st['tokens_generated']} tokens")
        health = view.get("health") or {}
        by_id = {r["id"]: r for r in health.get("replicas", [])}
        for rep in view["replicas"]:
            mark = (" [excluded]" if rep["id"] in view.get("excluded", [])
                    else "")
            line = f"  {rep['id']}: {rep['state']}{mark}"
            h = by_id.get(rep["id"])
            if h:
                line += (f" (breaker {h['breaker']}, "
                         f"beat {h['beat_age_s']:.1f}s ago")
                if h.get("fatal"):
                    line += f", FATAL: {h['fatal']}"
                line += ")"
            print(line)
        if health.get("quarantined_requests"):
            print(f"  {health['quarantined_requests']} request(s) "
                  f"quarantined as poison pills")
        last = health.get("last_incident")
        if last:
            repl = ", ".join(last.get("replacement") or []) or "none"
            print(f"  last incident: replica {last.get('replica')} "
                  f"{last.get('reason')} — {last.get('failed_requests')} "
                  f"request(s) failed over, "
                  f"{last.get('leaked_blocks')} block(s) leaked, "
                  f"recovered in {last.get('recovery_s', 0):.2f}s "
                  f"(replacement: {repl})")
        return 0
    session = make_session(args)
    fleets = session.get("/api/v1/serving/fleets").get("fleets", [])
    if args.json:
        print(json.dumps(fleets, indent=2))
        return 0
    if not fleets:
        print("no serving fleets")
        return 0
    for f in fleets:
        print(f"fleet {f['name']!r}: {f['running']} running / "
              f"{f['queued']} queued / {f['desired']} desired "
              f"(pool {f['resource_pool']}, "
              f"{f['slots_per_replica']} slots/replica)")
        for rep in f.get("replicas", []):
            print(f"  {rep['id']}: {rep['state']}")
    return 0


def cmd_fleet_rollout(args) -> int:
    """Blue-green checkpoint rollout through a fleet front door: the new
    version is proven on a drained canary before the rest of the fleet
    swaps, and no in-flight request ever spans a parameter change."""
    import urllib.request

    body = json.dumps({"checkpoint": args.checkpoint}).encode("utf-8")
    req = urllib.request.Request(
        f"{args.url.rstrip('/')}/v1/rollout", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=args.timeout) as resp:
        report = json.loads(resp.read().decode("utf-8"))
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    order = report.get("order", [])
    print(f"rollout complete in {report.get('duration_s', 0.0):.2f}s: "
          f"canary {order[0] if order else '?'}, "
          f"{len(order)} replicas swapped")
    for rid in order:
        print(f"  {rid}: drained in {report['drain_s'].get(rid, 0.0):.3f}s")
    return 0


def cmd_fleet_scale(args) -> int:
    """Resize a fleet: through the front door (--url, in-process drain)
    or through the master (drain-protected kill commands)."""
    import urllib.request

    if args.url:
        body = json.dumps({"replicas": args.replicas}).encode("utf-8")
        req = urllib.request.Request(
            f"{args.url.rstrip('/')}/v1/scale", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            view = json.loads(resp.read().decode("utf-8"))
        print(f"fleet now has {len(view['replicas'])} replicas: "
              f"{view['replicas']}")
        return 0
    session = make_session(args)
    session.post(f"/api/v1/serving/fleets/{args.name}/scale",
                 {"replicas": args.replicas})
    print(f"fleet {args.name!r} scaling to {args.replicas} replicas "
          f"(drain-protected)")
    return 0


def cmd_lint(args) -> int:
    """Run the dctlint static-analysis suite (docs/static_analysis.md).
    The linter lives in the repo's tools/ package (it is developer
    tooling, not shipped library code), so resolve it relative to the
    source checkout when it isn't already importable."""
    try:
        from tools.dctlint.__main__ import main as dctlint_main
    except ImportError:
        import determined_clone_tpu

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(determined_clone_tpu.__file__)))
        if not os.path.isdir(os.path.join(repo_root, "tools", "dctlint")):
            print("error: tools/dctlint not found — `dct lint` runs from "
                  "a source checkout", file=sys.stderr)
            return 2
        sys.path.insert(0, repo_root)
        from tools.dctlint.__main__ import main as dctlint_main

    argv: List[str] = list(args.paths)
    if args.select:
        argv += ["--select", args.select]
    if args.no_baseline:
        argv.append("--no-baseline")
    if args.write_baseline:
        argv.append("--write-baseline")
    if args.list_checkers:
        argv.append("--list-checkers")
    if args.json:
        argv += ["--format", "json"]
    return dctlint_main(argv)


def _deploy_runner(args):
    from determined_clone_tpu.deploy import DryRunRunner, SubprocessRunner

    return SubprocessRunner() if args.live else DryRunRunner()


def _print_plan(plan) -> int:
    if plan.get("dry_run"):
        print("# dry run — pass --live to execute:")
        for cmd in plan.get("commands", []):
            print(cmd)
    else:
        print("done")
    return 0


def cmd_deploy_gcp_up(args) -> int:
    from determined_clone_tpu.deploy import gcp_up

    return _print_plan(gcp_up(
        cluster_name=args.cluster_name, project=args.project, zone=args.zone,
        accelerator_type=args.accelerator_type, n_agents=args.agents,
        auth_required=args.auth_required, runner=_deploy_runner(args)))


def cmd_deploy_gcp_down(args) -> int:
    from determined_clone_tpu.deploy import gcp_down

    return _print_plan(gcp_down(
        cluster_name=args.cluster_name, project=args.project, zone=args.zone,
        n_agents=args.agents, runner=_deploy_runner(args)))


def cmd_deploy_gke_up(args) -> int:
    from determined_clone_tpu.deploy import gke_up

    return _print_plan(gke_up(
        cluster=args.cluster, project=args.project, zone=args.zone,
        namespace=args.namespace, image=args.image,
        accelerator_type=args.accelerator_type,
        tpu_topology=args.tpu_topology, manifest_path=args.manifests_out,
        runner=_deploy_runner(args)))


def cmd_deploy_gke_down(args) -> int:
    from determined_clone_tpu.deploy import gke_down

    return _print_plan(gke_down(
        cluster=args.cluster, project=args.project, zone=args.zone,
        namespace=args.namespace, runner=_deploy_runner(args)))


def cmd_user_login(args) -> int:
    session = make_session(args)
    import getpass

    password = args.password
    if password is None:
        password = getpass.getpass(f"Password for {args.username}: ")
    session.login(args.username, password)
    master = args.master or os.environ.get("DCT_MASTER", "127.0.0.1:8080")
    store = load_auth_store()
    store[master] = session.token
    save_auth_store(store)
    print(f"Logged in as {args.username}")
    return 0


def cmd_user_logout(args) -> int:
    session = make_session(args)
    try:
        session.logout()
    except MasterError:
        pass
    master = args.master or os.environ.get("DCT_MASTER", "127.0.0.1:8080")
    store = load_auth_store()
    store.pop(master, None)
    save_auth_store(store)
    print("Logged out")
    return 0


def cmd_user_whoami(args) -> int:
    print_json(make_session(args).whoami())
    return 0


def cmd_user_create(args) -> int:
    user = make_session(args).create_user(
        args.username, args.password or "", admin=args.admin)
    print(f"Created user {user['username']} (id {user['id']})")
    return 0


def cmd_user_list(args) -> int:
    print_table(make_session(args).list_users(),
                ["id", "username", "admin", "active"])
    return 0


def cmd_workspace_create(args) -> int:
    ws = make_session(args).create_workspace(args.name)
    print(f"Created workspace {ws['name']} (id {ws['id']})")
    return 0


def cmd_workspace_list(args) -> int:
    print_table(make_session(args).list_workspaces(),
                ["id", "name", "owner", "archived"])
    return 0


def cmd_workspace_describe(args) -> int:
    print_json(make_session(args).get_workspace(args.workspace_id))
    return 0


def cmd_project_create(args) -> int:
    proj = make_session(args).create_project(
        args.workspace_id, args.name, args.description or "")
    print(f"Created project {proj['name']} (id {proj['id']})")
    return 0


def cmd_model_create(args) -> int:
    model = make_session(args).create_model(
        args.name, description=args.description or "")
    print(f"Created model {model['name']} (id {model['id']})")
    return 0


def cmd_model_list(args) -> int:
    print_table(make_session(args).list_models(),
                ["id", "name", "workspace", "archived"])
    return 0


def cmd_model_describe(args) -> int:
    print_json(make_session(args).get_model(args.name))
    return 0


def cmd_model_register_version(args) -> int:
    v = make_session(args).register_model_version(
        args.name, args.checkpoint_uuid)
    print(f"Registered {args.name} version {v['version']}")
    return 0


def cmd_template_set(args) -> int:
    make_session(args).set_template(args.name, load_config_file(args.config))
    print(f"Set template {args.name}")
    return 0


def cmd_template_list(args) -> int:
    print_table(make_session(args).list_templates(), ["name"])
    return 0


def cmd_template_describe(args) -> int:
    print_json(make_session(args).get_template(args.name))
    return 0


def cmd_template_delete(args) -> int:
    make_session(args).delete_template(args.name)
    print(f"Deleted template {args.name}")
    return 0


def cmd_webhook_create(args) -> int:
    hook = make_session(args).create_webhook(
        args.url, triggers=args.trigger or [], webhook_type=args.type)
    print(f"Created webhook {hook['id']}")
    return 0


def cmd_webhook_list(args) -> int:
    print_table(make_session(args).get("/api/v1/webhooks")["webhooks"],
                ["id", "url", "webhook_type", "triggers"])
    return 0


def cmd_webhook_delete(args) -> int:
    make_session(args).request("DELETE", f"/api/v1/webhooks/{args.webhook_id}")
    print(f"Deleted webhook {args.webhook_id}")
    return 0


def cmd_group_create(args) -> int:
    g = make_session(args).create_group(args.name, user_ids=args.user or [])
    print(f"Created group {g['name']} (id {g['id']})")
    return 0


def cmd_group_list(args) -> int:
    print_table(make_session(args).list_groups(), ["id", "name", "user_ids"])
    return 0


def cmd_group_members(args) -> int:
    g = make_session(args).update_group_members(
        args.group_id, add=args.add or [], remove=args.remove or [])
    print(f"Group {g['name']} members: {g['user_ids']}")
    return 0


def cmd_group_delete(args) -> int:
    make_session(args).delete_group(args.group_id)
    print(f"Deleted group {args.group_id}")
    return 0


def cmd_rbac_list_roles(args) -> int:
    print_table(make_session(args).list_roles(), ["name", "rank"])
    return 0


def cmd_rbac_assign(args) -> int:
    a = make_session(args).assign_role(
        args.role, user_id=args.user_id or 0, group_id=args.group_id or 0,
        workspace_id=args.workspace_id or 0)
    print(f"Assigned {a['role']} (assignment {a['id']})")
    return 0


def cmd_rbac_list_assignments(args) -> int:
    print_table(make_session(args).list_role_assignments(),
                ["id", "role", "user_id", "group_id", "workspace_id"])
    return 0


def cmd_rbac_unassign(args) -> int:
    make_session(args).remove_role_assignment(args.assignment_id)
    print(f"Removed assignment {args.assignment_id}")
    return 0


def cmd_rbac_me(args) -> int:
    print_json(make_session(args).my_permissions(args.workspace_id or 0))
    return 0


def cmd_deploy_up(args) -> int:
    from determined_clone_tpu.deploy import cluster_up

    state = cluster_up(
        n_agents=args.agents, slots_per_agent=args.slots_per_agent,
        port=args.port, topology=args.topology or "",
        scheduler=args.scheduler, auth_required=args.auth_required,
    )
    print(f"Local cluster up: master 127.0.0.1:{state['port']} "
          f"({args.agents} agent(s) x {args.slots_per_agent} slot(s))")
    print(f"  export DCT_MASTER=127.0.0.1:{state['port']}")
    return 0


def cmd_deploy_down(args) -> int:
    from determined_clone_tpu.deploy import cluster_down

    out = cluster_down()
    print(f"Stopped {out['stopped']} process(es)")
    return 0


def cmd_deploy_status(args) -> int:
    from determined_clone_tpu.deploy import cluster_status

    print_json(cluster_status())
    return 0


# ---------------------------------------------------------------------------
# parser tree
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="det", description="determined-clone-tpu CLI")
    parser.add_argument("-m", "--master", default=None,
                        help="master address host:port (env DCT_MASTER)")
    sub = parser.add_subparsers(dest="command", required=True)

    # master
    p_master = sub.add_parser("master", help="master info")
    sm = p_master.add_subparsers(dest="subcommand", required=True)
    sm.add_parser("info").set_defaults(func=cmd_master_info)
    sm.add_parser("config").set_defaults(func=cmd_master_config)
    c = sm.add_parser("logs")
    c.add_argument("--limit", type=int, default=200)
    c.add_argument("--offset", type=int, default=0)
    c.set_defaults(func=cmd_master_logs)

    # experiment
    p_exp = sub.add_parser("experiment", aliases=["e"], help="experiments")
    se = p_exp.add_subparsers(dest="subcommand", required=True)
    c = se.add_parser("create")
    c.add_argument("config", help="experiment config YAML")
    c.add_argument("model_dir", nargs="?", default=None,
                   help="model definition directory to upload")
    c.add_argument("--config-override", action="append", default=[],
                   metavar="KEY=VALUE", help="dotted-path config override")
    c.add_argument("-f", "--follow", action="store_true",
                   help="wait for completion")
    c.add_argument("--timeout", type=float, default=3600)
    c.set_defaults(func=cmd_experiment_create)
    c = se.add_parser("list")
    c.add_argument("--show-archived", action="store_true",
                   help="include archived experiments")
    c.set_defaults(func=cmd_experiment_list)
    c = se.add_parser("describe")
    c.add_argument("experiment_id", type=int)
    c.set_defaults(func=cmd_experiment_describe)
    c = se.add_parser("kill")
    c.add_argument("experiment_id", type=int)
    c.set_defaults(func=cmd_experiment_kill)
    for action, fn in (("pause", cmd_experiment_pause),
                       ("activate", cmd_experiment_activate),
                       ("delete", cmd_experiment_delete)):
        c = se.add_parser(action)
        c.add_argument("experiment_id", type=int)
        c.set_defaults(func=fn)
    c = se.add_parser("move")
    c.add_argument("experiment_id", type=int)
    c.add_argument("project_id", type=int)
    c.set_defaults(func=cmd_experiment_move)
    c = se.add_parser("label")
    c.add_argument("experiment_id", type=int)
    c.add_argument("labels", help="comma-separated; empty string clears")
    c.set_defaults(func=cmd_experiment_label)
    c = se.add_parser("progress")
    c.add_argument("experiment_id", type=int)
    c.set_defaults(func=cmd_experiment_progress)
    c = se.add_parser("archive")
    c.add_argument("experiment_id", type=int)
    c.add_argument("--unarchive", action="store_true")
    c.set_defaults(func=cmd_experiment_archive)

    # trial
    p_trial = sub.add_parser("trial", aliases=["t"], help="trials")
    st = p_trial.add_subparsers(dest="subcommand", required=True)
    c = st.add_parser("describe")
    c.add_argument("trial_id", type=int)
    c.set_defaults(func=cmd_trial_describe)
    c = st.add_parser("kill")
    c.add_argument("trial_id", type=int)
    c.set_defaults(func=cmd_trial_kill)
    c = st.add_parser("summary")
    c.add_argument("trial_id", type=int)
    c.set_defaults(func=cmd_trial_summary)
    c = st.add_parser("metrics")
    c.add_argument("trial_id", type=int)
    c.add_argument("--limit", type=int, default=1000)
    c.set_defaults(func=cmd_trial_metrics)
    c = st.add_parser("logs")
    c.add_argument("trial_id", type=int)
    c.add_argument("-f", "--follow", action="store_true",
                   help="live-tail: long-poll for new lines until the "
                        "trial is terminal")
    c.set_defaults(func=cmd_trial_logs)

    # checkpoint
    p_ckpt = sub.add_parser("checkpoint", aliases=["c"], help="checkpoints")
    sc = p_ckpt.add_subparsers(dest="subcommand", required=True)
    c = sc.add_parser("list")
    c.add_argument("experiment_id", type=int)
    c.set_defaults(func=cmd_checkpoint_list)
    c = sc.add_parser("describe")
    c.add_argument("uuid")
    c.set_defaults(func=cmd_checkpoint_describe)
    c = sc.add_parser("download")
    c.add_argument("uuid")
    c.add_argument("-o", "--output-dir", default=".")
    c.set_defaults(func=cmd_checkpoint_download)
    c = sc.add_parser("stats",
                      help="content-addressed store dedup ratio + "
                           "chunk-cache hit rate")
    c.add_argument("--config", default=None,
                   help="experiment config yaml with a checkpoint_storage "
                        "cas block")
    c.add_argument("--host-path", default=None,
                   help="shared_fs storage root (shortcut for a config)")
    c.add_argument("--cache-path", default=None,
                   help="local chunk-cache dir (with --host-path)")
    c.set_defaults(func=cmd_checkpoint_stats)

    # exec-cache (persistent compiled-executable cache on the CAS store)
    p_exec = sub.add_parser(
        "exec-cache",
        help="persistent AOT executable cache on the CAS blob store")
    se = p_exec.add_subparsers(dest="subcommand", required=True)
    c = se.add_parser("stats",
                      help="entries, bytes, per-program breakdown, "
                           "session hit rate")
    c.add_argument("--config", default=None,
                   help="experiment config yaml with a checkpoint_storage "
                        "cas block")
    c.add_argument("--host-path", default=None,
                   help="shared_fs storage root (shortcut for a config)")
    c.add_argument("--dir", default=None,
                   help="bare exec-cache root (the DCT_EXEC_CACHE_DIR "
                        "convention)")
    c.set_defaults(func=cmd_exec_cache_stats)

    # kv (fleet-wide KV memory hierarchy — docs/serving.md)
    p_kv = sub.add_parser(
        "kv", help="fleet-wide KV memory hierarchy (host tier + "
                   "cas/kv/ spill)")
    skv = p_kv.add_subparsers(dest="subcommand", required=True)
    c = skv.add_parser("stats",
                       help="tier entries, bytes, hit split, CAS spill "
                            "accounting")
    c.add_argument("--url", default=None,
                   help="fleet front-door URL (live host-tier + CAS "
                        "counters)")
    c.add_argument("--config", default=None,
                   help="experiment config yaml with a checkpoint_storage "
                        "cas block")
    c.add_argument("--host-path", default=None,
                   help="shared_fs storage root (shortcut for a config)")
    c.set_defaults(func=cmd_kv_stats)

    # task (generic) + NTSC types
    p_task = sub.add_parser("task", help="NTSC tasks")
    stk = p_task.add_subparsers(dest="subcommand", required=True)
    c = stk.add_parser("list")
    c.add_argument("--type", default=None)
    c.set_defaults(func=cmd_task_list)
    c = stk.add_parser("kill")
    c.add_argument("task_id")
    c.set_defaults(func=cmd_task_kill)
    c = stk.add_parser("logs")
    c.add_argument("task_id")
    c.add_argument("-f", "--follow", action="store_true",
                   help="live-tail until the task is terminal")
    c.set_defaults(func=cmd_task_logs)

    p_nb = sub.add_parser("notebook", help="notebook tasks")
    sn = p_nb.add_subparsers(dest="subcommand", required=True)
    sn.add_parser("list").set_defaults(
        func=lambda a: _list_ntsc(a, "notebook"))
    c = sn.add_parser("start")
    c.add_argument("--name", default=None)
    c.add_argument("--idle-timeout", type=float, default=None)
    c.set_defaults(func=cmd_notebook_start)

    p_sh = sub.add_parser("shell", help="shell tasks")
    ss = p_sh.add_subparsers(dest="subcommand", required=True)
    ss.add_parser("list").set_defaults(
        func=lambda a: _list_ntsc(a, "shell"))
    c = ss.add_parser("start")
    c.add_argument("--name", default=None)
    c.add_argument("--idle-timeout", type=float, default=None)
    c.set_defaults(func=cmd_shell_start)
    c = ss.add_parser("exec")
    c.add_argument("task_id")
    c.add_argument("cmd", nargs="+")
    c.set_defaults(func=cmd_shell_exec)

    p_cmd = sub.add_parser("cmd", help="command tasks")
    scm = p_cmd.add_subparsers(dest="subcommand", required=True)
    scm.add_parser("list").set_defaults(
        func=lambda a: _list_ntsc(a, "command"))
    c = scm.add_parser("run")
    c.add_argument("--name", default=None)
    c.add_argument("cmd", nargs="+")
    c.set_defaults(func=cmd_command_run)

    p_tb = sub.add_parser("tensorboard", help="tensorboard tasks")
    stb = p_tb.add_subparsers(dest="subcommand", required=True)
    stb.add_parser("list").set_defaults(
        func=lambda a: _list_ntsc(a, "tensorboard"))
    c = stb.add_parser("start")
    c.add_argument("experiment_ids", help="comma-separated experiment ids")
    c.add_argument("--name", default=None)
    c.set_defaults(func=cmd_tensorboard_start)

    # agent / job
    p_agent = sub.add_parser("agent", aliases=["a"], help="agents")
    sa = p_agent.add_subparsers(dest="subcommand", required=True)
    sa.add_parser("list").set_defaults(func=cmd_agent_list)

    p_job = sub.add_parser("job", aliases=["j"], help="job queue")
    sj = p_job.add_subparsers(dest="subcommand", required=True)
    sj.add_parser("list").set_defaults(func=cmd_job_list)
    c = sj.add_parser("move")
    c.add_argument("allocation_id")
    g = c.add_mutually_exclusive_group(required=True)
    g.add_argument("--ahead-of", default="")
    g.add_argument("--behind", default="")
    c.set_defaults(func=cmd_job_move)
    c = sj.add_parser("set-priority")
    c.add_argument("allocation_id")
    c.add_argument("priority", type=int)
    c.set_defaults(func=cmd_job_set_priority)

    # user
    p_user = sub.add_parser("user", aliases=["u"], help="users")
    su = p_user.add_subparsers(dest="subcommand", required=True)
    c = su.add_parser("login")
    c.add_argument("username")
    c.add_argument("--password", default=None)
    c.set_defaults(func=cmd_user_login)
    su.add_parser("logout").set_defaults(func=cmd_user_logout)
    su.add_parser("whoami").set_defaults(func=cmd_user_whoami)
    c = su.add_parser("create")
    c.add_argument("username")
    c.add_argument("--password", default=None)
    c.add_argument("--admin", action="store_true")
    c.set_defaults(func=cmd_user_create)
    su.add_parser("list").set_defaults(func=cmd_user_list)
    c = su.add_parser("settings")
    c.add_argument("key", nargs="?", default=None)
    c.add_argument("value", nargs="?", default=None,
                   help="JSON value (bare strings accepted)")
    c.set_defaults(func=cmd_user_settings)

    # workspace / project
    p_ws = sub.add_parser("workspace", aliases=["w"], help="workspaces")
    sw = p_ws.add_subparsers(dest="subcommand", required=True)
    c = sw.add_parser("create")
    c.add_argument("name")
    c.set_defaults(func=cmd_workspace_create)
    sw.add_parser("list").set_defaults(func=cmd_workspace_list)
    c = sw.add_parser("describe")
    c.add_argument("workspace_id", type=int)
    c.set_defaults(func=cmd_workspace_describe)

    p_proj = sub.add_parser("project", aliases=["p"], help="projects")
    sp = p_proj.add_subparsers(dest="subcommand", required=True)
    c = sp.add_parser("move")
    c.add_argument("project_id", type=int)
    c.add_argument("workspace_id", type=int)
    c.set_defaults(func=cmd_project_move)
    c = sp.add_parser("create")
    c.add_argument("workspace_id", type=int)
    c.add_argument("name")
    c.add_argument("--description", default=None)
    c.set_defaults(func=cmd_project_create)

    # model registry
    p_model = sub.add_parser("model", help="model registry")
    smo = p_model.add_subparsers(dest="subcommand", required=True)
    c = smo.add_parser("create")
    c.add_argument("name")
    c.add_argument("--description", default=None)
    c.set_defaults(func=cmd_model_create)
    smo.add_parser("list").set_defaults(func=cmd_model_list)
    c = smo.add_parser("describe")
    c.add_argument("name")
    c.set_defaults(func=cmd_model_describe)
    c = smo.add_parser("register-version")
    c.add_argument("name")
    c.add_argument("checkpoint_uuid")
    c.set_defaults(func=cmd_model_register_version)

    # template
    p_tpl = sub.add_parser("template", help="config templates")
    stp = p_tpl.add_subparsers(dest="subcommand", required=True)
    c = stp.add_parser("set")
    c.add_argument("name")
    c.add_argument("config")
    c.set_defaults(func=cmd_template_set)
    stp.add_parser("list").set_defaults(func=cmd_template_list)
    c = stp.add_parser("describe")
    c.add_argument("name")
    c.set_defaults(func=cmd_template_describe)
    c = stp.add_parser("delete")
    c.add_argument("name")
    c.set_defaults(func=cmd_template_delete)

    # webhook
    p_wh = sub.add_parser("webhook", help="webhooks")
    swh = p_wh.add_subparsers(dest="subcommand", required=True)
    c = swh.add_parser("create")
    c.add_argument("url")
    c.add_argument("--trigger", action="append", default=None,
                   help="experiment state that fires the hook (repeatable)")
    c.add_argument("--type", default="default",
                   choices=["default", "slack"])
    c.set_defaults(func=cmd_webhook_create)
    swh.add_parser("list").set_defaults(func=cmd_webhook_list)
    c = swh.add_parser("delete")
    c.add_argument("webhook_id", type=int)
    c.set_defaults(func=cmd_webhook_delete)

    # group (≈ det user-group)
    p_grp = sub.add_parser("group", help="user groups")
    sg = p_grp.add_subparsers(dest="subcommand", required=True)
    c = sg.add_parser("create")
    c.add_argument("name")
    c.add_argument("--user", action="append", type=int, default=None,
                   help="user id to add (repeatable)")
    c.set_defaults(func=cmd_group_create)
    sg.add_parser("list").set_defaults(func=cmd_group_list)
    c = sg.add_parser("members")
    c.add_argument("group_id", type=int)
    c.add_argument("--add", action="append", type=int, default=None)
    c.add_argument("--remove", action="append", type=int, default=None)
    c.set_defaults(func=cmd_group_members)
    c = sg.add_parser("delete")
    c.add_argument("group_id", type=int)
    c.set_defaults(func=cmd_group_delete)

    # rbac (≈ det rbac)
    p_rbac = sub.add_parser("rbac", help="roles and assignments")
    sr = p_rbac.add_subparsers(dest="subcommand", required=True)
    sr.add_parser("list-roles").set_defaults(func=cmd_rbac_list_roles)
    c = sr.add_parser("assign")
    c.add_argument("role")
    c.add_argument("--user-id", type=int, default=None)
    c.add_argument("--group-id", type=int, default=None)
    c.add_argument("--workspace-id", type=int, default=None)
    c.set_defaults(func=cmd_rbac_assign)
    sr.add_parser("list-assignments").set_defaults(
        func=cmd_rbac_list_assignments)
    c = sr.add_parser("unassign")
    c.add_argument("assignment_id", type=int)
    c.set_defaults(func=cmd_rbac_unassign)
    c = sr.add_parser("me")
    c.add_argument("--workspace-id", type=int, default=None)
    c.set_defaults(func=cmd_rbac_me)

    # trace (telemetry timeline export — docs/observability.md)
    p_trace = sub.add_parser("trace", help="telemetry trace export")
    str_ = p_trace.add_subparsers(dest="subcommand", required=True)
    c = str_.add_parser("export",
                        help="build a Chrome trace-event JSON from a "
                             "trial's shipped spans")
    c.add_argument("trial_id", type=int, nargs="?", default=None)
    c.add_argument("--experiment", type=int, default=None,
                   help="stitch every lane of this experiment (runner + "
                        "trials) into one multi-process trace")
    c.add_argument("--from-file", default=None,
                   help="read span records from a local JSONL instead of "
                        "the master")
    c.add_argument("-o", "--output", default="trace.json")
    c.add_argument("--limit", type=int, default=100000,
                   help="max profiler samples to pull from the master")
    c.set_defaults(func=cmd_trace_export)
    c = str_.add_parser("request",
                        help="pull one request's stitched trace (front "
                             "door → router → replica) from a fleet's "
                             "request archive")
    c.add_argument("request_id", help="the request_id to look up")
    c.add_argument("--archive-dir", default=None,
                   help="the fleet's request archive directory "
                        "(DCT_REQUEST_ARCHIVE_DIR)")
    c.add_argument("-o", "--output", default="request-trace.json")
    c.set_defaults(func=cmd_trace_request)

    # debug (post-mortem tooling — docs/observability.md)
    p_dbg = sub.add_parser("debug", help="post-mortem debugging tools")
    sdbg = p_dbg.add_subparsers(dest="subcommand", required=True)
    c = sdbg.add_parser("flight",
                        help="dump a flight-recorder ring (crash black "
                             "box) into a Chrome trace + summary")
    c.add_argument("directory",
                   help="the flight dir (observability.flight_dir / "
                        "DCT_FLIGHT_DIR) of the dead process")
    c.add_argument("-o", "--output", default="flight-trace.json")
    c.add_argument("--json", action="store_true",
                   help="print the summary as JSON")
    c.set_defaults(func=cmd_debug_flight)

    # metrics (cluster-wide observability plane — docs/observability.md)
    c = sub.add_parser("metrics",
                       help="cluster metrics: top trials by throughput, "
                            "quantiles, restart/retry counters")
    c.add_argument("--raw", action="store_true",
                   help="print the raw Prometheus exposition text")
    c.set_defaults(func=cmd_metrics)

    # goodput (wall-clock attribution ledger — docs/observability.md)
    c = sub.add_parser("goodput",
                       help="goodput/badput accounting: fraction of each "
                            "trial's wall-clock that trained the model")
    c.add_argument("--experiment", type=int, default=None,
                   help="only trials of this experiment")
    c.add_argument("--dir", default=None,
                   help="merge an on-disk goodput journal directory "
                        "(observability.goodput_dir / DCT_GOODPUT_DIR) "
                        "instead of asking the master")
    c.add_argument("--json", action="store_true",
                   help="print the accounts as JSON")
    c.set_defaults(func=cmd_goodput)

    # slo (multi-window burn-rate objectives — docs/observability.md)
    c = sub.add_parser("slo",
                       help="serving SLO readout: availability + latency "
                            "burn rates over fast/slow windows")
    c.add_argument("--url", default=None,
                   help="ask a fleet front door (http://host:port) "
                        "instead of the master")
    c.add_argument("--json", action="store_true",
                   help="print the evaluation as JSON")
    c.set_defaults(func=cmd_slo)

    # query (windowed reductions over the master TSDB —
    # docs/observability.md "Time series, queries & alert rules")
    c = sub.add_parser("query",
                       help="query the master's time-series store: "
                            "rate/avg/max/quantile over a window")
    c.add_argument("name", nargs="?", default=None,
                   help="series name (omit to list stored series)")
    c.add_argument("--labels", default=None, metavar="K=V[,K=V...]",
                   help="label subset the series must match")
    c.add_argument("--window", type=float, default=300.0, metavar="S",
                   help="lookback window in seconds (default 300)")
    c.add_argument("--reduce", default="raw",
                   choices=["raw", "rate", "increase", "avg", "max",
                            "min", "last", "quantile"],
                   help="reduction over the window (default raw)")
    c.add_argument("--q", type=float, default=0.95,
                   help="quantile for --reduce quantile (default 0.95)")
    c.add_argument("--json", action="store_true",
                   help="print the query result as JSON")
    c.set_defaults(func=cmd_query)

    # alerts (declarative rule engine readout — docs/observability.md)
    c = sub.add_parser("alerts",
                       help="alert rules: firing/pending/resolved state "
                            "per configured rule")
    c.add_argument("--json", action="store_true",
                   help="print the rule states as JSON")
    c.set_defaults(func=cmd_alerts)

    # top (live dashboard over the query API — docs/observability.md)
    c = sub.add_parser("top",
                       help="live cluster dashboard: throughput "
                            "sparkline, per-replica queue/p99, goodput, "
                            "firing alerts")
    c.add_argument("--once", action="store_true",
                   help="print one frame and exit (for scripts/tests)")
    c.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="redraw period in seconds (default 2)")
    c.add_argument("--window", type=float, default=300.0, metavar="S",
                   help="query lookback window in seconds (default 300)")
    c.set_defaults(func=cmd_top)

    # mesh (collective accounting + straggler + scaling readout —
    # docs/parallelism.md)
    c = sub.add_parser("mesh",
                       help="mesh observability: collective op/byte "
                            "counts, straggler events, multichip scaling "
                            "artifacts")
    c.add_argument("--file", default=None,
                   help="render a MULTICHIP artifact (raw or driver "
                        "MULTICHIP_rN.json wrapper) instead of asking "
                        "the master")
    c.add_argument("--run", type=int, default=None, metavar="N",
                   help="measure fresh on an N-device simulated mesh "
                        "(runs parallel/scaling_bench in a subprocess)")
    c.add_argument("--json", action="store_true",
                   help="print the artifact/rollup as JSON")
    c.set_defaults(func=cmd_mesh)

    # serve (online inference: continuous batching + paged KV cache —
    # docs/serving.md)
    c = sub.add_parser("serve",
                       help="serve a GPT checkpoint over HTTP with "
                            "continuous batching and a paged KV cache")
    c.add_argument("--config", default=None,
                   help="experiment config yaml; its `serving:` block "
                        "sets buckets, KV pool, and admission knobs")
    c.add_argument("--checkpoint", default=None,
                   help="local checkpoint dir (core save_pytree layout) "
                        "to load params from; default: random init")
    c.add_argument("--model", default="tiny",
                   help="model preset (currently: tiny)")
    c.add_argument("--seed", type=int, default=0,
                   help="init seed when no checkpoint is given")
    c.add_argument("--host", default=None)
    c.add_argument("--port", type=int, default=None)
    c.add_argument("--selftest", action="store_true",
                   help="bind an ephemeral port, run a few generations "
                        "through the HTTP surface, print stats, exit")
    c.set_defaults(func=cmd_serve)

    # fleet (replica gangs + router + blue-green rollout — docs/serving.md)
    p_fleet = sub.add_parser("fleet",
                             help="serving fleet: replica gangs behind a "
                                  "least-loaded router with blue-green "
                                  "rollout")
    fleet_sub = p_fleet.add_subparsers(dest="fleet_cmd", required=True)

    c = fleet_sub.add_parser("up", help="run a fleet of engine replicas "
                                        "with an HTTP front door")
    c.add_argument("--replicas", type=int, default=2)
    c.add_argument("--name", default="fleet")
    c.add_argument("--model", default="tiny",
                   help="model preset (currently: tiny)")
    c.add_argument("--seed", type=int, default=0,
                   help="init seed when no checkpoint is given")
    c.add_argument("--checkpoint", default=None,
                   help="local checkpoint dir (core save_pytree layout)")
    c.add_argument("--iteration-floor", type=float, default=0.0,
                   help="simulated device-step floor in seconds (single-"
                        "host capacity modeling; see docs/serving.md)")
    c.add_argument("--with-master", action="store_true",
                   help="register the replicas as `serving` gang "
                        "allocations with the master (needs a local one)")
    c.add_argument("--host", default=None)
    c.add_argument("--port", type=int, default=None)
    c.add_argument("--selftest", action="store_true",
                   help="drive traffic through the HTTP surface, print "
                        "fleet stats as JSON, exit")
    c.set_defaults(func=cmd_fleet_up)

    c = fleet_sub.add_parser("status", help="fleet health from the front "
                                            "door or the master")
    c.add_argument("--url", default=None,
                   help="fleet front-door URL (default: ask the master)")
    c.add_argument("--json", action="store_true")
    c.set_defaults(func=cmd_fleet_status)

    c = fleet_sub.add_parser("rollout",
                             help="blue-green checkpoint rollout: canary "
                                  "first, drained swaps, zero failed "
                                  "requests")
    c.add_argument("--url", required=True,
                   help="fleet front-door URL")
    c.add_argument("--checkpoint", required=True,
                   help="checkpoint dir to roll out")
    c.add_argument("--timeout", type=float, default=300.0)
    c.add_argument("--json", action="store_true")
    c.set_defaults(func=cmd_fleet_rollout)

    c = fleet_sub.add_parser("scale", help="drain-protected fleet resize")
    c.add_argument("--replicas", type=int, required=True)
    c.add_argument("--url", default=None,
                   help="fleet front-door URL (default: ask the master; "
                        "--name selects the fleet)")
    c.add_argument("--name", default="fleet")
    c.add_argument("--timeout", type=float, default=300.0)
    c.set_defaults(func=cmd_fleet_scale)

    # lint (dctlint static analysis — docs/static_analysis.md)
    c = sub.add_parser("lint",
                       help="run the dctlint static-analysis suite over "
                            "the source tree")
    c.add_argument("paths", nargs="*", default=[],
                   help="files/directories (default: the tier-1 set: "
                        "determined_clone_tpu tools bench.py)")
    c.add_argument("--select", default=None,
                   help="comma-separated rule ids (e.g. JAX001,TIME001)")
    c.add_argument("--no-baseline", action="store_true")
    c.add_argument("--write-baseline", action="store_true")
    c.add_argument("--list-checkers", action="store_true")
    c.add_argument("--json", action="store_true")
    c.set_defaults(func=cmd_lint)

    # deploy
    p_dep = sub.add_parser("deploy", help="cluster deployment")
    sd = p_dep.add_subparsers(dest="subcommand", required=True)
    p_local = sd.add_parser("local", help="local process cluster")
    sdl = p_local.add_subparsers(dest="action", required=True)
    c = sdl.add_parser("cluster-up")
    c.add_argument("--agents", type=int, default=1)
    c.add_argument("--slots-per-agent", type=int, default=1)
    c.add_argument("--port", type=int, default=None)
    c.add_argument("--topology", default=None)
    c.add_argument("--scheduler", default="fifo",
                   choices=["fifo", "priority", "fair_share", "round_robin"])
    c.add_argument("--auth-required", action="store_true")
    c.set_defaults(func=cmd_deploy_up)
    sdl.add_parser("cluster-down").set_defaults(func=cmd_deploy_down)
    sdl.add_parser("status").set_defaults(func=cmd_deploy_status)
    p_gcp = sd.add_parser("gcp", help="GCP TPU-VM cluster (dry-run default)")
    sdg = p_gcp.add_subparsers(dest="action", required=True)
    for action, fn in (("up", cmd_deploy_gcp_up),
                       ("down", cmd_deploy_gcp_down)):
        c = sdg.add_parser(action)
        c.add_argument("--project", required=True)
        c.add_argument("--zone", required=True)
        c.add_argument("--cluster-name", default="dct")
        c.add_argument("--agents", type=int, default=1)
        if action == "up":
            c.add_argument("--accelerator-type", default="v5litepod-8")
            c.add_argument("--auth-required", action="store_true")
        c.add_argument("--live", action="store_true",
                       help="actually run gcloud (default: print the plan)")
        c.set_defaults(func=fn)
    p_gke = sd.add_parser("gke", help="GKE + kubernetes RM (dry-run default)")
    sdk = p_gke.add_subparsers(dest="action", required=True)
    for action, fn in (("up", cmd_deploy_gke_up),
                       ("down", cmd_deploy_gke_down)):
        c = sdk.add_parser(action)
        c.add_argument("--project", required=True)
        c.add_argument("--zone", required=True)
        c.add_argument("--cluster", default="dct")
        c.add_argument("--namespace", default="dct")
        if action == "up":
            c.add_argument("--image", default="determined-clone-tpu:latest")
            c.add_argument("--accelerator-type", default="v5litepod-8")
            c.add_argument("--tpu-topology", default="2x4")
            c.add_argument("--manifests-out", default=None,
                           help="write the k8s manifests to this file")
        c.add_argument("--live", action="store_true")
        c.set_defaults(func=fn)

    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        return args.func(args)
    except (MasterError, RuntimeError, FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
