"""The `det` command-line interface (≈ harness/determined/cli)."""
from determined_clone_tpu.cli.cli import main  # noqa: F401
