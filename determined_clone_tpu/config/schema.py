"""Experiment-config JSON schema: the schema as data + a small validator.

≈ the reference's schema-first expconf (schemas/expconf/v0/*.json sourcing
code-generated structs, master/pkg/schemas validation/defaulting). Here the
schema is a Python literal in the same JSON-Schema subset (type, enum,
required, properties, items, union via oneOf discriminated on a field),
validated by ``validate()`` before the dataclass layer parses values —
errors carry JSON paths, unknown keys are reported at known objects, and
unions resolve by their discriminator exactly like the reference's
searcher/storage/hparam union types (expconf/searcher_config.go:16-28).
"""
from __future__ import annotations

from typing import Any, Dict, List

# -- schema subset ----------------------------------------------------------
# {"type": "object", "properties": {...}, "required": [...], "open": bool}
# {"type": "string" | "number" | "integer" | "boolean" | "array", ...}
# {"union": {"field": <discriminator>, "variants": {value: schema}}}
# {"type": ..., "enum": [...]}  /  {"any": True}

LENGTH_SCHEMA = {
    "type": "object",
    "open": False,
    "properties": {
        "batches": {"type": "integer"},
        "records": {"type": "integer"},
        "epochs": {"type": "integer"},
    },
}

SEARCHER_SCHEMA = {
    "union": {
        "field": "name",
        "variants": {
            "single": {
                "type": "object", "open": True,
                "properties": {
                    "metric": {"type": "string"},
                    "smaller_is_better": {"type": "boolean"},
                    "max_length": LENGTH_SCHEMA,
                },
            },
            "random": {
                "type": "object", "open": True,
                "properties": {
                    "metric": {"type": "string"},
                    "max_trials": {"type": "integer"},
                    "max_length": LENGTH_SCHEMA,
                },
            },
            "grid": {
                "type": "object", "open": True,
                "properties": {
                    "metric": {"type": "string"},
                    "max_length": LENGTH_SCHEMA,
                },
            },
            "asha": {
                "type": "object", "open": True,
                "properties": {
                    "metric": {"type": "string"},
                    "max_trials": {"type": "integer"},
                    "num_rungs": {"type": "integer"},
                    "divisor": {"type": "number"},
                    "max_length": LENGTH_SCHEMA,
                },
            },
            "adaptive_asha": {
                "type": "object", "open": True,
                "properties": {
                    "metric": {"type": "string"},
                    "max_trials": {"type": "integer"},
                    "mode": {"type": "string",
                             "enum": ["aggressive", "standard",
                                      "conservative"]},
                    "max_length": LENGTH_SCHEMA,
                },
            },
            "custom": {"type": "object", "open": True, "properties": {}},
        },
    },
}

_STORAGE_VARIANTS = {
    "shared_fs": {
        "type": "object", "open": True,
        "properties": {"host_path": {"type": "string"},
                       "storage_path": {"type": "string"}},
        "required": ["host_path"],
    },
    "directory": {
        "type": "object", "open": True,
        "properties": {"container_path": {"type": "string"}},
        "required": ["container_path"],
    },
    "gcs": {
        "type": "object", "open": True,
        "properties": {"bucket": {"type": "string"},
                       "prefix": {"type": "string"}},
        "required": ["bucket"],
    },
    "s3": {
        "type": "object", "open": True,
        "properties": {"bucket": {"type": "string"},
                       "prefix": {"type": "string"}},
        "required": ["bucket"],
    },
    "azure": {
        "type": "object", "open": True,
        "properties": {"container": {"type": "string"},
                       "connection_string": {"type": "string"},
                       "prefix": {"type": "string"}},
        "required": ["container"],
    },
}

# content-addressed wrapper: nests one concrete backend under `inner`
# (or uses the flat host_path/container_path convenience form, so nothing
# is `required` here — from_dict enforces that one of the forms is given)
_STORAGE_VARIANTS["cas"] = {
    "type": "object", "open": True,
    "properties": {
        "inner": {"union": {"field": "type",
                            "variants": dict(_STORAGE_VARIANTS)}},
        "chunk_size_kb": {"type": "integer"},
        "cache_path": {"type": "string"},
        "cache_size_mb": {"type": "integer"},
        "transfer_workers": {"type": "integer"},
        "host_path": {"type": "string"},
        "storage_path": {"type": "string"},
        "container_path": {"type": "string"},
    },
}

STORAGE_SCHEMA = {
    "union": {
        "field": "type",
        "variants": _STORAGE_VARIANTS,
    },
}

EXPERIMENT_SCHEMA = {
    "type": "object",
    "open": False,
    "properties": {
        "config_version": {"type": "integer", "enum": [0, 1]},
        "name": {"type": "string"},
        "entrypoint": {"type": "string"},
        "template": {"type": "string"},
        "workspace": {"type": "string"},
        "project": {"type": "string"},
        "unmanaged": {"type": "boolean"},
        "labels": {"type": "array", "items": {"type": "string"}},
        "searcher": SEARCHER_SCHEMA,
        "checkpoint_storage": STORAGE_SCHEMA,
        "checkpoint_policy": {"type": "string",
                              "enum": ["best", "all", "none"]},
        "min_validation_period": LENGTH_SCHEMA,
        "min_checkpoint_period": LENGTH_SCHEMA,
        "perform_initial_validation": {"type": "boolean"},
        "max_restarts": {"type": "integer"},
        "records_per_epoch": {"type": "integer"},
        "scheduling_unit": {"type": "integer"},
        "reproducibility": {
            "type": "object", "open": False,
            "properties": {"experiment_seed": {"type": "integer"}},
        },
        "resources": {
            "type": "object", "open": False,
            "properties": {
                "slots_per_trial": {"type": "integer"},
                "resource_pool": {"type": "string"},
                "priority": {"type": "integer"},
                # "v5e-8" or the multislice object {slices, slice_shape}
                "topology": {"anyOf": [
                    {"type": "string"},
                    {"type": "object", "open": False, "properties": {
                        "slices": {"type": "integer"},
                        "slice_shape": {"type": "string"},
                    }},
                ]},
                "max_slots": {"type": "integer"},
            },
        },
        "hyperparameters": {"any": True},
        "log_policies": {
            "type": "array",
            "items": {
                "type": "object", "open": False,
                "properties": {
                    "pattern": {"type": "string"},
                    # string form or the reference's {"type": ...} object
                    "action": {"anyOf": [
                        {"type": "string",
                         "enum": ["cancel_retries", "exclude_node"]},
                        {"type": "object", "open": False,
                         "properties": {
                             "type": {"type": "string",
                                      "enum": ["cancel_retries",
                                               "exclude_node"]}},
                         "required": ["type"]},
                    ]},
                },
                "required": ["pattern", "action"],
            },
        },
        "profiling": {
            "type": "object", "open": False,
            "properties": {"enabled": {"type": "boolean"}},
        },
        # trial-side telemetry (spans + metrics + trace.json export;
        # docs/observability.md)
        "observability": {
            "type": "object", "open": False,
            "properties": {
                "enabled": {"type": "boolean"},
                "max_events": {"type": "integer"},
                "ship_spans": {"type": "boolean"},
                "ship_metrics": {"type": "boolean"},
                "trace_path": {"type": "string"},
                "flight_dir": {"type": "string"},
                "flight_segment_events": {"type": "integer"},
                "flight_segments": {"type": "integer"},
                "goodput_dir": {"type": "string"},
                "anomaly_window": {"type": "integer"},
                "anomaly_threshold": {"type": "number"},
                "anomaly_min_samples": {"type": "integer"},
                # master-side time-series store (telemetry/tsdb.py)
                "timeseries": {
                    "type": "object", "open": False,
                    "properties": {
                        "enabled": {"type": "boolean"},
                        "scrape_period_s": {"type": "number"},
                        "capacity_per_series": {"type": "integer"},
                        "coarse_step_s": {"type": "number"},
                        "coarse_capacity": {"type": "integer"},
                        "memory_budget_mb": {"type": "number"},
                        "max_series": {"type": "integer"},
                        "persist_dir": {"type": "string"},
                        "segment_scrapes": {"type": "integer"},
                        "max_segments": {"type": "integer"},
                    },
                },
                # sources with no ingest for this long are flagged
                # stale in `dct metrics` / absence-rule evaluation
                "stale_after_s": {"type": "number"},
                # declarative alert rules (telemetry/rules.py); each
                # item is validated in depth by AlertRule.from_dict
                "rules": {
                    "type": "array",
                    "items": {
                        "type": "object", "open": False,
                        "properties": {
                            "name": {"type": "string"},
                            "kind": {"type": "string",
                                     "enum": ["threshold",
                                              "rate_of_change",
                                              "burn_rate", "absence"]},
                            "series": {"type": "string"},
                            "labels": {"type": "object", "open": True},
                            "window_s": {"type": "number"},
                            "reduce": {"type": "string"},
                            "op": {"type": "string",
                                   "enum": ["gt", "ge", "lt", "le"]},
                            "value": {"type": "number"},
                            "for_s": {"type": "number"},
                            "severity": {"type": "string",
                                         "enum": ["page", "ticket"]},
                            "stale_s": {"type": "number"},
                            "windows": {
                                "type": "array",
                                "items": {"anyOf": [
                                    {"type": "string"},
                                    {"type": "number"},
                                ]},
                            },
                            "threshold": {"type": "number"},
                            "objective": {"type": "string"},
                            "bad_series": {"type": "string"},
                            "total_series": {"type": "string"},
                        },
                        "required": ["name", "kind"],
                    },
                },
                # install the two PR-13 burn-rate rules over
                # dct_slo_burn_rate (telemetry/rules.py stock_slo_rules)
                "stock_slo_rules": {"type": "boolean"},
            },
        },
        # online inference via `dct serve` (continuous batching over a
        # paged KV cache; docs/serving.md)
        "serving": {
            "type": "object", "open": False,
            "properties": {
                "max_batch": {"type": "integer"},
                "max_prefill_len": {"type": "integer"},
                "kv_block_size": {"type": "integer"},
                "kv_blocks": {"type": "integer"},
                "max_queue_depth": {"type": "integer"},
                "default_max_new_tokens": {"type": "integer"},
                "host": {"type": "string"},
                "port": {"type": "integer"},
                "prefix_cache": {"type": "boolean"},
                "chunk_prefill_len": {"type": "integer"},
                # draft-model speculative decoding (docs/serving.md);
                # the draft shares the target's tokenizer/vocab
                "speculative": {
                    "type": "object", "open": False,
                    "properties": {
                        "enabled": {"type": "boolean"},
                        "k": {"type": "integer"},
                        "draft_layers": {"type": "integer"},
                        "draft_d_model": {"type": "integer"},
                        "draft_n_heads": {"type": "integer"},
                        "draft_d_ff": {"type": "integer"},
                    },
                },
            },
        },
        # deterministic fault injection (seeded FaultPlan;
        # docs/fault_tolerance.md)
        "faults": {
            "type": "object", "open": False,
            "properties": {
                "enabled": {"type": "boolean"},
                "seed": {"type": "integer"},
                "rules": {
                    "type": "array",
                    "items": {
                        "type": "object", "open": False,
                        "properties": {
                            "point": {"type": "string"},
                            "action": {"type": "string",
                                       "enum": ["error", "delay",
                                                "truncate", "exit"]},
                            "nth": {"type": "integer"},
                            "times": {"type": "integer"},
                            "probability": {"type": "number"},
                            "delay_s": {"type": "number"},
                            "exc": {"type": "string",
                                    "enum": ["fault", "io", "conn"]},
                            "message": {"type": "string"},
                            "exit_code": {"type": "integer"},
                            "keep_bytes": {"type": "integer"},
                        },
                        "required": ["point"],
                    },
                },
            },
        },
        # hot-loop knobs (the TPU-native successor of the reference's
        # horovod-centric optimizations block)
        "optimizations": {
            "type": "object", "open": False,
            "properties": {
                "prefetch_depth": {"type": "integer"},
                "steps_per_dispatch": {"type": "integer"},
            },
        },
        "environment": {"any": True},
        "data": {"any": True},
    },
}


class SchemaError(ValueError):
    """Validation failure with a JSON path."""


_TYPES = {
    "string": str,
    "boolean": bool,
    "array": list,
    "object": dict,
}


def _type_ok(schema_type: str, value: Any) -> bool:
    if schema_type == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if schema_type == "number":
        return (isinstance(value, (int, float))
                and not isinstance(value, bool))
    return isinstance(value, _TYPES[schema_type])


def validate(value: Any, schema: Dict[str, Any] = EXPERIMENT_SCHEMA,
             path: str = "<config>",
             discriminator: str = "") -> List[str]:
    """Returns a list of error strings (empty = valid)."""
    errors: List[str] = []
    if schema.get("any"):
        return errors

    if "anyOf" in schema:
        attempts = [validate(value, sub, path) for sub in schema["anyOf"]]
        if any(not a for a in attempts):
            return []
        return [f"{path}: no alternative matched: " +
                "; ".join(a[0] for a in attempts if a)]

    if "union" in schema:
        field = schema["union"]["field"]
        variants = schema["union"]["variants"]
        if not isinstance(value, dict):
            return [f"{path}: expected an object"]
        tag = value.get(field)
        if tag not in variants:
            return [f"{path}.{field}: expected one of "
                    f"{sorted(variants)}, got {tag!r}"]
        return validate(value, variants[tag], path, discriminator=field)

    stype = schema["type"]
    if not _type_ok(stype, value):
        return [f"{path}: expected {stype}, got {type(value).__name__}"]

    if "enum" in schema and value not in schema["enum"]:
        errors.append(f"{path}: expected one of {schema['enum']}, "
                      f"got {value!r}")

    if stype == "object":
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in value:
                errors.append(f"{path}.{req}: required field missing")
        for key, sub in value.items():
            if key in props:
                errors.extend(validate(sub, props[key], f"{path}.{key}"))
            elif discriminator and key == discriminator:
                pass  # the union's tag field, already checked above
            elif not schema.get("open", False):
                errors.append(f"{path}.{key}: unknown field "
                              f"(known: {sorted(props)})")
    elif stype == "array" and "items" in schema:
        for i, item in enumerate(value):
            errors.extend(validate(item, schema["items"], f"{path}[{i}]"))

    return errors


def check(raw: Dict[str, Any]) -> None:
    """Raise SchemaError listing every violation, or return silently."""
    errors = validate(raw)
    if errors:
        raise SchemaError("invalid experiment config:\n  " +
                          "\n  ".join(errors))
