"""Versioned experiment-config shims: v0 spellings → current (v1).

≈ the reference's expconf versioning (schemas/expconf/v0 + legacy shims,
master/pkg/schemas/expconf/legacy.go): old configs keep submitting
unchanged — ``shim()`` rewrites legacy spellings into the current schema
before validation, and records what it changed so the API can surface
deprecation notices. A config opts into a version with ``config_version``
(absent = 0, the permissive legacy format; shimmed configs come out as 1).
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Tuple

CURRENT_VERSION = 1

# v0 searcher names that became adaptive_asha (the reference retired
# adaptive/adaptive_simple/sync_halving the same way)
_LEGACY_ADAPTIVE = {"adaptive", "adaptive_simple", "sync_halving"}


def _shim_length(value: Any, notes: List[str], where: str) -> Any:
    # v0 allowed a bare integer meaning batches
    if isinstance(value, int) and not isinstance(value, bool):
        notes.append(f"{where}: bare integer lengths are v0; "
                     f"use {{'batches': {value}}}")
        return {"batches": value}
    return value


def shim(raw: Dict[str, Any]) -> Tuple[Dict[str, Any], List[str]]:
    """Returns (current-version config, deprecation notes). Input is not
    mutated. A config already at CURRENT_VERSION passes through untouched
    (no silent rewriting of modern configs)."""
    version = raw.get("config_version", 0)
    if version >= CURRENT_VERSION:
        return raw, []

    cfg = copy.deepcopy(raw)
    notes: List[str] = []

    searcher = cfg.get("searcher")
    if isinstance(searcher, dict):
        name = searcher.get("name")
        if name in _LEGACY_ADAPTIVE:
            searcher["name"] = "adaptive_asha"
            notes.append(f"searcher.name {name!r} is v0; shimmed to "
                         "'adaptive_asha'")
        if "max_steps" in searcher and "max_length" not in searcher:
            searcher["max_length"] = {"batches": searcher.pop("max_steps")}
            notes.append("searcher.max_steps is v0; shimmed to "
                         "max_length.batches")
        if "max_length" in searcher:
            searcher["max_length"] = _shim_length(
                searcher["max_length"], notes, "searcher.max_length")
        if "smaller_is_better" not in searcher and "metric" in searcher:
            pass  # defaulting, not a shim

    for period in ("min_validation_period", "min_checkpoint_period"):
        if period in cfg:
            cfg[period] = _shim_length(cfg[period], notes, period)

    # v0 `batches_per_step` became scheduling_unit
    if "batches_per_step" in cfg and "scheduling_unit" not in cfg:
        cfg["scheduling_unit"] = cfg.pop("batches_per_step")
        notes.append("batches_per_step is v0; shimmed to scheduling_unit")

    # v0 nested `optimizations` block: horovod-era keys (aggregation_
    # frequency etc.) map onto nothing (XLA owns fusion); keep submissions
    # working by dropping those with a note, while the TPU-native keys
    # (prefetch_depth, steps_per_dispatch) pass through to the v1 block
    if "optimizations" in cfg:
        opt = cfg.pop("optimizations")
        kept = {}
        if isinstance(opt, dict):
            kept = {key: opt[key]
                    for key in ("prefetch_depth", "steps_per_dispatch")
                    if key in opt}
            dropped = sorted(set(opt) - set(kept))
        else:
            dropped = ["<non-mapping optimizations>"]
        if dropped:
            notes.append(f"optimizations keys {dropped} are v0 and have no "
                         "TPU equivalent (XLA owns fusion/aggregation); "
                         "ignored")
        if kept:
            cfg["optimizations"] = kept

    # v0 `telemetry` block became `observability` (matching the subsystem
    # package name); same keys, straight rename
    if "telemetry" in cfg:
        tel = cfg.pop("telemetry")
        if "observability" in cfg:
            raise ValueError(
                "config sets both legacy telemetry and observability "
                "blocks; remove the legacy key")
        cfg["observability"] = tel
        notes.append("top-level telemetry is v0; shimmed to observability")

    # flat `type: cas` form (a bare host_path/container_path instead of a
    # nested inner backend block) is the v0 spelling; rewrite it to the
    # explicit `inner:` form the v1 schema documents
    storage = cfg.get("checkpoint_storage")
    if (isinstance(storage, dict) and storage.get("type") == "cas"
            and "inner" not in storage):
        if storage.get("host_path"):
            storage["inner"] = {
                "type": "shared_fs",
                "host_path": storage.pop("host_path"),
            }
            if storage.get("storage_path"):
                storage["inner"]["storage_path"] = storage.pop(
                    "storage_path")
            notes.append("checkpoint_storage flat cas host_path is v0; "
                         "shimmed to inner shared_fs block")
        elif storage.get("container_path"):
            storage["inner"] = {
                "type": "directory",
                "container_path": storage.pop("container_path"),
            }
            notes.append("checkpoint_storage flat cas container_path is "
                         "v0; shimmed to inner directory block")

    # v0 flat `slots` became resources.slots_per_trial
    if "slots" in cfg:
        slots = cfg.pop("slots")
        resources = cfg.setdefault("resources", {})
        existing = resources.get("slots_per_trial")
        if existing is not None and existing != slots:
            # silently preferring either value would lie to the user about
            # their gang size — make the conflict explicit
            raise ValueError(
                f"config sets both legacy top-level slots ({slots}) and "
                f"resources.slots_per_trial ({existing}); remove the "
                "legacy key")
        resources.setdefault("slots_per_trial", slots)
        notes.append("top-level slots is v0; shimmed to "
                     "resources.slots_per_trial")

    cfg["config_version"] = CURRENT_VERSION
    return cfg, notes
