"""Hyperparameter space definitions.

Equivalent of the reference expconf hyperparameter union types
(master/pkg/schemas/expconf/hparam.go and schemas/expconf/v0/hyperparameter-*.json):
const / int / double / log / categorical, plus arbitrarily nested dicts.

A hyperparameter space is a nested dict whose leaves are either plain JSON
values (implicit const) or ``{"type": ...}`` dicts. ``sample()`` draws a
concrete assignment; ``grid_points()`` enumerates the grid for the grid
searcher (reference: master/pkg/searcher/grid.go).
"""
from __future__ import annotations

import abc
import dataclasses
import math
import random
from typing import Any, Dict, Iterator, List, Optional, Sequence


class Hyperparameter(abc.ABC):
    @abc.abstractmethod
    def sample(self, rng: random.Random) -> Any:
        ...

    @abc.abstractmethod
    def grid_points(self) -> List[Any]:
        """Values this hparam contributes to a grid search."""
        ...

    @abc.abstractmethod
    def to_dict(self) -> Dict[str, Any]:
        ...


@dataclasses.dataclass(frozen=True)
class Const(Hyperparameter):
    value: Any

    def sample(self, rng: random.Random) -> Any:
        return self.value

    def grid_points(self) -> List[Any]:
        return [self.value]

    def to_dict(self) -> Dict[str, Any]:
        return {"type": "const", "val": self.value}


@dataclasses.dataclass(frozen=True)
class Int(Hyperparameter):
    minval: int
    maxval: int
    count: Optional[int] = None  # for grid search

    def __post_init__(self) -> None:
        if self.minval > self.maxval:
            raise ValueError(f"int hparam: minval {self.minval} > maxval {self.maxval}")

    def sample(self, rng: random.Random) -> int:
        return rng.randint(self.minval, self.maxval)

    def grid_points(self) -> List[int]:
        n = self.count if self.count else (self.maxval - self.minval + 1)
        n = min(n, self.maxval - self.minval + 1)
        if n == 1:
            return [self.minval]
        step = (self.maxval - self.minval) / (n - 1)
        return [round(self.minval + i * step) for i in range(n)]

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"type": "int", "minval": self.minval, "maxval": self.maxval}
        if self.count is not None:
            d["count"] = self.count
        return d


@dataclasses.dataclass(frozen=True)
class Double(Hyperparameter):
    minval: float
    maxval: float
    count: Optional[int] = None

    def __post_init__(self) -> None:
        if self.minval > self.maxval:
            raise ValueError(f"double hparam: minval {self.minval} > maxval {self.maxval}")

    def sample(self, rng: random.Random) -> float:
        return rng.uniform(self.minval, self.maxval)

    def grid_points(self) -> List[float]:
        if not self.count:
            raise ValueError("double hparam requires `count` for grid search")
        if self.count == 1:
            return [self.minval]
        step = (self.maxval - self.minval) / (self.count - 1)
        return [self.minval + i * step for i in range(self.count)]

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"type": "double", "minval": self.minval, "maxval": self.maxval}
        if self.count is not None:
            d["count"] = self.count
        return d


@dataclasses.dataclass(frozen=True)
class Log(Hyperparameter):
    """Log-uniform over [base**minval, base**maxval]."""

    minval: float
    maxval: float
    base: float = 10.0
    count: Optional[int] = None

    def __post_init__(self) -> None:
        if self.minval > self.maxval:
            raise ValueError(f"log hparam: minval {self.minval} > maxval {self.maxval}")
        if self.base <= 0:
            raise ValueError("log hparam: base must be positive")

    def sample(self, rng: random.Random) -> float:
        return self.base ** rng.uniform(self.minval, self.maxval)

    def grid_points(self) -> List[float]:
        if not self.count:
            raise ValueError("log hparam requires `count` for grid search")
        if self.count == 1:
            return [self.base**self.minval]
        step = (self.maxval - self.minval) / (self.count - 1)
        return [self.base ** (self.minval + i * step) for i in range(self.count)]

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "type": "log", "minval": self.minval, "maxval": self.maxval, "base": self.base,
        }
        if self.count is not None:
            d["count"] = self.count
        return d


@dataclasses.dataclass(frozen=True)
class Categorical(Hyperparameter):
    vals: Sequence[Any]

    def __post_init__(self) -> None:
        if not self.vals:
            raise ValueError("categorical hparam needs at least one value")

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(list(self.vals))

    def grid_points(self) -> List[Any]:
        return list(self.vals)

    def to_dict(self) -> Dict[str, Any]:
        return {"type": "categorical", "vals": list(self.vals)}


_HP_TYPES = {"const", "int", "double", "log", "categorical"}


def parse_hyperparameter(raw: Any) -> Hyperparameter:
    """Parse one leaf of the hparam space. Non-dict (or dict without a known
    "type") values are implicit consts, matching the reference's behavior."""
    if isinstance(raw, dict) and raw.get("type") in _HP_TYPES:
        t = raw["type"]
        if t == "const":
            return Const(raw.get("val"))
        if t == "int":
            return Int(int(raw["minval"]), int(raw["maxval"]), raw.get("count"))
        if t == "double":
            return Double(float(raw["minval"]), float(raw["maxval"]), raw.get("count"))
        if t == "log":
            return Log(
                float(raw["minval"]), float(raw["maxval"]),
                float(raw.get("base", 10.0)), raw.get("count"),
            )
        if t == "categorical":
            return Categorical(list(raw["vals"]))
    return Const(raw)


class HyperparameterSpace:
    """A nested hparam space; leaves are Hyperparameter objects."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None) -> None:
        self.raw = raw or {}
        self._flat: Dict[str, Hyperparameter] = {}
        self._flatten("", self.raw)

    def _flatten(self, prefix: str, node: Any) -> None:
        if isinstance(node, dict) and not (node.get("type") in _HP_TYPES):
            for k, v in node.items():
                if "." in str(k):
                    raise ValueError(
                        f"hyperparameter name {k!r} may not contain '.' "
                        f"(reserved as the nesting separator)"
                    )
                self._flatten(f"{prefix}{k}.", v)
        else:
            self._flat[prefix[:-1] if prefix.endswith(".") else prefix] = (
                parse_hyperparameter(node)
            )

    @property
    def flat(self) -> Dict[str, Hyperparameter]:
        return dict(self._flat)

    def sample(self, rng: random.Random) -> Dict[str, Any]:
        """Draw one concrete (nested) assignment."""
        return self._unflatten({k: hp.sample(rng) for k, hp in self._flat.items()})

    def grid(self) -> Iterator[Dict[str, Any]]:
        """Enumerate the full cartesian grid (reference grid.go semantics)."""
        keys = sorted(self._flat)
        axes = [self._flat[k].grid_points() for k in keys]
        total = math.prod(len(a) for a in axes) if axes else 0
        if total == 0:
            yield {}
            return
        idx = [0] * len(axes)
        for _ in range(total):
            yield self._unflatten({k: axes[i][idx[i]] for i, k in enumerate(keys)})
            for i in reversed(range(len(axes))):
                idx[i] += 1
                if idx[i] < len(axes[i]):
                    break
                idx[i] = 0

    def grid_size(self) -> int:
        # empty product = 1, matching grid()'s single empty config
        return math.prod(len(hp.grid_points()) for hp in self._flat.values())

    @staticmethod
    def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, val in flat.items():
            parts = key.split(".")
            node = out
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = val
        return out
