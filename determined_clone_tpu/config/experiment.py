"""Experiment configuration — the expconf equivalent.

Mirrors the reference's versioned, validated experiment config
(master/pkg/schemas/expconf/experiment_config.go:20-50) with TPU-native
resources: ``slots_per_trial`` counts TPU chips and ``topology`` names a pod
slice shape (e.g. "v5e-8", "2x4"), which the scheduler's fitting logic treats
as an ICI-adjacency constraint rather than a flat slot count.

Parsing follows the reference pipeline (expconf/parse.go): parse → fill
defaults → validate, with union types for searcher / checkpoint storage and
clear error messages on invalid input.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from determined_clone_tpu.config.hyperparameters import HyperparameterSpace
from determined_clone_tpu.config.length import Length


class ConfigError(ValueError):
    """Invalid experiment configuration."""


# ---------------------------------------------------------------------------
# Searcher union (reference: expconf/searcher_config.go:16-28)
# ---------------------------------------------------------------------------

_SEARCHER_NAMES = {"single", "random", "grid", "asha", "adaptive_asha", "custom"}


@dataclasses.dataclass
class SearcherConfig:
    name: str = "single"
    metric: str = "loss"
    smaller_is_better: bool = True
    max_length: Optional[Length] = None
    # random
    max_trials: int = 1
    # asha / adaptive_asha
    max_time: Optional[int] = None      # rungs ceiling, in scheduling units
    num_rungs: int = 5
    divisor: int = 4
    max_concurrent_trials: int = 16
    # adaptive_asha
    mode: str = "standard"              # aggressive | standard | conservative
    bracket_rungs: Optional[List[int]] = None
    # single / stopping-based asha
    stop_once: bool = False
    # source-of-truth blob for anything extra (custom searchers)
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "SearcherConfig":
        if not isinstance(raw, dict):
            raise ConfigError(f"searcher must be a mapping, got {raw!r}")
        name = raw.get("name", "single")
        if name not in _SEARCHER_NAMES:
            raise ConfigError(
                f"unknown searcher name {name!r}; expected one of {sorted(_SEARCHER_NAMES)}"
            )
        known = {f.name for f in dataclasses.fields(SearcherConfig)} - {"extra"}
        cfg = SearcherConfig(
            name=name,
            metric=raw.get("metric", "loss"),
            smaller_is_better=bool(raw.get("smaller_is_better", True)),
            max_length=Length.from_dict(raw["max_length"]) if "max_length" in raw else None,
            max_trials=int(raw.get("max_trials", 1)),
            max_time=raw.get("max_time"),
            num_rungs=int(raw.get("num_rungs", 5)),
            divisor=int(raw.get("divisor", 4)),
            max_concurrent_trials=int(raw.get("max_concurrent_trials", 16)),
            mode=raw.get("mode", "standard"),
            bracket_rungs=raw.get("bracket_rungs"),
            stop_once=bool(raw.get("stop_once", False)),
            extra={k: v for k, v in raw.items() if k not in known},
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.name in ("random", "grid", "asha", "adaptive_asha") and self.max_trials < 1:
            raise ConfigError(f"searcher.max_trials must be >= 1, got {self.max_trials}")
        if self.name in ("asha", "adaptive_asha"):
            if self.divisor < 2:
                raise ConfigError(f"searcher.divisor must be >= 2, got {self.divisor}")
            if self.num_rungs < 1:
                raise ConfigError(f"searcher.num_rungs must be >= 1, got {self.num_rungs}")
        if self.name == "adaptive_asha" and self.mode not in (
            "aggressive", "standard", "conservative",
        ):
            raise ConfigError(f"unknown adaptive_asha mode {self.mode!r}")

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "metric": self.metric,
            "smaller_is_better": self.smaller_is_better,
        }
        if self.max_length is not None:
            d["max_length"] = self.max_length.to_dict()
        if self.name in ("random", "grid", "asha", "adaptive_asha"):
            d["max_trials"] = self.max_trials
        if self.name in ("asha", "adaptive_asha"):
            d.update(
                max_time=self.max_time, num_rungs=self.num_rungs, divisor=self.divisor,
                max_concurrent_trials=self.max_concurrent_trials, stop_once=self.stop_once,
            )
        if self.name == "adaptive_asha":
            d["mode"] = self.mode
        d.update(self.extra)
        return d


# ---------------------------------------------------------------------------
# Resources (TPU-native: chips + slice topology)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ResourcesConfig:
    slots_per_trial: int = 1            # TPU chips per trial (gang size)
    topology: Optional[str] = None      # e.g. "v5e-8", "2x4"; None = any fit
    slices: int = 1                     # multislice: gang N whole slices
                                        # (DCN between them); topology is
                                        # then the per-slice shape
    resource_pool: str = "default"
    priority: Optional[int] = None      # priority-scheduler weight
    max_slots: Optional[int] = None     # cap across concurrent trials

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "ResourcesConfig":
        topo = raw.get("topology")
        slices = 1
        if isinstance(topo, dict):
            slices = int(topo.get("slices", 1))
            topo = topo.get("slice_shape")
        cfg = ResourcesConfig(
            slots_per_trial=int(raw.get("slots_per_trial", 1)),
            topology=topo,
            slices=slices,
            resource_pool=raw.get("resource_pool", "default"),
            priority=int(raw["priority"]) if raw.get("priority") is not None else None,
            max_slots=raw.get("max_slots"),
        )
        if cfg.slices < 1:
            raise ConfigError(
                f"resources.topology.slices must be >= 1, got {cfg.slices}")
        if cfg.slices > 1 and (cfg.slots_per_trial < cfg.slices
                               or cfg.slots_per_trial % cfg.slices != 0):
            raise ConfigError(
                f"slots_per_trial ({cfg.slots_per_trial}) must divide evenly "
                f"into {cfg.slices} slices (at least one chip per slice)")
        if cfg.slots_per_trial < 0:
            raise ConfigError(f"resources.slots_per_trial must be >= 0, got {cfg.slots_per_trial}")
        if cfg.priority is not None and not (1 <= int(cfg.priority) <= 99):
            raise ConfigError("resources.priority must be in [1, 99]")
        return cfg

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None}
        if d.pop("slices", 1) > 1:
            # round-trip the multislice object form the master parses
            d["topology"] = {"slices": self.slices,
                            "slice_shape": self.topology}
            if self.topology is None:
                d["topology"].pop("slice_shape")
        return d


# ---------------------------------------------------------------------------
# Checkpoint storage union (reference: expconf checkpoint_storage_config;
# harness/determined/common/storage backends)
# ---------------------------------------------------------------------------

_STORAGE_TYPES = {"shared_fs", "directory", "gcs", "s3", "azure", "cas"}


@dataclasses.dataclass
class CheckpointStorageConfig:
    type: str = "shared_fs"
    host_path: Optional[str] = None       # shared_fs
    storage_path: Optional[str] = None    # shared_fs subdir / directory path
    container_path: Optional[str] = None  # directory
    bucket: Optional[str] = None          # gcs / s3
    prefix: Optional[str] = None          # gcs / s3 / azure
    container: Optional[str] = None       # azure blob container
    connection_string: Optional[str] = None  # azure
    save_experiment_best: int = 0
    save_trial_best: int = 1
    save_trial_latest: int = 1
    # content-addressed store (type: cas) — all default None so non-cas
    # configs round-trip byte-identically through to_dict
    chunk_size_kb: Optional[int] = None
    cache_path: Optional[str] = None
    cache_size_mb: Optional[int] = None
    transfer_workers: Optional[int] = None
    inner: Optional["CheckpointStorageConfig"] = None

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "CheckpointStorageConfig":
        t = raw.get("type", "shared_fs")
        if t not in _STORAGE_TYPES:
            raise ConfigError(
                f"unknown checkpoint_storage.type {t!r}; expected one of {sorted(_STORAGE_TYPES)}"
            )
        inner = None
        if t == "cas":
            inner_raw = raw.get("inner")
            if inner_raw is None:
                # flat convenience form: `type: cas` + a shared_fs/directory
                # path — synthesize the inner backend block
                if raw.get("host_path"):
                    inner_raw = {"type": "shared_fs",
                                 "host_path": raw["host_path"],
                                 "storage_path": raw.get("storage_path")}
                    inner_raw = {k: v for k, v in inner_raw.items()
                                 if v is not None}
                elif raw.get("container_path"):
                    inner_raw = {"type": "directory",
                                 "container_path": raw["container_path"]}
                else:
                    raise ConfigError(
                        "checkpoint_storage type 'cas' needs an 'inner' "
                        "backend block (or a flat host_path/container_path)")
            if inner_raw.get("type") == "cas":
                raise ConfigError(
                    "checkpoint_storage.inner cannot itself be 'cas'")
            inner = CheckpointStorageConfig.from_dict(inner_raw)
        cfg = CheckpointStorageConfig(
            type=t,
            host_path=raw.get("host_path"),
            storage_path=raw.get("storage_path"),
            container_path=raw.get("container_path"),
            bucket=raw.get("bucket"),
            prefix=raw.get("prefix"),
            container=raw.get("container"),
            connection_string=raw.get("connection_string"),
            save_experiment_best=int(raw.get("save_experiment_best", 0)),
            save_trial_best=int(raw.get("save_trial_best", 1)),
            save_trial_latest=int(raw.get("save_trial_latest", 1)),
            chunk_size_kb=(int(raw["chunk_size_kb"])
                           if raw.get("chunk_size_kb") is not None else None),
            cache_path=raw.get("cache_path"),
            cache_size_mb=(int(raw["cache_size_mb"])
                           if raw.get("cache_size_mb") is not None else None),
            transfer_workers=(int(raw["transfer_workers"])
                              if raw.get("transfer_workers") is not None
                              else None),
            inner=inner,
        )
        if t == "shared_fs" and not cfg.host_path:
            raise ConfigError("checkpoint_storage.host_path is required for shared_fs storage")
        if t == "directory" and not cfg.container_path:
            raise ConfigError(
                "checkpoint_storage.container_path is required for directory storage"
            )
        if t in ("gcs", "s3") and not cfg.bucket:
            raise ConfigError(f"checkpoint_storage.bucket is required for {t} storage")
        if t == "azure" and not cfg.container:
            raise ConfigError(
                "checkpoint_storage.container is required for azure storage"
            )
        if cfg.chunk_size_kb is not None and cfg.chunk_size_kb < 1:
            raise ConfigError(
                f"checkpoint_storage.chunk_size_kb must be >= 1, "
                f"got {cfg.chunk_size_kb}")
        if cfg.cache_size_mb is not None and cfg.cache_size_mb < 1:
            raise ConfigError(
                f"checkpoint_storage.cache_size_mb must be >= 1, "
                f"got {cfg.cache_size_mb}")
        if cfg.transfer_workers is not None and cfg.transfer_workers < 0:
            raise ConfigError(
                f"checkpoint_storage.transfer_workers must be >= 0, "
                f"got {cfg.transfer_workers}")
        return cfg

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None and k != "inner"}
        if self.inner is not None:
            d["inner"] = self.inner.to_dict()
        return d


# ---------------------------------------------------------------------------
# Optimizations (reference: expconf OptimizationsConfig — there it tunes
# horovod aggregation; here it tunes the XLA hot loop: input prefetch and
# fused multi-step dispatch)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OptimizationsConfig:
    prefetch_depth: int = 2        # device batches buffered ahead (0 = sync)
    steps_per_dispatch: int = 1    # optimizer steps fused into one program

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "OptimizationsConfig":
        if not isinstance(raw, dict):
            raise ConfigError(f"optimizations must be a mapping, got {raw!r}")
        cfg = OptimizationsConfig(
            prefetch_depth=int(raw.get("prefetch_depth", 2)),
            steps_per_dispatch=int(raw.get("steps_per_dispatch", 1)),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.prefetch_depth < 0:
            raise ConfigError(
                f"optimizations.prefetch_depth must be >= 0, "
                f"got {self.prefetch_depth}")
        if self.steps_per_dispatch < 1:
            raise ConfigError(
                f"optimizations.steps_per_dispatch must be >= 1, "
                f"got {self.steps_per_dispatch}")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Observability (trial-side telemetry: spans, metrics registry, Chrome-trace
# export — see docs/observability.md; disabled by default so the hot loop
# stays unwrapped)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ObservabilityConfig:
    enabled: bool = False
    max_events: int = 200_000      # span-record cap (head kept, tail dropped)
    ship_spans: bool = False       # ship span records over profiler channel
    ship_metrics: bool = True      # ship registry snapshots over it
    trace_path: Optional[str] = None  # trace.json destination; None = default
    # flight recorder (crash black box — telemetry/flight.py); None = off
    flight_dir: Optional[str] = None
    flight_segment_events: int = 256  # records per segment file
    flight_segments: int = 8          # ring size (oldest deleted)
    # goodput ledger journal (telemetry/goodput.py); None = ledger in
    # memory only (still published as goodput_* gauges)
    goodput_dir: Optional[str] = None
    # step-time anomaly detector (rolling median/MAD over train_dispatch)
    anomaly_window: int = 64       # rolling baseline length
    anomaly_threshold: float = 5.0  # MAD multiples above median to fire
    anomaly_min_samples: int = 16  # warmup before the detector arms
    # master-side time-series store (telemetry/tsdb.py); None = the
    # TSDB is not enabled. Keys mirror TimeSeriesDB.from_dict.
    timeseries: Optional[Dict[str, Any]] = None
    # sources with no ingest for this long are flagged stale in
    # `dct metrics` output and skipped by the TSDB scrape
    stale_after_s: float = 60.0
    # declarative alert rules (telemetry/rules.py AlertRule.from_dict)
    rules: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # install the two PR-13 burn-rate rules over dct_slo_burn_rate
    stock_slo_rules: bool = False

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "ObservabilityConfig":
        if not isinstance(raw, dict):
            raise ConfigError(f"observability must be a mapping, got {raw!r}")
        cfg = ObservabilityConfig(
            enabled=bool(raw.get("enabled", False)),
            max_events=int(raw.get("max_events", 200_000)),
            ship_spans=bool(raw.get("ship_spans", False)),
            ship_metrics=bool(raw.get("ship_metrics", True)),
            trace_path=raw.get("trace_path"),
            flight_dir=raw.get("flight_dir"),
            flight_segment_events=int(raw.get("flight_segment_events", 256)),
            flight_segments=int(raw.get("flight_segments", 8)),
            goodput_dir=raw.get("goodput_dir"),
            anomaly_window=int(raw.get("anomaly_window", 64)),
            anomaly_threshold=float(raw.get("anomaly_threshold", 5.0)),
            anomaly_min_samples=int(raw.get("anomaly_min_samples", 16)),
            timeseries=raw.get("timeseries"),
            stale_after_s=float(raw.get("stale_after_s", 60.0)),
            rules=list(raw.get("rules") or []),
            stock_slo_rules=bool(raw.get("stock_slo_rules", False)),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.max_events < 1:
            raise ConfigError(
                f"observability.max_events must be >= 1, "
                f"got {self.max_events}")
        if self.flight_segment_events < 1:
            raise ConfigError(
                f"observability.flight_segment_events must be >= 1, "
                f"got {self.flight_segment_events}")
        if self.flight_segments < 2:
            raise ConfigError(
                f"observability.flight_segments must be >= 2, "
                f"got {self.flight_segments}")
        if self.anomaly_window < 4:
            raise ConfigError(
                f"observability.anomaly_window must be >= 4, "
                f"got {self.anomaly_window}")
        if self.anomaly_threshold <= 0:
            raise ConfigError(
                f"observability.anomaly_threshold must be > 0, "
                f"got {self.anomaly_threshold}")
        if self.anomaly_min_samples < 2:
            raise ConfigError(
                f"observability.anomaly_min_samples must be >= 2, "
                f"got {self.anomaly_min_samples}")
        if self.timeseries is not None and not isinstance(self.timeseries,
                                                          dict):
            raise ConfigError(
                f"observability.timeseries must be a mapping, "
                f"got {self.timeseries!r}")
        if self.stale_after_s <= 0:
            raise ConfigError(
                f"observability.stale_after_s must be > 0, "
                f"got {self.stale_after_s}")
        # rule semantics (per-kind required fields, thresholds) live in
        # telemetry/rules.py; validating here makes `dct experiment
        # create` reject a bad rule instead of the master at scrape time
        from determined_clone_tpu.telemetry.rules import AlertRule

        for i, rule in enumerate(self.rules):
            try:
                AlertRule.from_dict(rule)
            except (TypeError, ValueError) as e:
                raise ConfigError(
                    f"observability.rules[{i}]: {e}") from e

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


# ---------------------------------------------------------------------------
# Serving (the `serving:` block) — online inference via `dct serve`:
# continuous batching over a paged KV cache; see docs/serving.md. The knobs
# are the engine's shape/capacity contract: buckets bound the XLA program
# count, kv blocks bound concurrent context, queue depth is the admission
# valve. No reference equivalent (the reference serves only batch jobs).
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SpeculativeConfig:
    """The ``serving.speculative:`` block — draft-model speculative
    decoding (docs/serving.md). The draft GPT shares the target's
    tokenizer/vocab and max_seq_len; only its depth/width are chosen
    here. Greedy output is bit-identical to plain decode regardless of
    draft quality — a bad draft only costs speed."""
    enabled: bool = False
    k: int = 4                      # draft tokens proposed per iteration
    draft_layers: int = 1
    draft_d_model: int = 128
    draft_n_heads: int = 2
    draft_d_ff: int = 512

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "SpeculativeConfig":
        if not isinstance(raw, dict):
            raise ConfigError(
                f"serving.speculative must be a mapping, got {raw!r}")
        cfg = SpeculativeConfig(
            enabled=bool(raw.get("enabled", False)),
            k=int(raw.get("k", 4)),
            draft_layers=int(raw.get("draft_layers", 1)),
            draft_d_model=int(raw.get("draft_d_model", 128)),
            draft_n_heads=int(raw.get("draft_n_heads", 2)),
            draft_d_ff=int(raw.get("draft_d_ff", 512)),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if not 1 <= self.k <= 16:
            raise ConfigError(
                f"serving.speculative.k must be in [1, 16], got {self.k}")
        for name, v in (("draft_layers", self.draft_layers),
                        ("draft_d_model", self.draft_d_model),
                        ("draft_n_heads", self.draft_n_heads),
                        ("draft_d_ff", self.draft_d_ff)):
            if v < 1:
                raise ConfigError(
                    f"serving.speculative.{name} must be >= 1, got {v}")
        if self.draft_d_model % self.draft_n_heads:
            raise ConfigError(
                f"serving.speculative.draft_d_model "
                f"{self.draft_d_model} must divide by draft_n_heads "
                f"{self.draft_n_heads}")


@dataclasses.dataclass
class ServingConfig:
    max_batch: int = 8              # largest (pow2) batch bucket
    max_prefill_len: int = 128      # largest (pow2) prompt-length bucket
    kv_block_size: int = 16         # KV pool block size (pow2 positions)
    kv_blocks: int = 0              # pool blocks; 0 = size for max_batch
    max_queue_depth: int = 64       # admission valve → 429/ServerOverloaded
    default_max_new_tokens: int = 64
    host: str = "127.0.0.1"
    port: int = 8191
    prefix_cache: bool = False      # copy-on-write prompt-prefix sharing
    chunk_prefill_len: int = 0      # 0 = off; else a prefill bucket size
    speculative: SpeculativeConfig = dataclasses.field(
        default_factory=SpeculativeConfig)

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "ServingConfig":
        if not isinstance(raw, dict):
            raise ConfigError(f"serving must be a mapping, got {raw!r}")
        cfg = ServingConfig(
            max_batch=int(raw.get("max_batch", 8)),
            max_prefill_len=int(raw.get("max_prefill_len", 128)),
            kv_block_size=int(raw.get("kv_block_size", 16)),
            kv_blocks=int(raw.get("kv_blocks", 0)),
            max_queue_depth=int(raw.get("max_queue_depth", 64)),
            default_max_new_tokens=int(raw.get("default_max_new_tokens", 64)),
            host=str(raw.get("host", "127.0.0.1")),
            port=int(raw.get("port", 8191)),
            prefix_cache=bool(raw.get("prefix_cache", False)),
            chunk_prefill_len=int(raw.get("chunk_prefill_len", 0)),
            speculative=SpeculativeConfig.from_dict(
                raw.get("speculative", {})),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        for name, v in (("max_batch", self.max_batch),
                        ("max_prefill_len", self.max_prefill_len),
                        ("kv_block_size", self.kv_block_size)):
            if v < 1 or v & (v - 1):
                raise ConfigError(
                    f"serving.{name} must be a power of two >= 1, got {v}")
        if self.kv_blocks < 0:
            raise ConfigError(
                f"serving.kv_blocks must be >= 0 (0 = auto), "
                f"got {self.kv_blocks}")
        if self.max_queue_depth < 1:
            raise ConfigError(
                f"serving.max_queue_depth must be >= 1, "
                f"got {self.max_queue_depth}")
        if self.default_max_new_tokens < 1:
            raise ConfigError(
                f"serving.default_max_new_tokens must be >= 1, "
                f"got {self.default_max_new_tokens}")
        if not 0 < self.port < 65536:
            raise ConfigError(
                f"serving.port must be in (0, 65536), got {self.port}")
        if self.chunk_prefill_len < 0:
            raise ConfigError(
                f"serving.chunk_prefill_len must be >= 0 (0 = off), "
                f"got {self.chunk_prefill_len}")
        if self.chunk_prefill_len:
            v = self.chunk_prefill_len
            if (v & (v - 1) or v > self.max_prefill_len
                    or v < min(8, self.max_prefill_len)):
                raise ConfigError(
                    f"serving.chunk_prefill_len must be a power of two "
                    f"in [{min(8, self.max_prefill_len)}, "
                    f"{self.max_prefill_len}] (it must land on a prefill "
                    f"bucket), got {v}")
        self.speculative.validate()

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Fault injection (the `faults:` block) — a seeded, deterministic FaultPlan
# for chaos testing; see docs/fault_tolerance.md. No reference equivalent:
# the reference exercises failure paths with live clusters, we do it by seed.
# ---------------------------------------------------------------------------

_FAULT_ACTIONS = ("error", "delay", "truncate", "exit")
_FAULT_EXCS = ("fault", "io", "conn")


@dataclasses.dataclass
class FaultsConfig:
    enabled: bool = True
    seed: int = 0
    rules: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "FaultsConfig":
        if not isinstance(raw, dict):
            raise ConfigError(f"faults must be a mapping, got {raw!r}")
        cfg = FaultsConfig(
            enabled=bool(raw.get("enabled", True)),
            seed=int(raw.get("seed", 0)),
            rules=[dict(r) for r in raw.get("rules") or []],
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        for i, rule in enumerate(self.rules):
            if not isinstance(rule, dict) or not rule.get("point"):
                raise ConfigError(f"faults.rules[{i}] requires a `point`")
            action = rule.get("action", "error")
            if action not in _FAULT_ACTIONS:
                raise ConfigError(
                    f"faults.rules[{i}].action must be one of "
                    f"{_FAULT_ACTIONS}, got {action!r}")
            exc = rule.get("exc", "fault")
            if exc not in _FAULT_EXCS:
                raise ConfigError(
                    f"faults.rules[{i}].exc must be one of "
                    f"{_FAULT_EXCS}, got {exc!r}")
            prob = float(rule.get("probability", 1.0))
            if not 0.0 <= prob <= 1.0:
                raise ConfigError(
                    f"faults.rules[{i}].probability must be in [0, 1], "
                    f"got {prob}")

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"enabled": self.enabled, "seed": self.seed}
        if self.rules:
            d["rules"] = self.rules
        return d


# ---------------------------------------------------------------------------
# Log policies (reference: expconf log_policies → logpattern subsystem)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LogPolicy:
    pattern: str
    action: str = "exclude_node"  # exclude_node | cancel_retries

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "LogPolicy":
        if "pattern" not in raw:
            raise ConfigError("log policy requires a `pattern`")
        action = raw.get("action", "exclude_node")
        if isinstance(action, dict):  # reference's {"type": "..."} form
            action = action.get("type", "exclude_node")
        if action not in ("exclude_node", "cancel_retries"):
            raise ConfigError(f"unknown log policy action {action!r}")
        return LogPolicy(pattern=raw["pattern"], action=action)


# ---------------------------------------------------------------------------
# The experiment config itself
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ExperimentConfig:
    name: str = "unnamed-experiment"
    entrypoint: Optional[str] = None
    searcher: SearcherConfig = dataclasses.field(default_factory=SearcherConfig)
    resources: ResourcesConfig = dataclasses.field(default_factory=ResourcesConfig)
    hyperparameters: HyperparameterSpace = dataclasses.field(
        default_factory=HyperparameterSpace
    )
    checkpoint_storage: Optional[CheckpointStorageConfig] = None
    optimizations: OptimizationsConfig = dataclasses.field(
        default_factory=OptimizationsConfig
    )
    observability: ObservabilityConfig = dataclasses.field(
        default_factory=ObservabilityConfig
    )
    serving: Optional[ServingConfig] = None
    faults: Optional[FaultsConfig] = None
    checkpoint_policy: str = "best"     # best | all | none
    min_validation_period: Optional[Length] = None
    min_checkpoint_period: Optional[Length] = None
    perform_initial_validation: bool = False
    max_restarts: int = 5
    records_per_epoch: int = 0
    scheduling_unit: int = 100          # batches per searcher unit
    experiment_seed: int = 0            # reproducibility.experiment_seed
    labels: List[str] = dataclasses.field(default_factory=list)
    workspace: str = "Uncategorized"
    project: str = "Uncategorized"
    log_policies: List[LogPolicy] = dataclasses.field(default_factory=list)
    profiling_enabled: bool = False
    environment: Dict[str, Any] = dataclasses.field(default_factory=dict)
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)
    raw: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # what the v0->v1 shim rewrote (surfaced as deprecation notices)
    deprecations: List[str] = dataclasses.field(default_factory=list)

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "ExperimentConfig":
        if not isinstance(raw, dict):
            raise ConfigError(f"experiment config must be a mapping, got {type(raw).__name__}")
        # schema-first pipeline (≈ expconf parse.go): shim legacy (v0)
        # spellings to the current version, then validate against the
        # schema-as-data before the dataclass layer parses values
        from determined_clone_tpu.config import schema as schema_mod
        from determined_clone_tpu.config import shims

        try:
            raw, deprecations = shims.shim(raw)
        except ValueError as e:
            raise ConfigError(str(e)) from None
        errors = schema_mod.validate(raw)
        if errors:
            raise ConfigError("invalid experiment config:\n  " +
                              "\n  ".join(errors))
        profiling = raw.get("profiling", {})
        cfg = ExperimentConfig(
            name=raw.get("name", "unnamed-experiment"),
            entrypoint=raw.get("entrypoint"),
            searcher=SearcherConfig.from_dict(raw.get("searcher", {})),
            resources=ResourcesConfig.from_dict(raw.get("resources", {})),
            hyperparameters=HyperparameterSpace(raw.get("hyperparameters", {})),
            checkpoint_storage=(
                CheckpointStorageConfig.from_dict(raw["checkpoint_storage"])
                if raw.get("checkpoint_storage") else None
            ),
            optimizations=OptimizationsConfig.from_dict(
                raw.get("optimizations") or {}
            ),
            observability=ObservabilityConfig.from_dict(
                raw.get("observability") or {}
            ),
            serving=(ServingConfig.from_dict(raw["serving"])
                     if raw.get("serving") else None),
            faults=(FaultsConfig.from_dict(raw["faults"])
                    if raw.get("faults") else None),
            checkpoint_policy=raw.get("checkpoint_policy", "best"),
            min_validation_period=(
                Length.from_dict(raw["min_validation_period"])
                if "min_validation_period" in raw else None
            ),
            min_checkpoint_period=(
                Length.from_dict(raw["min_checkpoint_period"])
                if "min_checkpoint_period" in raw else None
            ),
            perform_initial_validation=bool(raw.get("perform_initial_validation", False)),
            max_restarts=int(raw.get("max_restarts", 5)),
            records_per_epoch=int(raw.get("records_per_epoch", 0)),
            scheduling_unit=int(raw.get("scheduling_unit", 100)),
            experiment_seed=int(
                (raw.get("reproducibility") or {}).get("experiment_seed", 0)
            ),
            labels=list(raw.get("labels", []) or []),
            workspace=raw.get("workspace", "Uncategorized"),
            project=raw.get("project", "Uncategorized"),
            log_policies=[LogPolicy.from_dict(p) for p in raw.get("log_policies", []) or []],
            profiling_enabled=bool(
                profiling.get("enabled", False) if isinstance(profiling, dict) else profiling
            ),
            environment=raw.get("environment", {}) or {},
            data=raw.get("data", {}) or {},
            raw=raw,
            deprecations=deprecations,
        )
        cfg.validate()
        return cfg

    @staticmethod
    def from_yaml(path: str) -> "ExperimentConfig":
        import yaml  # lazy; pyyaml ships with the baked-in stack

        with open(path) as f:
            return ExperimentConfig.from_dict(yaml.safe_load(f) or {})

    def validate(self) -> None:
        if self.checkpoint_policy not in ("best", "all", "none"):
            raise ConfigError(
                f"checkpoint_policy must be best|all|none, got {self.checkpoint_policy!r}"
            )
        if self.max_restarts < 0:
            raise ConfigError(f"max_restarts must be >= 0, got {self.max_restarts}")
        if self.scheduling_unit < 1:
            raise ConfigError(f"scheduling_unit must be >= 1, got {self.scheduling_unit}")
        if self.searcher.name == "grid" and self.hyperparameters.grid_size() == 0:
            # a grid over an empty space is a single trial; allowed, like the reference
            pass

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "searcher": self.searcher.to_dict(),
            "resources": self.resources.to_dict(),
            "hyperparameters": self.hyperparameters.raw,
            "checkpoint_policy": self.checkpoint_policy,
            "max_restarts": self.max_restarts,
            "records_per_epoch": self.records_per_epoch,
            "scheduling_unit": self.scheduling_unit,
            "reproducibility": {"experiment_seed": self.experiment_seed},
            "labels": self.labels,
            "workspace": self.workspace,
            "project": self.project,
        }
        if self.entrypoint:
            d["entrypoint"] = self.entrypoint
        if self.checkpoint_storage:
            d["checkpoint_storage"] = self.checkpoint_storage.to_dict()
        if self.optimizations != OptimizationsConfig():
            d["optimizations"] = self.optimizations.to_dict()
        if self.observability != ObservabilityConfig():
            d["observability"] = self.observability.to_dict()
        if self.serving is not None:
            d["serving"] = self.serving.to_dict()
        if self.faults is not None:
            d["faults"] = self.faults.to_dict()
        if self.min_validation_period:
            d["min_validation_period"] = self.min_validation_period.to_dict()
        if self.min_checkpoint_period:
            d["min_checkpoint_period"] = self.min_checkpoint_period.to_dict()
        if self.data:
            d["data"] = self.data
        return d


def merge_configs(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Template merging (reference: master/internal/templates + schemas.Merge):
    override wins per key; nested dicts merge recursively; lists replace."""
    out = dict(base)
    for k, v in override.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_configs(out[k], v)
        else:
            out[k] = v
    return out
