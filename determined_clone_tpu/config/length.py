"""Training-length units.

Equivalent of the reference's ``expconf.Length`` (master/pkg/schemas/expconf/length.go):
a quantity of training expressed in records, batches, or epochs. The trainer
resolves everything to batches given ``global_batch_size`` and
``records_per_epoch``.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, Union


class Unit(str, enum.Enum):
    RECORDS = "records"
    BATCHES = "batches"
    EPOCHS = "epochs"


@dataclasses.dataclass(frozen=True)
class Length:
    unit: Unit
    value: int

    def __post_init__(self) -> None:
        if self.value < 0:
            raise ValueError(f"Length value must be >= 0, got {self.value}")

    @staticmethod
    def records(value: int) -> "Length":
        return Length(Unit.RECORDS, value)

    @staticmethod
    def batches(value: int) -> "Length":
        return Length(Unit.BATCHES, value)

    @staticmethod
    def epochs(value: int) -> "Length":
        return Length(Unit.EPOCHS, value)

    @staticmethod
    def from_dict(d: Union[int, Dict[str, Any]]) -> "Length":
        """Parse ``{"batches": 100}`` / ``{"epochs": 2}`` / ``{"records": 5000}``.

        A bare int means batches (the reference's default ``scheduling_unit``
        semantics).
        """
        if isinstance(d, int):
            return Length.batches(d)
        if not isinstance(d, dict) or len(d) != 1:
            raise ValueError(
                f"a length must be an int or a single-key dict of "
                f"records/batches/epochs, got {d!r}"
            )
        (key, value), = d.items()
        try:
            unit = Unit(key)
        except ValueError:
            raise ValueError(f"unknown length unit {key!r}") from None
        if not isinstance(value, int):
            raise ValueError(f"length value must be an int, got {value!r}")
        return Length(unit, value)

    def to_dict(self) -> Dict[str, int]:
        return {self.unit.value: self.value}

    def to_batches(self, global_batch_size: int, records_per_epoch: int = 0) -> int:
        """Resolve to a batch count."""
        if self.unit == Unit.BATCHES:
            return self.value
        if self.unit == Unit.RECORDS:
            if global_batch_size <= 0:
                raise ValueError("global_batch_size must be positive to convert records")
            return max(1, self.value // global_batch_size)
        # epochs
        if records_per_epoch <= 0:
            raise ValueError(
                "records_per_epoch must be set in the experiment config to use "
                "epoch-based lengths"
            )
        if global_batch_size <= 0:
            raise ValueError("global_batch_size must be positive to convert epochs")
        return max(1, (self.value * records_per_epoch) // global_batch_size)

    def __str__(self) -> str:
        return f"{self.value} {self.unit.value}"
