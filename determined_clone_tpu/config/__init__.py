"""Experiment configuration (expconf equivalent — SURVEY.md §2.1, §5.6)."""
from determined_clone_tpu.config.experiment import (
    CheckpointStorageConfig,
    ConfigError,
    ExperimentConfig,
    LogPolicy,
    OptimizationsConfig,
    ResourcesConfig,
    SearcherConfig,
    merge_configs,
)
from determined_clone_tpu.config.hyperparameters import (
    Categorical,
    Const,
    Double,
    Hyperparameter,
    HyperparameterSpace,
    Int,
    Log,
    parse_hyperparameter,
)
from determined_clone_tpu.config.length import Length, Unit

__all__ = [
    "CheckpointStorageConfig",
    "ConfigError",
    "ExperimentConfig",
    "LogPolicy",
    "OptimizationsConfig",
    "ResourcesConfig",
    "SearcherConfig",
    "merge_configs",
    "Categorical",
    "Const",
    "Double",
    "Hyperparameter",
    "HyperparameterSpace",
    "Int",
    "Log",
    "parse_hyperparameter",
    "Length",
    "Unit",
]
