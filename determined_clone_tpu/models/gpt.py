"""GPT — the flagship decoder-only transformer family.

Capability target: the reference's DeepSpeed GPT trials
(examples/deepspeed/gpt_neox, BASELINE.md "DeepSpeed GPT ZeRO-2 → pjit
FSDP-style sharding") re-designed TPU-first:

 - params are a pytree with **stacked blocks** ([L, ...] leading layer dim)
   walked by ``lax.scan`` — one compiled block body regardless of depth
   (fast XLA compiles, natural pipeline-stage slicing later);
 - bf16 activations/compute, fp32 params & softmax;
 - megatron TP sharding expressed as regex→PartitionSpec rules
   (parallel/sharding.py), fsdp fallback = ZeRO-3;
 - sequence axis ready for ring attention over the ``sp`` mesh axis;
 - ``jax.checkpoint`` (remat) around each block to trade FLOPs for HBM.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from determined_clone_tpu.ops.attention import (
    causal_blockwise_attention,
    mha,
    rotary_embedding,
)
from determined_clone_tpu.ops.layers import (
    dense,
    dense_init,
    dropout,
    embedding_init,
    layernorm,
    layernorm_init,
    softmax_cross_entropy,
    trunc_normal,
)
from determined_clone_tpu.ops.moe import moe_ffn
from determined_clone_tpu.parallel.sharding import ShardingRules

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # gpt-neox vocab, padded to a multiple of 128
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 2048
    dropout: float = 0.0
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # attention implementation: "auto" (flash on TPU, mha elsewhere),
    # "mha" (plain XLA), "blockwise" (streaming scan for long seqs),
    # "flash" (fused Pallas TPU kernel). TPU-first means the fused kernel
    # is the default on TPU hardware with an explicit opt-out; off-TPU the
    # kernel would run in slow interpret mode, so auto picks plain XLA.
    # The legacy blockwise_attention flag still selects "blockwise".
    attention_impl: str = "auto"
    blockwise_attention: bool = False
    attention_block_size: int = 512
    tie_embeddings: bool = True
    # MoE (expert parallel over the ep mesh axis; 0 = dense FFN).
    moe_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # GPipe pipeline over the pp mesh axis (0/1 = no pipelining). Takes
    # effect when apply/loss_fn receive a mesh whose pp axis is > 1.
    pipeline_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(vocab_size=256, n_layers=2, d_model=64, n_heads=4,
                         d_ff=128, max_seq_len=128, remat=False)


def resolved_attention_impl(cfg: GPTConfig) -> str:
    """The concrete attention kernel ``cfg`` selects on this backend.

    "auto" resolves per backend at trace time (``jax.default_backend()``
    is static under jit): the fused Pallas kernel on TPU, plain XLA
    attention elsewhere. Exposed so tests and benchmarks can assert which
    path a config actually takes — a silent fall-off the fast path is a
    perf regression, not an implementation detail.
    """
    impl = "blockwise" if cfg.blockwise_attention else cfg.attention_impl
    if impl == "auto":
        return "flash" if jax.default_backend() == "tpu" else "mha"
    if impl not in ("mha", "blockwise", "flash"):
        raise ValueError(
            f"unknown attention_impl {impl!r}; "
            f"expected auto|mha|blockwise|flash")
    return impl


# Megatron-style TP rules + explicit fsdp specs. Column-parallel up-projections
# shard the output dim on tp; row-parallel down-projections shard the input dim
# (XLA inserts the all-reduce the megatron pattern implies). Stacked block
# leaves have a leading [L] layer dim; with ``pipelined=True`` that dim is
# sliced over the pp axis (one contiguous run of layers per stage).
def sharding_rules(pipelined: bool = False) -> ShardingRules:
    lead = "pp" if pipelined else None
    return ShardingRules(rules=[
        (r"embed/table$",            P("tp", "fsdp")),       # [V, D] vocab-parallel
        (r"blocks/.*attn_qkv/kernel$",  P(lead, "fsdp", "tp")),  # [L, D, 3D] column
        (r"blocks/.*attn_out/kernel$",  P(lead, "tp", "fsdp")),  # [L, D, D]  row
        (r"blocks/.*mlp_up/kernel$",    P(lead, "fsdp", "tp")),  # [L, D, F]  column
        (r"blocks/.*mlp_down/kernel$",  P(lead, "tp", "fsdp")),  # [L, F, D]  row
        (r"blocks/moe/router/kernel$",  P(lead)),               # [L, D, E] small
        (r"blocks/moe/up/kernel$",      P(lead, "ep", "fsdp", "tp")),   # [L,E,D,F]
        (r"blocks/moe/down/kernel$",    P(lead, "ep", "tp", "fsdp")),   # [L,E,F,D]
        (r"blocks/moe/.*bias$",         P(lead, "ep")),         # [L, E, ·]
        (r"blocks/.*(bias|scale)$",     P(lead)),
        (r"lm_head/kernel$",         P("fsdp", "tp")),       # [D, V]
        (r"final_norm/",             P()),
    ])


GPT_SHARDING_RULES = sharding_rules(pipelined=False)
GPT_PP_SHARDING_RULES = sharding_rules(pipelined=True)

# Activation specs: batch over (dp, fsdp), sequence over sp, heads/features over tp.
TOKENS_SPEC = P(("dp", "fsdp"), "sp")
ACTIVATION_SPEC = P(("dp", "fsdp"), "sp", "tp")


def init(key: jax.Array, cfg: GPTConfig) -> Params:
    """Initialize stacked-block GPT params."""
    keys = jax.random.split(key, 8)
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    dt = cfg.param_dtype

    def stacked(k, shape, stddev=0.02):
        return trunc_normal(k, (L, *shape), stddev=stddev, dtype=dt)

    blocks: Params = {
        "ln1": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
        "attn_qkv": {"kernel": stacked(keys[1], (D, 3 * D)),
                     "bias": jnp.zeros((L, 3 * D), dt)},
        "attn_out": {"kernel": stacked(keys[2], (D, D),
                                       stddev=0.02 / (2 * L) ** 0.5),
                     "bias": jnp.zeros((L, D), dt)},
        "ln2": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
    }
    if cfg.moe_experts > 0:
        from determined_clone_tpu.ops.moe import moe_init

        blocks["moe"] = jax.vmap(
            lambda k: moe_init(k, cfg.moe_experts, D, F, dtype=dt,
                               out_stddev=0.02 / (2 * L) ** 0.5)
        )(jax.random.split(keys[3], L))
    else:
        blocks["mlp_up"] = {"kernel": stacked(keys[3], (D, F)),
                            "bias": jnp.zeros((L, F), dt)}
        blocks["mlp_down"] = {"kernel": stacked(keys[4], (F, D),
                                                stddev=0.02 / (2 * L) ** 0.5),
                              "bias": jnp.zeros((L, D), dt)}
    params: Params = {
        "embed": embedding_init(keys[0], cfg.vocab_size, D, dtype=dt),
        "blocks": blocks,
        "final_norm": layernorm_init(D, dtype=dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(keys[5], D, cfg.vocab_size, bias=False, dtype=dt)
    return params


def _block(cfg: GPTConfig, block_params: Params, x: jax.Array,
           positions: jax.Array, dropout_key: Optional[jax.Array]):
    """One pre-LN transformer block. x: [B, T, D] in compute dtype.
    Returns (x, aux) — aux is the MoE load-balancing loss (0 for dense)."""
    B, T, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    k_attn = k_mlp = None
    if dropout_key is not None:
        k_attn, k_mlp = jax.random.split(dropout_key)

    h = layernorm(block_params["ln1"], x)
    qkv = dense(block_params["attn_qkv"], h, compute_dtype=cfg.compute_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = rotary_embedding(q.reshape(B, T, H, hd), positions)
    k = rotary_embedding(k.reshape(B, T, H, hd), positions)
    v = v.reshape(B, T, H, hd)
    impl = resolved_attention_impl(cfg)
    if impl == "blockwise":
        attn = causal_blockwise_attention(q, k, v, block_size=cfg.attention_block_size)
    elif impl == "flash":
        from determined_clone_tpu.ops.flash_attention import flash_attention

        blk = min(cfg.attention_block_size, 128)
        # the kernel tiles T into blk-sized blocks; pad indivisible T (the
        # everyday case: loss_fn slices tokens[:, :-1]) and slice back.
        # Safe because attention is causal: real queries only ever see
        # real keys (i < T), and padded rows are discarded.
        pad = -T % blk
        if pad:
            q, k, v = (jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0)))
                       for t in (q, k, v))
        attn = flash_attention(q, k, v, causal=True, block_q=blk,
                               block_k=blk)
        if pad:
            attn = attn[:, :T]
    else:
        attn = mha(q, k, v, causal=True)
    attn = dense(block_params["attn_out"], attn.reshape(B, T, D),
                 compute_dtype=cfg.compute_dtype)
    x = x + dropout(k_attn, attn, cfg.dropout, training=k_attn is not None)

    h = layernorm(block_params["ln2"], x)
    if cfg.moe_experts > 0:
        h, aux = moe_ffn(block_params["moe"], h, k=cfg.moe_k,
                         capacity_factor=cfg.moe_capacity_factor,
                         compute_dtype=cfg.compute_dtype)
    else:
        h = dense(block_params["mlp_up"], h, compute_dtype=cfg.compute_dtype)
        h = jax.nn.gelu(h, approximate=True)
        h = dense(block_params["mlp_down"], h, compute_dtype=cfg.compute_dtype)
        aux = jnp.zeros((), jnp.float32)
    x = x + dropout(k_mlp, h, cfg.dropout, training=k_mlp is not None)
    return x, aux


def _forward(params: Params, cfg: GPTConfig, tokens: jax.Array, *,
             training: bool = False,
             dropout_key: Optional[jax.Array] = None,
             mesh: Optional[Any] = None):
    """Forward pass → (logits [B, T, V] fp32, aux scalar). tokens: int32 [B, T].

    Dropout is active only when ``training`` and ``dropout_key`` are given and
    ``cfg.dropout > 0``; per-layer keys are split outside the scan.

    With a mesh whose ``pp`` axis is > 1 and ``cfg.pipeline_microbatches > 1``,
    the block stack runs as a GPipe pipeline (parallel/pipeline.py): layers are
    sliced over pp, activations rotate the stage ring. (In that mode per-layer
    dropout keys are shared across microbatches — masks repeat across
    microbatches of one step; statistically harmless.)
    """
    B, T = tokens.shape
    positions = jnp.arange(T)
    x = jnp.take(params["embed"]["table"], tokens, axis=0).astype(cfg.compute_dtype)

    use_dropout = training and dropout_key is not None and cfg.dropout > 0.0
    layer_keys = (
        jax.random.split(dropout_key, cfg.n_layers) if use_dropout else None
    )

    def block_fn(layer_params, x, key):
        return _block(cfg, layer_params, x, positions, key)
    if cfg.remat:
        block_fn = jax.checkpoint(
            block_fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )

    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    if pp > 1 and cfg.pipeline_microbatches > 1:
        from determined_clone_tpu.parallel.pipeline import pipeline_apply

        M = cfg.pipeline_microbatches
        stacked: Params = {"blocks": params["blocks"]}
        if layer_keys is not None:
            stacked["keys"] = layer_keys

        def stage_fn(local, carrier):
            has_keys = "keys" in local
            xs = (local["blocks"], local["keys"]) if has_keys else local["blocks"]

            def body(carry, inp):
                h, aux = carry
                lp, key = inp if has_keys else (inp, None)
                h, a = block_fn(lp, h, key)
                # Spread the scalar aux over the microbatch's batch rows so the
                # carrier keeps its [mb] shape; summing recovers the total.
                return (h, aux + a / h.shape[0]), None

            (h, aux), _ = jax.lax.scan(body, (carrier["x"], carrier["aux"]), xs)
            return {"x": h, "aux": aux}

        carrier = {"x": x, "aux": jnp.zeros((B,), jnp.float32)}
        out = pipeline_apply(stage_fn, stacked, carrier, mesh=mesh,
                             num_microbatches=M)
        x = out["x"]
        aux_total = jnp.sum(out["aux"]) / M  # mean over microbatches
    elif layer_keys is not None:
        def scan_body(x, inputs):
            layer_params, key = inputs
            x, aux = block_fn(layer_params, x, key)
            return x, aux
        x, aux_stack = jax.lax.scan(scan_body, x, (params["blocks"], layer_keys))
        aux_total = jnp.sum(aux_stack)
    else:
        def scan_body(x, layer_params):
            x, aux = block_fn(layer_params, x, None)
            return x, aux
        x, aux_stack = jax.lax.scan(scan_body, x, params["blocks"])
        aux_total = jnp.sum(aux_stack)

    x = layernorm(params["final_norm"], x)
    if cfg.tie_embeddings:
        logits = x.astype(jnp.float32) @ params["embed"]["table"].astype(jnp.float32).T
    else:
        logits = dense(params["lm_head"], x, compute_dtype=jnp.float32)
    return logits.astype(jnp.float32), aux_total


def apply(params: Params, cfg: GPTConfig, tokens: jax.Array, *,
          training: bool = False,
          dropout_key: Optional[jax.Array] = None,
          mesh: Optional[Any] = None) -> jax.Array:
    """Forward pass → logits [B, T, V] (fp32); see ``_forward``."""
    logits, _ = _forward(params, cfg, tokens, training=training,
                         dropout_key=dropout_key, mesh=mesh)
    return logits


def loss_fn(params: Params, cfg: GPTConfig, tokens: jax.Array,
            targets: jax.Array, mask: Optional[jax.Array] = None, *,
            training: bool = False,
            dropout_key: Optional[jax.Array] = None,
            mesh: Optional[Any] = None) -> jax.Array:
    """Mean next-token cross-entropy (+ MoE aux loss). targets/mask: [B, T]."""
    logits, aux = _forward(params, cfg, tokens, training=training,
                           dropout_key=dropout_key, mesh=mesh)
    per_tok = softmax_cross_entropy(logits, targets)
    if mask is not None:
        maskf = mask.astype(jnp.float32)
        ce = jnp.sum(per_tok * maskf) / jnp.maximum(jnp.sum(maskf), 1.0)
    else:
        ce = jnp.mean(per_tok)
    if cfg.moe_experts > 0:
        ce = ce + cfg.moe_aux_weight * aux
    return ce


def param_count(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))
