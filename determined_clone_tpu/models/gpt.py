"""GPT — the flagship decoder-only transformer family.

Capability target: the reference's DeepSpeed GPT trials
(examples/deepspeed/gpt_neox, BASELINE.md "DeepSpeed GPT ZeRO-2 → pjit
FSDP-style sharding") re-designed TPU-first:

 - params are a pytree with **stacked blocks** ([L, ...] leading layer dim)
   walked by ``lax.scan`` — one compiled block body regardless of depth
   (fast XLA compiles, natural pipeline-stage slicing later);
 - bf16 activations/compute, fp32 params & softmax;
 - megatron TP sharding expressed as regex→PartitionSpec rules
   (parallel/sharding.py), fsdp fallback = ZeRO-3;
 - sequence axis ready for ring attention over the ``sp`` mesh axis;
 - ``jax.checkpoint`` (remat) around each block to trade FLOPs for HBM.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from determined_clone_tpu.ops.attention import (
    causal_blockwise_attention,
    mha,
    rotary_embedding,
)
from determined_clone_tpu.ops.layers import (
    dense,
    dense_init,
    dropout,
    embedding_init,
    layernorm,
    layernorm_init,
    softmax_cross_entropy,
    trunc_normal,
)
from determined_clone_tpu.ops.moe import moe_ffn
from determined_clone_tpu.parallel.sharding import ShardingRules

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # gpt-neox vocab, padded to a multiple of 128
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 2048
    dropout: float = 0.0
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # attention implementation: "auto" (flash on TPU, mha elsewhere),
    # "mha" (plain XLA), "blockwise" (streaming scan for long seqs),
    # "flash" (fused Pallas TPU kernel). TPU-first means the fused kernel
    # is the default on TPU hardware with an explicit opt-out; off-TPU the
    # kernel would run in slow interpret mode, so auto picks plain XLA.
    # The legacy blockwise_attention flag still selects "blockwise".
    attention_impl: str = "auto"
    blockwise_attention: bool = False
    attention_block_size: int = 512
    tie_embeddings: bool = True
    # MoE (expert parallel over the ep mesh axis; 0 = dense FFN).
    moe_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # GPipe pipeline over the pp mesh axis (0/1 = no pipelining). Takes
    # effect when apply/loss_fn receive a mesh whose pp axis is > 1.
    pipeline_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(vocab_size=256, n_layers=2, d_model=64, n_heads=4,
                         d_ff=128, max_seq_len=128, remat=False)


def resolved_attention_impl(cfg: GPTConfig) -> str:
    """The concrete attention kernel ``cfg`` selects on this backend.

    "auto" resolves per backend at trace time (``jax.default_backend()``
    is static under jit): the fused Pallas kernel on TPU, plain XLA
    attention elsewhere. Exposed so tests and benchmarks can assert which
    path a config actually takes — a silent fall-off the fast path is a
    perf regression, not an implementation detail.
    """
    impl = "blockwise" if cfg.blockwise_attention else cfg.attention_impl
    if impl == "auto":
        return "flash" if jax.default_backend() == "tpu" else "mha"
    if impl not in ("mha", "blockwise", "flash"):
        raise ValueError(
            f"unknown attention_impl {impl!r}; "
            f"expected auto|mha|blockwise|flash")
    return impl


# Megatron-style TP rules + explicit fsdp specs. Column-parallel up-projections
# shard the output dim on tp; row-parallel down-projections shard the input dim
# (XLA inserts the all-reduce the megatron pattern implies). Stacked block
# leaves have a leading [L] layer dim; with ``pipelined=True`` that dim is
# sliced over the pp axis (one contiguous run of layers per stage).
def sharding_rules(pipelined: bool = False) -> ShardingRules:
    lead = "pp" if pipelined else None
    return ShardingRules(rules=[
        (r"embed/table$",            P("tp", "fsdp")),       # [V, D] vocab-parallel
        (r"blocks/.*attn_qkv/kernel$",  P(lead, "fsdp", "tp")),  # [L, D, 3D] column
        (r"blocks/.*attn_out/kernel$",  P(lead, "tp", "fsdp")),  # [L, D, D]  row
        (r"blocks/.*mlp_up/kernel$",    P(lead, "fsdp", "tp")),  # [L, D, F]  column
        (r"blocks/.*mlp_down/kernel$",  P(lead, "tp", "fsdp")),  # [L, F, D]  row
        (r"blocks/moe/router/kernel$",  P(lead)),               # [L, D, E] small
        (r"blocks/moe/up/kernel$",      P(lead, "ep", "fsdp", "tp")),   # [L,E,D,F]
        (r"blocks/moe/down/kernel$",    P(lead, "ep", "tp", "fsdp")),   # [L,E,F,D]
        (r"blocks/moe/.*bias$",         P(lead, "ep")),         # [L, E, ·]
        (r"blocks/.*(bias|scale)$",     P(lead)),
        (r"lm_head/kernel$",         P("fsdp", "tp")),       # [D, V]
        (r"final_norm/",             P()),
    ])


GPT_SHARDING_RULES = sharding_rules(pipelined=False)
GPT_PP_SHARDING_RULES = sharding_rules(pipelined=True)

# Activation specs: batch over (dp, fsdp), sequence over sp, heads/features over tp.
TOKENS_SPEC = P(("dp", "fsdp"), "sp")
ACTIVATION_SPEC = P(("dp", "fsdp"), "sp", "tp")


def init(key: jax.Array, cfg: GPTConfig) -> Params:
    """Initialize stacked-block GPT params."""
    keys = jax.random.split(key, 8)
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    dt = cfg.param_dtype

    def stacked(k, shape, stddev=0.02):
        return trunc_normal(k, (L, *shape), stddev=stddev, dtype=dt)

    blocks: Params = {
        "ln1": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
        "attn_qkv": {"kernel": stacked(keys[1], (D, 3 * D)),
                     "bias": jnp.zeros((L, 3 * D), dt)},
        "attn_out": {"kernel": stacked(keys[2], (D, D),
                                       stddev=0.02 / (2 * L) ** 0.5),
                     "bias": jnp.zeros((L, D), dt)},
        "ln2": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
    }
    if cfg.moe_experts > 0:
        from determined_clone_tpu.ops.moe import moe_init

        blocks["moe"] = jax.vmap(
            lambda k: moe_init(k, cfg.moe_experts, D, F, dtype=dt,
                               out_stddev=0.02 / (2 * L) ** 0.5)
        )(jax.random.split(keys[3], L))
    else:
        blocks["mlp_up"] = {"kernel": stacked(keys[3], (D, F)),
                            "bias": jnp.zeros((L, F), dt)}
        blocks["mlp_down"] = {"kernel": stacked(keys[4], (F, D),
                                                stddev=0.02 / (2 * L) ** 0.5),
                              "bias": jnp.zeros((L, D), dt)}
    params: Params = {
        "embed": embedding_init(keys[0], cfg.vocab_size, D, dtype=dt),
        "blocks": blocks,
        "final_norm": layernorm_init(D, dtype=dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(keys[5], D, cfg.vocab_size, bias=False, dtype=dt)
    return params


def _block(cfg: GPTConfig, block_params: Params, x: jax.Array,
           positions: jax.Array, dropout_key: Optional[jax.Array]):
    """One pre-LN transformer block. x: [B, T, D] in compute dtype.
    Returns (x, aux) — aux is the MoE load-balancing loss (0 for dense)."""
    B, T, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    k_attn = k_mlp = None
    if dropout_key is not None:
        k_attn, k_mlp = jax.random.split(dropout_key)

    h = layernorm(block_params["ln1"], x)
    qkv = dense(block_params["attn_qkv"], h, compute_dtype=cfg.compute_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = rotary_embedding(q.reshape(B, T, H, hd), positions)
    k = rotary_embedding(k.reshape(B, T, H, hd), positions)
    v = v.reshape(B, T, H, hd)
    impl = resolved_attention_impl(cfg)
    if impl == "blockwise":
        attn = causal_blockwise_attention(q, k, v, block_size=cfg.attention_block_size)
    elif impl == "flash":
        from determined_clone_tpu.ops.flash_attention import flash_attention

        blk = min(cfg.attention_block_size, 128)
        # the kernel tiles T into blk-sized blocks; pad indivisible T (the
        # everyday case: loss_fn slices tokens[:, :-1]) and slice back.
        # Safe because attention is causal: real queries only ever see
        # real keys (i < T), and padded rows are discarded.
        pad = -T % blk
        if pad:
            q, k, v = (jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0)))
                       for t in (q, k, v))
        attn = flash_attention(q, k, v, causal=True, block_q=blk,
                               block_k=blk)
        if pad:
            attn = attn[:, :T]
    else:
        attn = mha(q, k, v, causal=True)
    attn = dense(block_params["attn_out"], attn.reshape(B, T, D),
                 compute_dtype=cfg.compute_dtype)
    x = x + dropout(k_attn, attn, cfg.dropout, training=k_attn is not None)

    h = layernorm(block_params["ln2"], x)
    if cfg.moe_experts > 0:
        h, aux = moe_ffn(block_params["moe"], h, k=cfg.moe_k,
                         capacity_factor=cfg.moe_capacity_factor,
                         compute_dtype=cfg.compute_dtype)
    else:
        h = dense(block_params["mlp_up"], h, compute_dtype=cfg.compute_dtype)
        h = jax.nn.gelu(h, approximate=True)
        h = dense(block_params["mlp_down"], h, compute_dtype=cfg.compute_dtype)
        aux = jnp.zeros((), jnp.float32)
    x = x + dropout(k_mlp, h, cfg.dropout, training=k_mlp is not None)
    return x, aux


def _forward(params: Params, cfg: GPTConfig, tokens: jax.Array, *,
             training: bool = False,
             dropout_key: Optional[jax.Array] = None,
             mesh: Optional[Any] = None):
    """Forward pass → (logits [B, T, V] fp32, aux scalar). tokens: int32 [B, T].

    Dropout is active only when ``training`` and ``dropout_key`` are given and
    ``cfg.dropout > 0``; per-layer keys are split outside the scan.

    With a mesh whose ``pp`` axis is > 1 and ``cfg.pipeline_microbatches > 1``,
    the block stack runs as a GPipe pipeline (parallel/pipeline.py): layers are
    sliced over pp, activations rotate the stage ring. (In that mode per-layer
    dropout keys are shared across microbatches — masks repeat across
    microbatches of one step; statistically harmless.)
    """
    B, T = tokens.shape
    positions = jnp.arange(T)
    x = jnp.take(params["embed"]["table"], tokens, axis=0).astype(cfg.compute_dtype)

    use_dropout = training and dropout_key is not None and cfg.dropout > 0.0
    layer_keys = (
        jax.random.split(dropout_key, cfg.n_layers) if use_dropout else None
    )

    def block_fn(layer_params, x, key):
        return _block(cfg, layer_params, x, positions, key)
    if cfg.remat:
        block_fn = jax.checkpoint(
            block_fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )

    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    if pp > 1 and cfg.pipeline_microbatches > 1:
        from determined_clone_tpu.parallel.pipeline import pipeline_apply

        M = cfg.pipeline_microbatches
        stacked: Params = {"blocks": params["blocks"]}
        if layer_keys is not None:
            stacked["keys"] = layer_keys

        def stage_fn(local, carrier):
            has_keys = "keys" in local
            xs = (local["blocks"], local["keys"]) if has_keys else local["blocks"]

            def body(carry, inp):
                h, aux = carry
                lp, key = inp if has_keys else (inp, None)
                h, a = block_fn(lp, h, key)
                # Spread the scalar aux over the microbatch's batch rows so the
                # carrier keeps its [mb] shape; summing recovers the total.
                return (h, aux + a / h.shape[0]), None

            (h, aux), _ = jax.lax.scan(body, (carrier["x"], carrier["aux"]), xs)
            return {"x": h, "aux": aux}

        carrier = {"x": x, "aux": jnp.zeros((B,), jnp.float32)}
        out = pipeline_apply(stage_fn, stacked, carrier, mesh=mesh,
                             num_microbatches=M)
        x = out["x"]
        aux_total = jnp.sum(out["aux"]) / M  # mean over microbatches
    elif layer_keys is not None:
        def scan_body(x, inputs):
            layer_params, key = inputs
            x, aux = block_fn(layer_params, x, key)
            return x, aux
        x, aux_stack = jax.lax.scan(scan_body, x, (params["blocks"], layer_keys))
        aux_total = jnp.sum(aux_stack)
    else:
        def scan_body(x, layer_params):
            x, aux = block_fn(layer_params, x, None)
            return x, aux
        x, aux_stack = jax.lax.scan(scan_body, x, params["blocks"])
        aux_total = jnp.sum(aux_stack)

    x = layernorm(params["final_norm"], x)
    if cfg.tie_embeddings:
        logits = x.astype(jnp.float32) @ params["embed"]["table"].astype(jnp.float32).T
    else:
        logits = dense(params["lm_head"], x, compute_dtype=jnp.float32)
    return logits.astype(jnp.float32), aux_total


def apply(params: Params, cfg: GPTConfig, tokens: jax.Array, *,
          training: bool = False,
          dropout_key: Optional[jax.Array] = None,
          mesh: Optional[Any] = None) -> jax.Array:
    """Forward pass → logits [B, T, V] (fp32); see ``_forward``."""
    logits, _ = _forward(params, cfg, tokens, training=training,
                         dropout_key=dropout_key, mesh=mesh)
    return logits


def loss_fn(params: Params, cfg: GPTConfig, tokens: jax.Array,
            targets: jax.Array, mask: Optional[jax.Array] = None, *,
            training: bool = False,
            dropout_key: Optional[jax.Array] = None,
            mesh: Optional[Any] = None) -> jax.Array:
    """Mean next-token cross-entropy (+ MoE aux loss). targets/mask: [B, T]."""
    logits, aux = _forward(params, cfg, tokens, training=training,
                           dropout_key=dropout_key, mesh=mesh)
    per_tok = softmax_cross_entropy(logits, targets)
    if mask is not None:
        maskf = mask.astype(jnp.float32)
        ce = jnp.sum(per_tok * maskf) / jnp.maximum(jnp.sum(maskf), 1.0)
    else:
        ce = jnp.mean(per_tok)
    if cfg.moe_experts > 0:
        ce = ce + cfg.moe_aux_weight * aux
    return ce


def _block_paged(cfg: GPTConfig, block_params: Params, x: jax.Array,
                 positions: jax.Array, k_pool_l: jax.Array,
                 v_pool_l: jax.Array, scatter_idx: jax.Array,
                 gather_idx: jax.Array, attn_mask: jax.Array):
    """One pre-LN block on the paged-KV serving path.

    x: [B, T, D] new tokens only (prefill: the prompt; decode: T=1).
    k_pool_l/v_pool_l: [N, bs, H, hd] — this layer's slice of the paged
    pool. The new tokens' K/V are scattered into the pool at
    ``scatter_idx`` ([B*T] flat slot ids, out-of-range = padding →
    dropped), then attention gathers the full paged context back via
    ``gather_idx`` ([B, S] flat slot ids) under ``attn_mask``
    ([B, 1, T, S]). Two sequences never share a pool block, so the
    scatter indices are collision-free by construction.

    Returns (x, k_pool_l, v_pool_l) — the same block math as ``_block``
    (dense or MoE FFN), minus dropout (inference) and remat.
    """
    B, T, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    N, bs = k_pool_l.shape[0], k_pool_l.shape[1]

    h = layernorm(block_params["ln1"], x)
    qkv = dense(block_params["attn_qkv"], h, compute_dtype=cfg.compute_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = rotary_embedding(q.reshape(B, T, H, hd), positions)
    k = rotary_embedding(k.reshape(B, T, H, hd), positions)
    v = v.reshape(B, T, H, hd)

    k_flat = k_pool_l.reshape(N * bs, H, hd)
    v_flat = v_pool_l.reshape(N * bs, H, hd)
    k_flat = k_flat.at[scatter_idx].set(k.reshape(B * T, H, hd), mode="drop")
    v_flat = v_flat.at[scatter_idx].set(v.reshape(B * T, H, hd), mode="drop")
    # gather the whole paged context: [B, S, H, hd]; slot j of the gathered
    # context is sequence position j (block tables map contiguously)
    ctx_k = k_flat[gather_idx]
    ctx_v = v_flat[gather_idx]
    attn = mha(q, ctx_k, ctx_v, causal=False, mask=attn_mask)
    attn = dense(block_params["attn_out"], attn.reshape(B, T, D),
                 compute_dtype=cfg.compute_dtype)
    x = x + attn

    h = layernorm(block_params["ln2"], x)
    if cfg.moe_experts > 0:
        h, _ = moe_ffn(block_params["moe"], h, k=cfg.moe_k,
                       capacity_factor=cfg.moe_capacity_factor,
                       compute_dtype=cfg.compute_dtype)
    else:
        h = dense(block_params["mlp_up"], h, compute_dtype=cfg.compute_dtype)
        h = jax.nn.gelu(h, approximate=True)
        h = dense(block_params["mlp_down"], h, compute_dtype=cfg.compute_dtype)
    x = x + h
    return x, k_flat.reshape(N, bs, H, hd), v_flat.reshape(N, bs, H, hd)


def _paged_backbone(params: Params, cfg: GPTConfig, tokens: jax.Array,
                    positions: jax.Array, token_mask: jax.Array,
                    k_pool: jax.Array, v_pool: jax.Array,
                    block_tables: jax.Array):
    """Embed → paged transformer stack → final layernorm.

    The shared core of :func:`forward_paged` (last-token readout, the
    prefill/decode workhorse) and :func:`forward_paged_logits` (all-token
    readout, the speculative-verify workhorse). Returns
    ``(x [B, T, D] normed, k_pool, v_pool)``.
    """
    B, T = tokens.shape
    N, bs = k_pool.shape[1], k_pool.shape[2]
    W = block_tables.shape[1]
    S = W * bs

    # scatter slots for the new tokens: pool block backing position p is
    # block_tables[b, p // bs]; padding tokens get an out-of-range slot so
    # .at[].set(mode="drop") discards them
    blk = jnp.take_along_axis(block_tables, positions // bs, axis=1)
    scatter_idx = jnp.where(token_mask, blk * bs + positions % bs,
                            N * bs).reshape(B * T)
    gather_idx = (block_tables[:, :, None] * bs
                  + jnp.arange(bs)[None, None, :]).reshape(B, S)
    # context slot j == sequence position j: causal = "j <= my position"
    attn_mask = (jnp.arange(S)[None, None, :] <= positions[:, :, None]
                 ) & token_mask[:, :, None]
    attn_mask = attn_mask[:, None]  # [B, 1, T, S] broadcast over heads

    x = jnp.take(params["embed"]["table"], tokens,
                 axis=0).astype(cfg.compute_dtype)

    def scan_body(x, layer_in):
        layer_params, k_l, v_l = layer_in
        x, k_l, v_l = _block_paged(cfg, layer_params, x, positions, k_l,
                                   v_l, scatter_idx, gather_idx, attn_mask)
        return x, (k_l, v_l)

    x, (k_pool, v_pool) = jax.lax.scan(
        scan_body, x, (params["blocks"], k_pool, v_pool))

    return layernorm(params["final_norm"], x), k_pool, v_pool


def forward_paged(params: Params, cfg: GPTConfig, tokens: jax.Array,
                  positions: jax.Array, token_mask: jax.Array,
                  last_index: jax.Array, k_pool: jax.Array,
                  v_pool: jax.Array, block_tables: jax.Array):
    """KV-cache-aware forward for online serving (paged attention).

    ONE function covers both halves of the prefill/decode split — the
    serving engine jits it once and XLA compiles one program per
    (batch-bucket, length-bucket) shape:

    - **prefill**: ``tokens`` is the bucketed-padded prompt ([B, T]),
      every prompt token's K/V is written into the pool, and the returned
      logits are each row's *last real token* (→ first sampled token);
    - **decode**: ``T == 1`` — one new token per running sequence is
      appended to the pool and attends to its full paged context.

    Args:
      tokens:     int32 [B, T] new token ids.
      positions:  int32 [B, T] absolute sequence positions of ``tokens``.
      token_mask: bool  [B, T] — False marks batch/length padding; padded
                  tokens are neither written to the pool nor attended to.
      last_index: int32 [B] — index into T of each row's last real token
                  (prefill: prompt_len-1; decode: 0).
      k_pool/v_pool: [L, N, block, H, hd] paged pools. Callers jitting
                  this should donate both (the pool is updated in place).
      block_tables: int32 [B, W] pool block ids per sequence; entry w
                  backs sequence positions [w*block, (w+1)*block). Padding
                  entries may hold any valid id — they are never written
                  (mask) and reads of them are masked out of attention.

    Returns ``(logits [B, V] fp32, k_pool, v_pool)``.

    Numerics match :func:`apply` (same dtypes, fp32 softmax/logits): a
    greedy decode through this path is token-identical to re-running the
    full uncached forward each step — tests/test_serving.py asserts it.
    """
    x, k_pool, v_pool = _paged_backbone(params, cfg, tokens, positions,
                                        token_mask, k_pool, v_pool,
                                        block_tables)
    h_last = jnp.take_along_axis(
        x, last_index[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    if cfg.tie_embeddings:
        logits = (h_last.astype(jnp.float32)
                  @ params["embed"]["table"].astype(jnp.float32).T)
    else:
        logits = dense(params["lm_head"], h_last, compute_dtype=jnp.float32)
    return logits.astype(jnp.float32), k_pool, v_pool


def forward_paged_logits(params: Params, cfg: GPTConfig, tokens: jax.Array,
                         positions: jax.Array, token_mask: jax.Array,
                         k_pool: jax.Array, v_pool: jax.Array,
                         block_tables: jax.Array):
    """Multi-token paged forward returning logits at *every* position.

    The speculative-decoding verify step (docs/serving.md): the target
    model scores ``[last committed token, draft_1 .. draft_k]`` in one
    call — ``T == k + 1`` — and the engine accepts the longest draft
    prefix whose tokens equal the target's own greedy picks. Because the
    logits at position i condition only on real committed/accepted
    context (the accept rule stops at the first disagreement), greedy
    output is bit-identical to one-token-at-a-time decode for any draft.

    Same argument contract as :func:`forward_paged` minus ``last_index``;
    returns ``(logits [B, T, V] fp32, k_pool, v_pool)``. K/V for all
    masked-in tokens are written to the pool — rejected drafts leave
    stale entries past the accepted frontier, which is safe: attention
    masks slots beyond the query's own position, and the next iteration's
    scatter overwrites them before they ever become visible.
    """
    x, k_pool, v_pool = _paged_backbone(params, cfg, tokens, positions,
                                        token_mask, k_pool, v_pool,
                                        block_tables)
    if cfg.tie_embeddings:
        logits = (x.astype(jnp.float32)
                  @ params["embed"]["table"].astype(jnp.float32).T)
    else:
        logits = dense(params["lm_head"], x, compute_dtype=jnp.float32)
    return logits.astype(jnp.float32), k_pool, v_pool


def param_count(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))


def extend_with_identity_layers(params: Params, cfg: GPTConfig,
                                extra_layers: int):
    """Append ``extra_layers`` exact-identity residual blocks.

    Pre-LN blocks add their output to the residual stream, so a block
    whose ``attn_out`` and ``mlp_down`` projections (kernel AND bias)
    are zero contributes exactly zero: the extended model's logits are
    bit-identical to the original's, while every call pays the deeper
    model's weight traffic and op count (the QKV/up projections and
    attention still run — only the final adds vanish). That makes the
    pair (original, extended) a controlled speculative-decoding
    testbed: the original IS a perfectly-distilled draft of the
    extended target, so greedy acceptance is exactly 1.0. bench.py and
    tests/test_serving_speed.py use it to measure the spec-decode
    ceiling without training a real draft.

    Returns ``(params, cfg)`` for the deepened model. Stacked-block
    layout means extension is a leading-axis concat; MoE blocks are not
    supported (no per-expert identity construction).
    """
    if extra_layers <= 0:
        return params, cfg
    if cfg.moe_experts > 0:
        raise ValueError("identity extension supports dense blocks only")

    zero_adds = ("attn_out", "mlp_down")

    def pad(path_top: str, leaf: jax.Array) -> jax.Array:
        tile = jnp.tile(leaf[:1], (extra_layers,) + (1,) * (leaf.ndim - 1))
        if path_top in zero_adds:
            tile = jnp.zeros_like(tile)
        return jnp.concatenate([leaf, tile], axis=0)

    blocks = {name: {k: pad(name, v) for k, v in sub.items()}
              for name, sub in params["blocks"].items()}
    out = dict(params)
    out["blocks"] = blocks
    return out, dataclasses.replace(
        cfg, n_layers=cfg.n_layers + extra_layers)


def slice_prefix_layers(params: Params, cfg: GPTConfig, n_layers: int):
    """Keep only the first ``n_layers`` stacked blocks (embed, final
    norm and head shared) — the draft half of the identity-extension
    testbed, and the cheap way to carve a layer-sliced draft out of any
    stacked-block checkpoint. Returns ``(params, cfg)``."""
    if not 0 < n_layers <= cfg.n_layers:
        raise ValueError(f"n_layers must be in [1, {cfg.n_layers}], "
                         f"got {n_layers}")
    blocks = {name: {k: v[:n_layers] for k, v in sub.items()}
              for name, sub in params["blocks"].items()}
    out = dict(params)
    out["blocks"] = blocks
    return out, dataclasses.replace(cfg, n_layers=n_layers)
