"""Built-in model families (≈ the reference's examples/ + model_hub coverage)."""
from determined_clone_tpu.models import gpt, mlp, mnist_cnn, vit

__all__ = ["gpt", "mlp", "mnist_cnn", "vit"]
