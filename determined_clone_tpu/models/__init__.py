"""Built-in model families (≈ the reference's examples/ + model_hub coverage)."""
from determined_clone_tpu.models import bert, gpt, mlp, mnist_cnn, resnet, vit

__all__ = ["bert", "gpt", "mlp", "mnist_cnn", "resnet", "vit"]
