"""MLP classifier — the mnist workhorse.

Capability target: the reference's mnist_pytorch tutorial model
(examples/tutorials/mnist_pytorch, gated at >0.97 accuracy by
e2e_tests/tests/nightly/test_convergence.py:25).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

from determined_clone_tpu.ops.layers import dense, dense_init, softmax_cross_entropy

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden_dims: Sequence[int] = (128, 64)
    n_classes: int = 10
    compute_dtype: Any = jnp.float32


def init(key: jax.Array, cfg: MLPConfig) -> Params:
    dims = [cfg.in_dim, *cfg.hidden_dims, cfg.n_classes]
    keys = jax.random.split(key, len(dims) - 1)
    return {
        f"layer_{i}": dense_init(keys[i], dims[i], dims[i + 1])
        for i in range(len(dims) - 1)
    }


def apply(params: Params, cfg: MLPConfig, x: jax.Array) -> jax.Array:
    """x: [B, in_dim] (or [B, 28, 28(, 1)], flattened here) → logits [B, C]."""
    x = x.reshape(x.shape[0], -1)
    n = len(params)
    for i in range(n):
        x = dense(params[f"layer_{i}"], x, compute_dtype=cfg.compute_dtype)
        if i < n - 1:
            x = jax.nn.relu(x)
    return x.astype(jnp.float32)


def loss_fn(params: Params, cfg: MLPConfig, x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.mean(softmax_cross_entropy(apply(params, cfg, x), y))
