"""Small conv net for mnist — NHWC, TPU-layout-native.

Matches the capability of the reference tutorial's conv model
(examples/tutorials/mnist_pytorch/model_def.py): two conv blocks + two dense
layers, dropout between them.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from determined_clone_tpu.ops.layers import (
    conv2d,
    conv_init,
    dense,
    dense_init,
    dropout,
    softmax_cross_entropy,
)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MnistCNNConfig:
    n_filters_1: int = 32
    n_filters_2: int = 64
    dropout_1: float = 0.25
    dropout_2: float = 0.5
    n_classes: int = 10
    compute_dtype: Any = jnp.float32


def init(key: jax.Array, cfg: MnistCNNConfig) -> Params:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    flat = 7 * 7 * cfg.n_filters_2  # 28 → 14 → 7 after two stride-2 pools
    return {
        "conv1": conv_init(k1, 1, cfg.n_filters_1, 3),
        "conv2": conv_init(k2, cfg.n_filters_1, cfg.n_filters_2, 3),
        "fc1": dense_init(k3, flat, 128),
        "fc2": dense_init(k4, 128, cfg.n_classes),
    }


def _maxpool2(x: jax.Array) -> jax.Array:
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def apply(params: Params, cfg: MnistCNNConfig, x: jax.Array, *,
          training: bool = False, dropout_key: Optional[jax.Array] = None) -> jax.Array:
    """x: [B, 28, 28, 1] NHWC (flat [B, 784] accepted) → logits [B, C]."""
    if x.ndim == 2:
        x = x.reshape(-1, 28, 28, 1)
    k1 = k2 = None
    if dropout_key is not None:
        k1, k2 = jax.random.split(dropout_key)
    x = jax.nn.relu(conv2d(params["conv1"], x, compute_dtype=cfg.compute_dtype))
    x = _maxpool2(x)
    x = jax.nn.relu(conv2d(params["conv2"], x, compute_dtype=cfg.compute_dtype))
    x = _maxpool2(x)
    x = dropout(k1, x, cfg.dropout_1, training)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(dense(params["fc1"], x, compute_dtype=cfg.compute_dtype))
    x = dropout(k2, x, cfg.dropout_2, training)
    return dense(params["fc2"], x, compute_dtype=cfg.compute_dtype).astype(jnp.float32)


def loss_fn(params: Params, cfg: MnistCNNConfig, x: jax.Array, y: jax.Array, *,
            training: bool = False, dropout_key: Optional[jax.Array] = None) -> jax.Array:
    logits = apply(params, cfg, x, training=training, dropout_key=dropout_key)
    return jnp.mean(softmax_cross_entropy(logits, y))
