"""Vision Transformer — the vision-domain flagship backbone.

TPU-native ViT: patch embedding is one big matmul (MXU-friendly — no
im2col gather), the encoder is a `lax.scan` over stacked per-layer params
like models/gpt.py (one compiled block body regardless of depth), and all
matmuls run in bfloat16 by default. Fills the vision slot the reference's
model_hub/mmdetection covers (model_hub/model_hub/mmdetection/ adapters);
the architecture itself follows the standard ViT recipe.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from determined_clone_tpu.ops import layers
from determined_clone_tpu.ops.attention import mha

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    n_classes: int = 1000
    d_model: int = 384
    n_layers: int = 12
    n_heads: int = 6
    d_ff: int = 1536
    dropout: float = 0.0
    compute_dtype: Any = jnp.bfloat16
    remat: bool = False

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size ** 2

    @staticmethod
    def tiny() -> "ViTConfig":
        return ViTConfig(image_size=32, patch_size=8, channels=3,
                         n_classes=10, d_model=64, n_layers=2, n_heads=4,
                         d_ff=128, compute_dtype=jnp.float32)


def init(key: jax.Array, cfg: ViTConfig) -> Params:
    ks = jax.random.split(key, 8)

    def stacked(k, shape, stddev=0.02):
        return layers.trunc_normal(k, (cfg.n_layers, *shape), stddev)

    d, f = cfg.d_model, cfg.d_ff
    return {
        "patch_proj": layers.dense_init(ks[0], cfg.patch_dim, d),
        "pos_embed": layers.trunc_normal(ks[1], (cfg.n_patches + 1, d)),
        "cls_token": layers.trunc_normal(ks[2], (d,)),
        "blocks": {
            "ln1_scale": jnp.ones((cfg.n_layers, d)),
            "ln1_bias": jnp.zeros((cfg.n_layers, d)),
            "wqkv": stacked(ks[3], (d, 3 * d)),
            "wo": stacked(ks[4], (d, d), stddev=0.02 / (2 * cfg.n_layers) ** 0.5),
            "ln2_scale": jnp.ones((cfg.n_layers, d)),
            "ln2_bias": jnp.zeros((cfg.n_layers, d)),
            "w1": stacked(ks[5], (d, f)),
            "w2": stacked(ks[6], (f, d), stddev=0.02 / (2 * cfg.n_layers) ** 0.5),
        },
        "ln_f": layers.layernorm_init(d),
        "head": layers.dense_init(ks[7], d, cfg.n_classes),
    }


def patchify(cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """[B,H,W,C] -> [B, n_patches, patch_dim] without gathers."""
    b = images.shape[0]
    p, g = cfg.patch_size, cfg.image_size // cfg.patch_size
    x = images.reshape(b, g, p, g, p, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B,g,g,p,p,C]
    return x.reshape(b, g * g, cfg.patch_dim)


def _block(cfg: ViTConfig, bp: Params, x: jax.Array) -> jax.Array:
    d, h = cfg.d_model, cfg.n_heads
    y = layers.layernorm({"scale": bp["ln1_scale"], "bias": bp["ln1_bias"]}, x)
    y = y.astype(cfg.compute_dtype)
    qkv = y @ bp["wqkv"].astype(cfg.compute_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(*t.shape[:-1], h, d // h)

    attn = mha(heads(q), heads(k), heads(v), causal=False)
    attn = attn.reshape(*attn.shape[:-2], d)
    x = x + (attn @ bp["wo"].astype(cfg.compute_dtype)).astype(x.dtype)

    y = layers.layernorm({"scale": bp["ln2_scale"], "bias": bp["ln2_bias"]}, x)
    y = y.astype(cfg.compute_dtype)
    y = layers.gelu(y @ bp["w1"].astype(cfg.compute_dtype))
    x = x + (y @ bp["w2"].astype(cfg.compute_dtype)).astype(x.dtype)
    return x


def encode(params: Params, cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """[B,H,W,C] -> [B, 1+n_patches, d_model] encoded tokens (f32)."""
    x = patchify(cfg, images).astype(cfg.compute_dtype)
    x = layers.dense(params["patch_proj"], x, compute_dtype=cfg.compute_dtype)
    x = x.astype(jnp.float32)
    cls = jnp.broadcast_to(params["cls_token"], (x.shape[0], 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"]

    block_fn = _block
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn, static_argnums=(0,))

    def scan_body(x, layer_params):
        return block_fn(cfg, layer_params, x), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    return layers.layernorm(params["ln_f"], x)


def apply(params: Params, cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """Classification logits [B, n_classes] from the CLS token."""
    tokens = encode(params, cfg, images)
    return layers.dense(params["head"], tokens[:, 0, :])


def loss_fn(params: Params, cfg: ViTConfig, images: jax.Array,
            labels: jax.Array) -> jax.Array:
    logits = apply(params, cfg, images)
    return layers.softmax_cross_entropy(logits, labels).mean()


def param_count(params: Params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params)
               if hasattr(p, "size"))
