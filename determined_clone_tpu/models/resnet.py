"""ResNet — the conv-net workhorse family (ResNet-50 class).

Fills BASELINE.json config #3 ("examples/computer_vision ResNet-50
ImageNet PyTorchTrial (distributed)"; the reference trains it through
torchvision models under harness/determined/pytorch). TPU-first choices:

- NHWC end to end (channels ride the 128-lane minor dim; conv2d in
  ops/layers.py already speaks NHWC/HWIO).
- GroupNorm instead of BatchNorm: batch-size independent, so per-device
  batch never changes the math under data parallelism, and there are no
  running stats to thread through the functional step (the standard
  "ResNet-50-GN" recipe). BatchNorm remains available in ops/layers.py
  for parity experiments.
- bfloat16 compute by default; params stay float32.
- Blocks are a static Python loop (16 bodies for ResNet-50): conv stages
  are shallow and heterogeneous (stride/projection on stage entry), so a
  lax.scan buys little here — unlike the uniform GPT/ViT stacks.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from determined_clone_tpu.ops.layers import (
    conv2d,
    conv_init,
    dense,
    dense_init,
    groupnorm,
    groupnorm_init,
    softmax_cross_entropy,
)

Params = Dict[str, Any]

# stage depths per variant (bottleneck blocks; expansion 4)
DEPTHS = {
    26: (1, 2, 4, 1),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    n_classes: int = 1000
    width: int = 64          # stem/base width; stages are width*(1,2,4,8)
    channels: int = 3
    gn_groups: int = 32
    compute_dtype: Any = jnp.bfloat16

    @property
    def stage_blocks(self) -> Tuple[int, int, int, int]:
        if self.depth not in DEPTHS:
            raise ValueError(
                f"unsupported resnet depth {self.depth}; "
                f"expected one of {sorted(DEPTHS)}")
        return DEPTHS[self.depth]

    @staticmethod
    def tiny() -> "ResNetConfig":
        return ResNetConfig(depth=26, n_classes=10, width=16,
                            compute_dtype=jnp.float32)


def _block_init(key: jax.Array, c_in: int, c_mid: int,
                stride: int) -> Params:
    c_out = 4 * c_mid
    k1, k2, k3, k4 = jax.random.split(key, 4)
    p = {
        "conv1": conv_init(k1, c_in, c_mid, 1),
        "gn1": groupnorm_init(c_mid),
        "conv2": conv_init(k2, c_mid, c_mid, 3),
        "gn2": groupnorm_init(c_mid),
        "conv3": conv_init(k3, c_mid, c_out, 1),
        "gn3": groupnorm_init(c_out),
    }
    if stride != 1 or c_in != c_out:
        p["proj"] = conv_init(k4, c_in, c_out, 1)
        p["gn_proj"] = groupnorm_init(c_out)
    return p


def init(key: jax.Array, cfg: ResNetConfig) -> Params:
    keys = jax.random.split(key, 2 + sum(cfg.stage_blocks))
    params: Params = {
        "stem": conv_init(keys[0], cfg.channels, cfg.width, 7),
        "gn_stem": groupnorm_init(cfg.width),
    }
    c_in = cfg.width
    ki = 1
    for s, n_blocks in enumerate(cfg.stage_blocks):
        c_mid = cfg.width * (2 ** s)
        for b in range(n_blocks):
            stride = 2 if (b == 0 and s > 0) else 1
            params[f"s{s}b{b}"] = _block_init(keys[ki], c_in, c_mid, stride)
            c_in = 4 * c_mid
            ki += 1
    params["head"] = dense_init(keys[ki], c_in, cfg.n_classes)
    return params


def _bottleneck(p: Params, cfg: ResNetConfig, x: jax.Array,
                stride: int) -> jax.Array:
    g = cfg.gn_groups
    h = conv2d(p["conv1"], x, compute_dtype=cfg.compute_dtype)
    h = jax.nn.relu(groupnorm(p["gn1"], h, groups=g))
    h = conv2d(p["conv2"], h, stride=stride,
               compute_dtype=cfg.compute_dtype)
    h = jax.nn.relu(groupnorm(p["gn2"], h, groups=g))
    h = conv2d(p["conv3"], h, compute_dtype=cfg.compute_dtype)
    h = groupnorm(p["gn3"], h, groups=g)
    if "proj" in p:
        x = groupnorm(p["gn_proj"],
                      conv2d(p["proj"], x, stride=stride,
                             compute_dtype=cfg.compute_dtype),
                      groups=g)
    return jax.nn.relu(x + h)


def _maxpool3_s2(x: jax.Array) -> jax.Array:
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")


def apply(params: Params, cfg: ResNetConfig, x: jax.Array) -> jax.Array:
    """x: [B, H, W, C] NHWC → logits [B, n_classes] (float32)."""
    x = conv2d(params["stem"], x, stride=2, compute_dtype=cfg.compute_dtype)
    x = jax.nn.relu(groupnorm(params["gn_stem"], x, groups=cfg.gn_groups))
    x = _maxpool3_s2(x)
    for s, n_blocks in enumerate(cfg.stage_blocks):
        for b in range(n_blocks):
            stride = 2 if (b == 0 and s > 0) else 1
            x = _bottleneck(params[f"s{s}b{b}"], cfg, x, stride)
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    return dense(params["head"], x,
                 compute_dtype=cfg.compute_dtype).astype(jnp.float32)


def loss_fn(params: Params, cfg: ResNetConfig, x: jax.Array,
            y: jax.Array) -> jax.Array:
    return jnp.mean(softmax_cross_entropy(apply(params, cfg, x), y))


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
