"""BERT — bidirectional encoder for masked-LM pretraining and fine-tuning.

Fills BASELINE.json config #4 ("examples/hf_trainer_api BERT fine-tune via
Core API"; the reference fine-tunes HF BERT through its Core API — see
/root/reference/examples/hf_trainer_api). The HF-checkpoint path lives in
model_hub/huggingface.py; this module is the native TPU family for
training from scratch or fine-tuning without torch weights.

Same TPU-first construction as models/gpt.py:
- stacked-block params ([L, ...] leading dim) walked by ``lax.scan`` —
  one compiled block body regardless of depth;
- bfloat16 matmuls (params float32), bidirectional ``mha`` (no causal
  mask — the encoder half the GPT stack never uses);
- learned position + segment embeddings, MLM head tied to the token
  embedding, and a [CLS] pooler + classification head for fine-tunes.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from determined_clone_tpu.ops.attention import mha
from determined_clone_tpu.ops.layers import (
    dense,
    dense_init,
    embedding_init,
    gelu,
    layernorm,
    layernorm_init,
    softmax_cross_entropy,
    trunc_normal,
)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522      # bert-base wordpiece vocab
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    n_segments: int = 2
    n_classes: int = 2           # fine-tune head (e.g. GLUE pair tasks)
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @staticmethod
    def tiny() -> "BertConfig":
        return BertConfig(vocab_size=256, n_layers=2, d_model=64, n_heads=4,
                          d_ff=128, max_seq_len=64, n_classes=2,
                          compute_dtype=jnp.float32, remat=False)


def init(key: jax.Array, cfg: BertConfig) -> Params:
    keys = jax.random.split(key, 8)
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    dt = cfg.param_dtype

    def stacked(k, shape, stddev=0.02):
        return trunc_normal(k, (L, *shape), stddev=stddev, dtype=dt)

    blocks: Params = {
        "ln1": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
        "attn_qkv": {"kernel": stacked(keys[1], (D, 3 * D)),
                     "bias": jnp.zeros((L, 3 * D), dt)},
        "attn_out": {"kernel": stacked(keys[2], (D, D),
                                       stddev=0.02 / (2 * L) ** 0.5),
                     "bias": jnp.zeros((L, D), dt)},
        "ln2": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
        "mlp_up": {"kernel": stacked(keys[3], (D, F)),
                   "bias": jnp.zeros((L, F), dt)},
        "mlp_down": {"kernel": stacked(keys[4], (F, D),
                                       stddev=0.02 / (2 * L) ** 0.5),
                     "bias": jnp.zeros((L, D), dt)},
    }
    return {
        "embed": embedding_init(keys[0], cfg.vocab_size, D, dtype=dt),
        "pos_embed": trunc_normal(keys[5], (cfg.max_seq_len, D), dtype=dt),
        "seg_embed": trunc_normal(keys[6], (cfg.n_segments, D), dtype=dt),
        "embed_norm": layernorm_init(D, dtype=dt),
        "blocks": blocks,
        "pooler": dense_init(keys[7], D, D, dtype=dt),
        "cls_head": dense_init(jax.random.fold_in(keys[7], 1), D,
                               cfg.n_classes, dtype=dt),
        # MLM output bias (the projection is tied to the embedding table)
        "mlm_bias": jnp.zeros((cfg.vocab_size,), dt),
    }


def _block(cfg: BertConfig, p: Params, x: jax.Array,
           pad_mask: jax.Array) -> jax.Array:
    B, T, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    h = layernorm(p["ln1"], x)
    qkv = dense(p["attn_qkv"], h, compute_dtype=cfg.compute_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    # bidirectional attention; padded KEYS are pushed to -inf so real
    # tokens never mix in padding (zeroed pad activations still carry a
    # layernorm bias, so value-zeroing alone would not be enough)
    attn = mha(q.reshape(B, T, H, hd), k.reshape(B, T, H, hd),
               v.reshape(B, T, H, hd), causal=False,
               mask=pad_mask[:, None, None, :] > 0)
    attn = dense(p["attn_out"], attn.reshape(B, T, D),
                 compute_dtype=cfg.compute_dtype)
    x = x + attn
    h = layernorm(p["ln2"], x)
    h = dense(p["mlp_up"], h, compute_dtype=cfg.compute_dtype)
    h = gelu(h)
    h = dense(p["mlp_down"], h, compute_dtype=cfg.compute_dtype)
    x = x + h
    return x * pad_mask[..., None]  # keep padded positions inert


def encode(params: Params, cfg: BertConfig, tokens: jax.Array,
           segments: Optional[jax.Array] = None,
           pad_mask: Optional[jax.Array] = None) -> jax.Array:
    """tokens: int32 [B, T] → sequence output [B, T, D] (compute dtype).

    ``pad_mask``: float [B, T] with 1 for real tokens, 0 for padding
    (defaults to all-ones). Padded positions are zeroed between blocks and
    must be excluded from any loss.
    """
    B, T = tokens.shape
    if segments is None:
        segments = jnp.zeros_like(tokens)
    if pad_mask is None:
        pad_mask = jnp.ones((B, T), jnp.float32)
    x = (jnp.take(params["embed"]["table"], tokens, axis=0)
         + params["pos_embed"][None, :T]
         + jnp.take(params["seg_embed"], segments, axis=0))
    x = layernorm(params["embed_norm"], x).astype(cfg.compute_dtype)

    def block_fn(layer_params, x):
        return _block(cfg, layer_params, x, pad_mask)

    body = jax.checkpoint(block_fn) if cfg.remat else block_fn
    x, _ = jax.lax.scan(lambda carry, lp: (body(lp, carry), None),
                        x, params["blocks"])
    return x


def pooled(params: Params, cfg: BertConfig, seq_out: jax.Array) -> jax.Array:
    """[CLS] pooler: tanh(dense(first token)) → [B, D]."""
    return jnp.tanh(dense(params["pooler"], seq_out[:, 0],
                          compute_dtype=cfg.compute_dtype))


def classify(params: Params, cfg: BertConfig, tokens: jax.Array,
             segments: Optional[jax.Array] = None,
             pad_mask: Optional[jax.Array] = None) -> jax.Array:
    """Fine-tune head → logits [B, n_classes] (float32)."""
    seq = encode(params, cfg, tokens, segments, pad_mask)
    return dense(params["cls_head"], pooled(params, cfg, seq),
                 compute_dtype=cfg.compute_dtype).astype(jnp.float32)


def mlm_logits(params: Params, cfg: BertConfig, tokens: jax.Array,
               segments: Optional[jax.Array] = None,
               pad_mask: Optional[jax.Array] = None) -> jax.Array:
    """Masked-LM logits [B, T, V] — projection tied to the embedding."""
    seq = encode(params, cfg, tokens, segments, pad_mask)
    table = params["embed"]["table"].astype(cfg.compute_dtype)
    logits = jnp.einsum("btd,vd->btv", seq, table) + params["mlm_bias"]
    return logits.astype(jnp.float32)


def classify_loss(params: Params, cfg: BertConfig, tokens: jax.Array,
                  labels: jax.Array,
                  segments: Optional[jax.Array] = None,
                  pad_mask: Optional[jax.Array] = None) -> jax.Array:
    logits = classify(params, cfg, tokens, segments, pad_mask)
    return jnp.mean(softmax_cross_entropy(logits, labels))


def mlm_loss(params: Params, cfg: BertConfig, tokens: jax.Array,
             targets: jax.Array, mask: jax.Array,
             segments: Optional[jax.Array] = None) -> jax.Array:
    """MLM objective: ``mask`` [B, T] selects the positions whose
    ``targets`` count (the 15% that were masked/corrupted)."""
    logits = mlm_logits(params, cfg, tokens, segments)
    per_tok = softmax_cross_entropy(
        logits.reshape(-1, cfg.vocab_size), targets.reshape(-1))
    m = mask.reshape(-1).astype(jnp.float32)
    return jnp.sum(per_tok * m) / jnp.maximum(jnp.sum(m), 1.0)


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


# Megatron-style TP + fsdp layout, mirroring models/gpt.py's rules: the
# encoder block has the same [L, ...] stacked structure, so column-parallel
# up-projections shard the output dim on tp and row-parallel down-projections
# the input dim. The embedding is vocab-parallel; the tied MLM projection
# reuses it, so mlm_logits' einsum contracts over the same sharded table.
from determined_clone_tpu.parallel.sharding import (  # noqa: E402
    ShardingRules,
)
from jax.sharding import PartitionSpec as P  # noqa: E402

BERT_SHARDING_RULES = ShardingRules(rules=[
    (r"embed/table$",              P("tp", "fsdp")),        # [V, D]
    (r"blocks/attn_qkv/kernel$",   P(None, "fsdp", "tp")),  # [L, D, 3D] col
    (r"blocks/attn_out/kernel$",   P(None, "tp", "fsdp")),  # [L, D, D]  row
    (r"blocks/mlp_up/kernel$",     P(None, "fsdp", "tp")),  # [L, D, F]  col
    (r"blocks/mlp_down/kernel$",   P(None, "tp", "fsdp")),  # [L, F, D]  row
    (r"blocks/.*(bias|scale)$",    P(None)),
    (r"(pos_embed|seg_embed|embed_norm/|mlm_bias)", P()),
    (r"(pooler|cls_head)/",        P()),                    # small heads
])
