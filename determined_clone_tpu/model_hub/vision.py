"""Vision model hub: ready-made classification + detection trials.

The second model-hub domain, filling the role of the reference's
mmdetection adapters (model_hub/model_hub/mmdetection/_trial.py: ready-
made object-detection trials over a config) the TPU-native way: a ViT
classifier (models/vit.py) and a compact anchor-free single-stage
detector — per-cell objectness / class / box regression over a conv
backbone, the FCOS/YOLO family shape — implemented as pure jitted
functions. Subclass, provide data, train.

    class MyDetection(SingleStageDetectionTrial):
        def training_data(self):
            yield {"image": ..., "boxes": ..., "labels": ...}
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.models import vit
from determined_clone_tpu.ops import layers
from determined_clone_tpu.training.trial import JaxTrial

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

class ViTClassificationTrial(JaxTrial):
    """Image classification on a ViT backbone. Hyperparameters mirror
    ViTConfig fields (image_size, patch_size, d_model, ...)."""

    def vit_config(self) -> vit.ViTConfig:
        hp = self.context.get_hparam
        return vit.ViTConfig(
            image_size=int(hp("image_size", 32)),
            patch_size=int(hp("patch_size", 8)),
            channels=int(hp("channels", 3)),
            n_classes=int(hp("n_classes", 10)),
            d_model=int(hp("d_model", 64)),
            n_layers=int(hp("n_layers", 2)),
            n_heads=int(hp("n_heads", 4)),
            d_ff=int(hp("d_ff", 128)),
            compute_dtype=jnp.float32 if hp("full_precision", False)
            else jnp.bfloat16,
            remat=bool(hp("remat", False)),
        )

    def initial_params(self, rng: jax.Array) -> Params:
        self._cfg = self.vit_config()
        return vit.init(rng, self._cfg)

    def optimizer(self) -> optax.GradientTransformation:
        lr = float(self.context.get_hparam("lr", 1e-3))
        return optax.adamw(lr, weight_decay=float(
            self.context.get_hparam("weight_decay", 0.01)))

    def loss(self, params, batch, rng):
        del rng
        logits = vit.apply(params, self._cfg, batch["image"])
        loss = layers.softmax_cross_entropy(logits, batch["label"]).mean()
        return loss, {"accuracy": layers.accuracy(logits, batch["label"])}

    def training_data(self) -> Iterable[Any]:
        raise NotImplementedError("subclass provides training_data()")


# ---------------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    image_size: int = 64
    channels: int = 3
    n_classes: int = 4
    widths: Tuple[int, ...] = (16, 32, 64)  # conv stages, each /2
    compute_dtype: Any = jnp.float32

    @property
    def grid(self) -> int:
        return self.image_size // (2 ** len(self.widths))


def detector_init(key: jax.Array, cfg: DetectorConfig) -> Params:
    ks = jax.random.split(key, len(cfg.widths) + 1)
    backbone = []
    in_ch = cfg.channels
    for i, out_ch in enumerate(cfg.widths):
        backbone.append(layers.conv_init(ks[i], in_ch, out_ch, 3))
        in_ch = out_ch
    # per-cell head: 1 objectness + 4 box (cx, cy, w, h) + n_classes
    head = layers.conv_init(ks[-1], in_ch, 5 + cfg.n_classes, 1)
    return {"backbone": backbone, "head": head}


def detector_apply(params: Params, cfg: DetectorConfig,
                   images: jax.Array) -> Dict[str, jax.Array]:
    """[B,H,W,C] -> per-cell predictions on the [grid, grid] feature map:
    obj logits [B,g,g], boxes [B,g,g,4] — sigmoid-squashed GLOBAL image
    fractions (cx, cy, w, h), regressed directly against ground truth in
    detection_loss (no cell-origin offset) — and class logits
    [B,g,g,n_classes]."""
    x = images.astype(cfg.compute_dtype)
    for conv in params["backbone"]:
        x = layers.conv2d(conv, x, stride=2)
        x = jax.nn.relu(x)
    out = layers.conv2d(params["head"], x)
    obj = out[..., 0]
    box = jax.nn.sigmoid(out[..., 1:5])
    cls = out[..., 5:]
    return {"objectness": obj, "boxes": box, "class_logits": cls}


def detection_loss(params: Params, cfg: DetectorConfig, images: jax.Array,
                   boxes: jax.Array, labels: jax.Array,
                   mask: jax.Array) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Anchor-free cell assignment: each ground-truth box (cx,cy,w,h in
    image fractions; [B,M,4] with validity mask [B,M]) is matched to the
    cell containing its center. Loss = BCE(objectness) + L1(box) +
    CE(class) on matched cells (≈ the FCOS/YOLO recipe the mmdetection
    single-stage trials wrap)."""
    g = cfg.grid
    preds = detector_apply(params, cfg, images)
    b, m = boxes.shape[0], boxes.shape[1]

    cell = jnp.clip((boxes[..., :2] * g).astype(jnp.int32), 0, g - 1)  # [B,M,2]
    # objectness target grid: scatter 1 at matched cells
    batch_idx = jnp.arange(b)[:, None] * jnp.ones((1, m), jnp.int32)
    flat = batch_idx * g * g + cell[..., 1] * g + cell[..., 0]  # y-major
    obj_target = jnp.zeros((b * g * g,), jnp.float32)
    obj_target = obj_target.at[flat.reshape(-1)].max(
        mask.reshape(-1).astype(jnp.float32))
    obj_target = obj_target.reshape(b, g, g)

    obj_loss = optax.sigmoid_binary_cross_entropy(
        preds["objectness"], obj_target).mean()

    # gather predictions at matched cells: [B,M,...]
    def gather_cells(t):
        return t.reshape(b, g * g, *t.shape[3:])[
            jnp.arange(b)[:, None], cell[..., 1] * g + cell[..., 0]]

    pred_box = gather_cells(preds["boxes"])
    pred_cls = gather_cells(preds["class_logits"])
    denom = jnp.maximum(mask.sum(), 1.0)
    box_loss = (jnp.abs(pred_box - boxes).sum(-1) * mask).sum() / denom
    cls_loss = (layers.softmax_cross_entropy(pred_cls, labels)
                * mask).sum() / denom
    total = obj_loss + box_loss + cls_loss
    return total, {"obj_loss": obj_loss, "box_loss": box_loss,
                   "cls_loss": cls_loss}


class SingleStageDetectionTrial(JaxTrial):
    """Object detection with the compact anchor-free detector. Batches:
    {"image": [B,H,W,C], "boxes": [B,M,4], "labels": [B,M], "mask": [B,M]}.
    """

    def detector_config(self) -> DetectorConfig:
        hp = self.context.get_hparam
        widths = hp("widths", (16, 32, 64))
        return DetectorConfig(
            image_size=int(hp("image_size", 64)),
            channels=int(hp("channels", 3)),
            n_classes=int(hp("n_classes", 4)),
            widths=tuple(int(w) for w in widths),
        )

    def initial_params(self, rng: jax.Array) -> Params:
        self._cfg = self.detector_config()
        return detector_init(rng, self._cfg)

    def optimizer(self) -> optax.GradientTransformation:
        return optax.adam(float(self.context.get_hparam("lr", 1e-3)))

    def loss(self, params, batch, rng):
        del rng
        return detection_loss(params, self._cfg, batch["image"],
                              batch["boxes"], batch["labels"], batch["mask"])

    def training_data(self) -> Iterable[Any]:
        raise NotImplementedError("subclass provides training_data()")


def synthetic_detection_batches(cfg: DetectorConfig, *, batch_size: int,
                                n_batches: int, max_boxes: int = 3,
                                seed: int = 0) -> Iterable[Dict[str, np.ndarray]]:
    """Deterministic synthetic shapes-on-canvas data: colored axis-aligned
    rectangles whose class is their color — learnable signal for tests and
    smoke benchmarks (the no_op/fixtures role of the reference's e2e data)."""
    rng = np.random.RandomState(seed)
    s = cfg.image_size
    for _ in range(n_batches):
        images = np.zeros((batch_size, s, s, cfg.channels), np.float32)
        boxes = np.zeros((batch_size, max_boxes, 4), np.float32)
        labels = np.zeros((batch_size, max_boxes), np.int32)
        mask = np.zeros((batch_size, max_boxes), np.float32)
        for b in range(batch_size):
            for m in range(rng.randint(1, max_boxes + 1)):
                w, h = rng.uniform(0.15, 0.4, 2)
                cx = rng.uniform(w / 2, 1 - w / 2)
                cy = rng.uniform(h / 2, 1 - h / 2)
                cls = rng.randint(cfg.n_classes)
                x0, x1 = int((cx - w / 2) * s), int((cx + w / 2) * s)
                y0, y1 = int((cy - h / 2) * s), int((cy + h / 2) * s)
                images[b, y0:y1, x0:x1, cls % cfg.channels] = 1.0
                boxes[b, m] = (cx, cy, w, h)
                labels[b, m] = cls
                mask[b, m] = 1.0
        yield {"image": images, "boxes": boxes, "labels": labels,
               "mask": mask}
