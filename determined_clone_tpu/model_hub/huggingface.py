"""HuggingFace transformers adapter — Flax causal-LM fine-tuning trials.

≈ the reference's model_hub/model_hub/huggingface (BaseTransformerTrial:
wraps an HF model + optimizer + LR schedule behind the Trial API). Here the
model is a Flax transformer traced into the jitted train step; weights come
from ``from_pretrained`` when a checkout/network is available or
``from_config`` (random init) otherwise — the config path is fully offline.

Usage::

    from transformers import GPT2Config

    class MyTrial(HFCausalLMTrial):
        def model_config(self):
            return GPT2Config(n_layer=4, n_embd=256, n_head=8)

        def training_data(self):
            yield from lm_batches(token_array, self.global_batch_size,
                                  seq_len=128)
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training.trial import JaxTrial


def lm_batches(tokens: np.ndarray, batch_size: int,
               seq_len: int) -> Iterator[np.ndarray]:
    """Chop a flat token array into (batch, seq_len+1) LM batches (the +1
    feeds the shifted-label loss). A ragged tail that can't fill a whole
    batch is dropped (static shapes keep XLA from recompiling)."""
    window = seq_len + 1
    n = (len(tokens) - 1) // (batch_size * seq_len)
    for i in range(n):
        rows = []
        for b in range(batch_size):
            lo = (i * batch_size + b) * seq_len
            chunk = tokens[lo:lo + window]
            if len(chunk) < window:
                break
            rows.append(chunk)
        if len(rows) == batch_size:
            yield np.stack(rows).astype(np.int32)


class HFCausalLMTrial(JaxTrial):
    """Fine-tune (or train) an HF Flax causal-LM.

    Subclasses override ``model_config()`` (offline) or
    ``pretrained_name()`` (downloads weights). hparams understood:
    learning_rate, weight_decay, warmup_steps, adam_beta1/2.
    """

    # -- model construction -------------------------------------------------

    def model_config(self) -> Any:
        """Return a transformers PretrainedConfig (offline path)."""
        raise NotImplementedError(
            "override model_config() or pretrained_name()")

    def pretrained_name(self) -> Optional[str]:
        """Model id/path for from_pretrained; None = random init from
        model_config()."""
        return None

    def build_model(self) -> Any:
        from transformers import FlaxAutoModelForCausalLM

        name = self.pretrained_name()
        if name:
            return FlaxAutoModelForCausalLM.from_pretrained(name)
        return FlaxAutoModelForCausalLM.from_config(self.model_config())

    @property
    def model(self) -> Any:
        """The Flax model wrapper (built once; its .params are NOT used as
        training state — initial_params owns that)."""
        if not hasattr(self, "_model"):
            self._model = self.build_model()
        return self._model

    # -- JaxTrial surface ---------------------------------------------------

    def initial_params(self, rng: jax.Array) -> Any:
        params = self.model.params
        # the train state owns the weights from here on; keeping the
        # wrapper's copy too would pin ~2x params for the trial's lifetime
        try:
            self._model._params = None  # loss() always passes params=
        except AttributeError:
            pass
        return params

    def optimizer(self) -> optax.GradientTransformation:
        get = self.context.get_hparam
        lr = float(get("learning_rate", 5e-5))
        warmup = int(get("warmup_steps", 0))
        schedule: Any = lr
        if warmup > 0:
            schedule = optax.linear_schedule(0.0, lr, warmup)
        return optax.adamw(
            schedule,
            b1=float(get("adam_beta1", 0.9)),
            b2=float(get("adam_beta2", 0.999)),
            weight_decay=float(get("weight_decay", 0.01)),
        )

    def _lm_loss(self, params: Any, batch: Any, *, train: bool,
                 rng: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        from determined_clone_tpu.ops.layers import softmax_cross_entropy

        inputs, labels = batch[:, :-1], batch[:, 1:]
        kwargs: Dict[str, Any] = {}
        if train and rng is not None:
            kwargs["dropout_rng"] = rng
        logits = self.model(inputs, params=params, train=train,
                            **kwargs).logits
        loss = softmax_cross_entropy(logits, labels).mean()
        return loss, {"perplexity": jnp.exp(loss)}

    def loss(self, params: Any, batch: Any, rng: jax.Array
             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Next-token cross entropy over a (batch, seq+1) int32 array;
        dropout active (train mode), driven by the step rng."""
        return self._lm_loss(params, batch, train=True, rng=rng)

    def eval_metrics(self, params: Any, batch: Any) -> Dict[str, jax.Array]:
        """Validation in eval mode — dropout off."""
        loss, metrics = self._lm_loss(params, batch, train=False)
        return {"loss": loss, **metrics}

    def training_data(self) -> Iterable[Any]:
        raise NotImplementedError("provide training_data()")
