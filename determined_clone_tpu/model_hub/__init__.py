"""Model hub — ready-made trials for external model families.

≈ the reference's model_hub package: HF-transformers fine-tuning trials
(model_hub/model_hub/huggingface/) and a vision/detection domain filling
the mmdetection role (model_hub/model_hub/mmdetection/) the TPU-native
way — ViT classification + an anchor-free single-stage detector."""
from determined_clone_tpu.model_hub.huggingface import (
    HFCausalLMTrial,
    lm_batches,
)
from determined_clone_tpu.model_hub.vision import (
    DetectorConfig,
    SingleStageDetectionTrial,
    ViTClassificationTrial,
    detection_loss,
    detector_apply,
    detector_init,
    synthetic_detection_batches,
)

__all__ = [
    "DetectorConfig",
    "HFCausalLMTrial",
    "SingleStageDetectionTrial",
    "ViTClassificationTrial",
    "detection_loss",
    "detector_apply",
    "detector_init",
    "lm_batches",
    "synthetic_detection_batches",
]
