"""Model hub — ready-made trials for external model families.

≈ the reference's model_hub package (model_hub/model_hub/huggingface/:
HF-transformers fine-tuning trials; mmdetection has no JAX ecosystem
equivalent, its role — a second adapted family — is filled by the
built-in model zoo in determined_clone_tpu.models)."""
from determined_clone_tpu.model_hub.huggingface import (
    HFCausalLMTrial,
    lm_batches,
)

__all__ = ["HFCausalLMTrial", "lm_batches"]
