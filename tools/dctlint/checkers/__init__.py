"""Checker modules. Importing this package registers every checker;
add a new module here to enroll it (docs/static_analysis.md §adding)."""
from tools.dctlint.checkers import (  # noqa: F401  (import = registration)
    concurrency,
    contracts,
    exceptions,
    jax_checks,
    jit_purity,
    lockorder,
    retry,
    timeutils,
)
