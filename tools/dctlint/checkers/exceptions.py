"""EXC001 — no silently swallowed exceptions without a stated reason.

The PR 2 gate (``tools/check_swallowed_exceptions.py``), migrated into the
framework; that script is now a thin shim over this checker. Flags every
``except Exception:`` / ``except BaseException:`` / bare ``except:``
handler whose body is only ``pass`` (or ``...``) unless a justification
comment sits adjacent — any ``#`` comment from three lines above the
``except`` through one line below the handler body. Narrow handlers
(``except KeyError:`` etc.) are fine: catching a specific error and
ignoring it is a statement in itself; catching *everything* silently
needs words (see docs/observability.md — this is how profiler sample
drops went invisible).
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from tools.dctlint.core import Checker, Diagnostic, FileContext, register

BROAD = ("Exception", "BaseException")
COMMENT_WINDOW_ABOVE = 3


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD for e in t.elts)
    return False


def _is_noop_body(body: List[ast.stmt]) -> bool:
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _has_adjacent_comment(lines: List[str],
                          handler: ast.ExceptHandler) -> bool:
    start = max(0, handler.lineno - 1 - COMMENT_WINDOW_ABOVE)
    end = min(len(lines), (handler.body[-1].end_lineno or handler.lineno) + 1)
    return any("#" in line for line in lines[start:end])


@register
class SwallowedException(Checker):
    rule = "EXC001"
    title = "broad except with silent pass and no justification"
    hint = ("narrow the handler, count the drop in a telemetry counter, "
            "or add a comment saying why silence is correct")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        for node in ctx.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and _is_noop_body(node.body) \
                    and not _has_adjacent_comment(ctx.lines, node):
                what = ast.unparse(node.type) if node.type else "<bare>"
                yield self.diag(
                    ctx, node,
                    f"swallowed `except {what}: pass` with no adjacent "
                    f"justification comment")
