"""CONC003/CONC004 — whole-program lock discipline (ISSUE 18).

CONC003 builds the static lock-acquisition graph: an edge L -> M means
some thread may acquire M while holding L, either lexically (nested
``with``) or through the call graph (a call made under L reaches an
acquire of M). Deadlock needs a cycle in that graph plus concurrent
threads — and the platform has two dozen daemon-thread loops, so every
cycle is treated as real. Acyclicity is verified against the documented
lock hierarchy (:data:`LOCK_HIERARCHY`, docs/static_analysis.md
"Lock hierarchy"): an edge from a later tier back into an earlier one
fails even before it closes a cycle, which keeps the graph a DAG by
construction as the codebase grows.

CONC004 flags blocking work reachable while a lock is held:
``time.sleep``, blocking ``Queue.get/put``, HTTP/subprocess requests,
``block_until_ready``, indefinite ``Event.wait``/``Thread.join``/
``Future.result`` — lexically or through certain call-graph edges. The
one sanctioned exception is ``Condition.wait`` while holding only that
condition's own lock (that *is* the condition-variable protocol; the
wait releases the lock).

Reentrant locks (RLock, and Condition whose default lock is an RLock)
do not self-edge; a plain ``Lock`` re-acquired on a call path is
reported as a self-deadlock.
"""
from __future__ import annotations

import fnmatch
from typing import Dict, Iterator, List, Optional, Tuple

from tools.dctlint.core import Diagnostic, ProjectChecker, register
from tools.dctlint.project import ProjectIndex

# The documented lock hierarchy, outermost tier first: an acquisition
# edge must go left-to-right (same tier is allowed only between
# *different* locks that never close a cycle). Patterns are fnmatch
# globs over lock ids (``module.Class.attr`` / ``module.varname``).
# Derived from the measured acquisition graph of the tree (every one
# of its edges is tier-descending) and enforced on all future edges.
# Keep in sync with docs/static_analysis.md "Lock hierarchy".
LOCK_HIERARCHY: List[Tuple[str, List[str]]] = [
    # cluster-control plane: fleet/master/autoscaler/task lifecycles —
    # these call into everything below, never the reverse
    ("control", [
        "*.serving.fleet.*",
        "*.serving.supervisor.*",
        "*.serving.autoscale.*",
        "*.api.inprocess.*",
        "*.core._unmanaged.*",
        "*.core._distributed.*",
        "*.exec.task.*",
        "*.tensorboard.manager.*",
    ]),
    # a single replica's serving loop: scheduler condition + router
    ("serving", [
        "*.serving.engine.*",
        "*.serving.router.*",
    ]),
    # resource pools the serving/training loops draw from: KV blocks,
    # CAS blobs, executable cache, transfer pool
    ("resource", [
        "*.serving.kv_cache.*",
        "*.storage.*",
    ]),
    # telemetry producers that write files/evaluate rules under their
    # own lock while emitting into the sinks below
    ("recorder", [
        "*.telemetry.flight.*",
        "*.telemetry.goodput.*",
        "*.telemetry.rules.*",
        "*.telemetry.aggregate.*",
        "*.telemetry.device.*",
        "*.profiler.*",
    ]),
    # leaf sinks: metric families, tracer, tsdb, SLO engine, fault
    # plan — must never call out while holding their lock
    ("sink", [
        "*.telemetry.*",
        "*.faults.*",
    ]),
]
_LEAF_TIER = len(LOCK_HIERARCHY)  # unmatched locks: innermost


def _tier(lock_id: str) -> int:
    for i, (_name, patterns) in enumerate(LOCK_HIERARCHY):
        for pat in patterns:
            if fnmatch.fnmatchcase(lock_id, pat):
                return i
    return _LEAF_TIER


def hierarchy_display() -> str:
    return " < ".join(name for name, _ in LOCK_HIERARCHY) + " < leaf"


def _chain_display(chain) -> str:
    return " -> ".join(f"{fq}:{line}" for fq, line in chain)


class _Edge:
    __slots__ = ("src", "dst", "path", "line", "chain", "certain")

    def __init__(self, src, dst, path, line, chain, certain):
        self.src, self.dst = src, dst
        self.path, self.line = path, line
        self.chain, self.certain = chain, certain


def _collect_edges(index: ProjectIndex) -> List[_Edge]:
    edges: List[_Edge] = []
    seen = set()

    def add(src, dst, path, line, chain, certain):
        key = (src, dst, path, line)
        if key in seen:
            return
        seen.add(key)
        edges.append(_Edge(src, dst, path, line, chain, certain))

    for fq, rec in index.functions.items():
        facts, path = rec["facts"], rec["path"]
        for acq in facts.get("acquires", []):
            held = index.held_lock_ids(fq, acq.get("held", []))
            if not held:
                continue
            resolved = index.resolve_lockref(rec["module"], acq["l"])
            if not resolved or resolved[1] not in ("lock", "rlock",
                                                   "condition"):
                continue
            dst, _kind = resolved
            for src, _k in held:
                if src != dst:
                    add(src, dst, path, acq["line"],
                        [(fq, acq["line"])], True)
        for call in facts.get("calls", []):
            if len(call) < 3:
                continue  # no locks held at this call site
            desc, line, held_refs = call
            held = index.held_lock_ids(fq, held_refs)
            if not held:
                continue
            for callee, certain in index.resolve_call(fq, desc):
                acquired = index.eventual_acquires(callee)
                for dst, info in acquired.items():
                    for src, src_kind in held:
                        if src == dst:
                            # reentrancy: fine for rlock/condition,
                            # self-deadlock for a plain Lock
                            if src_kind == "lock" and certain \
                                    and info["certain"]:
                                add(src, dst, path, line,
                                    [(fq, line)] + list(info["chain"]),
                                    True)
                            continue
                        add(src, dst, path, line,
                            [(fq, line)] + list(info["chain"]),
                            certain and info["certain"])
    return edges


def _find_cycles(edges: List[_Edge]) -> List[List[_Edge]]:
    """Cycles in the lock graph, reported once each: for every edge
    that closes a path back to its source, return the closing edges
    along a shortest path."""
    adj: Dict[str, List[_Edge]] = {}
    for e in edges:
        adj.setdefault(e.src, []).append(e)
    cycles: List[List[_Edge]] = []
    reported = set()
    for start in sorted(adj):
        # BFS from start; a path back to start is a cycle
        frontier: List[Tuple[str, List[_Edge]]] = [(start, [])]
        visited = {start: 0}
        found: Optional[List[_Edge]] = None
        while frontier and found is None:
            nxt: List[Tuple[str, List[_Edge]]] = []
            for node, path in frontier:
                for e in adj.get(node, []):
                    if e.src == e.dst:
                        continue  # self-edges reported separately
                    if e.dst == start:
                        found = path + [e]
                        break
                    if e.dst not in visited:
                        visited[e.dst] = 1
                        nxt.append((e.dst, path + [e]))
                if found:
                    break
            frontier = nxt
        if found:
            key = frozenset((e.src, e.dst) for e in found)
            if key not in reported:
                reported.add(key)
                cycles.append(found)
    return cycles


@register
class LockOrderChecker(ProjectChecker):
    rule = "CONC003"
    title = "lock-order cycle / hierarchy violation (deadlock risk)"
    hint = ("acquire locks in the documented hierarchy order "
            "(docs/static_analysis.md \"Lock hierarchy\") — move the "
            "inner acquire out of the outer critical section, or take "
            "both locks in hierarchy order up front")

    def project_check(self, index: ProjectIndex) -> Iterator[Diagnostic]:
        edges = _collect_edges(index)
        n_locks = len({e.src for e in edges} | {e.dst for e in edges})
        cycles = _find_cycles(edges)
        violations = 0
        for cyc in cycles:
            first = cyc[0]
            ring = " -> ".join([e.src for e in cyc] + [cyc[0].src])
            violations += 1
            yield self.pdiag(
                first.path, first.line,
                f"lock-order cycle {ring} (first edge held "
                f"{first.src} while acquiring {first.dst} via "
                f"{_chain_display(first.chain)})")
        for e in edges:
            if e.src == e.dst:
                violations += 1
                yield self.pdiag(
                    e.path, e.line,
                    f"non-reentrant lock {e.src} re-acquired on a "
                    f"path that already holds it "
                    f"(via {_chain_display(e.chain)})",
                    hint="use threading.RLock, or split the helper "
                         "into a _locked variant called under the "
                         "lock")
                continue
            st, dt = _tier(e.src), _tier(e.dst)
            if st > dt and e.certain:
                violations += 1
                yield self.pdiag(
                    e.path, e.line,
                    f"lock hierarchy violation: {e.src} (tier "
                    f"{LOCK_HIERARCHY[st][0] if st < _LEAF_TIER else 'leaf'}"
                    f") held while acquiring {e.dst} (tier "
                    f"{LOCK_HIERARCHY[dt][0] if dt < _LEAF_TIER else 'leaf'}"
                    f") via {_chain_display(e.chain)}")
        index.summaries[self.rule] = (
            f"{n_locks} ordered locks, {len(edges)} acquisition "
            f"edges, {len(cycles)} cycle(s), {violations} "
            f"violation(s); hierarchy verified: {hierarchy_display()}")


@register
class BlockingUnderLockChecker(ProjectChecker):
    rule = "CONC004"
    title = "blocking call reachable while a lock is held"
    hint = ("do the blocking work outside the critical section: "
            "snapshot state under the lock, release, then "
            "sleep/wait/transfer (serving/fleet.py's drain-outside-"
            "lock pattern)")

    def project_check(self, index: ProjectIndex) -> Iterator[Diagnostic]:
        emitted = set()
        flagged = 0
        for fq, rec in index.functions.items():
            facts, path = rec["facts"], rec["path"]
            for ev in facts.get("blocking", []):
                held = index.held_lock_ids(fq, ev.get("held", []))
                d = self._event_diag(index, rec, fq, ev, held,
                                     ev["line"], None)
                if d and (d.path, d.line, d.message) not in emitted:
                    emitted.add((d.path, d.line, d.message))
                    flagged += 1
                    yield d
            for call in facts.get("calls", []):
                if len(call) < 3:
                    continue
                desc, line, held_refs = call
                held = index.held_lock_ids(fq, held_refs)
                if not held:
                    continue
                for callee, certain in index.resolve_call(fq, desc):
                    if not certain:
                        continue
                    for ev in index.eventual_blocking(callee):
                        d = self._event_diag(index, rec, fq, ev, held,
                                             line, callee)
                        if d and (d.path, d.line,
                                  d.message) not in emitted:
                            emitted.add((d.path, d.line, d.message))
                            flagged += 1
                            yield d
        index.summaries[self.rule] = (
            f"{flagged} blocking-under-lock site(s)")

    def _event_diag(self, index, rec, fq, ev, held, line,
                    callee) -> Optional[Diagnostic]:
        if not held:
            return None
        kind = ev["kind"]
        if kind == "event_wait" and ev.get("bounded"):
            return None  # bounded stop-flag polls are the idiom
        ev_lock = ev.get("lock")
        if ev_lock is None and ev.get("ref") is not None:
            resolved = index.resolve_lockref(rec["module"], ev["ref"])
            ev_lock = resolved[0] if resolved else None
        if kind == "cond_wait":
            # waiting on a condition releases its own lock — exempt
            # when that is the only lock held
            if len(held) == 1 and ev_lock == held[0][0]:
                return None
        held_ids = ", ".join(h[0] for h in held)
        if callee is None:
            msg = (f"{ev['api']} while holding {held_ids}")
        else:
            tail = ev["chain"][-1]
            msg = (f"call into {callee} may block "
                   f"({ev['api']} at {tail[0]}:{tail[1]}) while "
                   f"holding {held_ids}")
        return self.pdiag(rec["path"], line, msg)
