"""JAX tracing checkers: host syncs in jit, RNG key hygiene, donation.

Why these are linted rather than reviewed: inside ``jax.jit`` the Python
body runs ONCE at trace time, so a ``print``/``np.*``/``.item()`` either
silently prints tracers, forces a device→host sync that serializes the
pipeline, or is constant-folded into the compiled program — none of which
fail a test. Same for a constant ``PRNGKey``: the program is *correct*,
just statistically wrong (every step sees the same dropout mask). These
only surface as perf cliffs or bad convergence, which is exactly what
static analysis is for (ISSUE 3; docs/static_analysis.md).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set

from tools.dctlint.core import Checker, Diagnostic, FileContext, register

JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit", "pjit"}
SCAN_NAMES = {"jax.lax.scan", "lax.scan"}
KEY_NAMES = {"jax.random.PRNGKey", "jax.random.key"}
KEY_CONSUMERS = {"jax.random.split", "jax.random.fold_in"}

# per-step / loss-shaped function names: the ones called once per batch,
# where a constant key means every step reuses the same randomness
PER_STEP_NAME = re.compile(r"(^|_)(loss|step|train|eval|metric)", re.I)
TRAIN_STEP_NAME = re.compile(r"train|(^|_)step(_|$)", re.I)


def _call_qname(ctx: FileContext, call: ast.Call) -> Optional[str]:
    return ctx.qualified_name(call.func)


def _decorator_traces(ctx: FileContext, dec: ast.expr) -> bool:
    """True when a decorator jits the function: ``@jax.jit``, ``@pjit``,
    ``@partial(jax.jit, ...)`` or ``@jax.jit(...)`` parameterized."""
    if isinstance(dec, ast.Call):
        name = ctx.qualified_name(dec.func) or ""
        if name in JIT_NAMES:
            return True
        if name in ("functools.partial", "partial"):
            return bool(dec.args) and (
                ctx.qualified_name(dec.args[0]) in JIT_NAMES)
        return False
    return (ctx.qualified_name(dec) or "") in JIT_NAMES


def _traced_functions(ctx: FileContext) -> Set[ast.AST]:
    """Function/lambda nodes whose bodies run under trace: jit-decorated
    defs, defs passed to ``jax.jit``/``pjit``/``lax.scan`` (through one
    level of ``alias = fn`` indirection), and everything nested inside."""
    defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
    aliases: Dict[str, str] = {}
    for node in ctx.nodes:
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, []).append(node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Name):
            aliases[node.targets[0].id] = node.value.id

    traced: Set[ast.AST] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.FunctionDef):
            if any(_decorator_traces(ctx, d) for d in node.decorator_list):
                traced.add(node)
        elif isinstance(node, ast.Call):
            name = _call_qname(ctx, node) or ""
            if name not in JIT_NAMES and name not in SCAN_NAMES:
                continue
            if not node.args:
                continue
            fn = node.args[0]
            if isinstance(fn, ast.Lambda):
                traced.add(fn)
            elif isinstance(fn, ast.Name):
                target = aliases.get(fn.id, fn.id)
                for d in defs_by_name.get(target, []):
                    traced.add(d)
    # nested defs trace with their parent
    closure: Set[ast.AST] = set()
    for root in traced:
        for sub in ast.walk(root):
            if isinstance(sub, (ast.FunctionDef, ast.Lambda)):
                closure.add(sub)
    return closure


@register
class HostSyncInJit(Checker):
    rule = "JAX001"
    title = "host sync / side effect inside traced code"
    hint = ("use jax.debug.print / jnp.* inside jit; move host conversions "
            "(.item(), float()) outside the traced function or behind "
            "jax.block_until_ready at a reporting boundary")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        traced = _traced_functions(ctx)
        seen: Set[ast.AST] = set()
        for root in traced:
            for node in ast.walk(root):
                if not isinstance(node, ast.Call) or node in seen:
                    continue
                seen.add(node)
                name = _call_qname(ctx, node) or ""
                if name == "print":
                    yield self.diag(ctx, node,
                                    "print() inside a jitted/scanned "
                                    "function runs at trace time only (and "
                                    "prints tracers)")
                elif name.split(".")[0] == "numpy":
                    yield self.diag(ctx, node,
                                    f"{name}() inside a jitted/scanned "
                                    f"function forces a host round-trip or "
                                    f"constant-folds at trace time")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    yield self.diag(ctx, node,
                                    ".item() inside a jitted/scanned "
                                    "function is a blocking device->host "
                                    "sync")
                elif name == "float" and node.args \
                        and not isinstance(node.args[0], ast.Constant):
                    yield self.diag(ctx, node,
                                    "float() on a traced value is a "
                                    "blocking device->host sync")


def _enclosing_def_names(ctx: FileContext, node: ast.AST) -> List[str]:
    return [f.name for f in ctx.enclosing_functions(node)
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))]


@register
class ConstantKeyReuse(Checker):
    rule = "JAX002"
    title = "constant PRNGKey in per-step code / key reused without split"
    hint = ("thread a key from the seeded rng chain (jax.random.split / "
            "fold_in) instead of re-deriving a constant key per call")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        # (a) constant PRNGKey inside loss/step/eval-shaped functions:
        # the same key every invocation means the same dropout mask /
        # noise every step — silently wrong statistics
        for node in ctx.nodes:
            if isinstance(node, ast.Call) \
                    and (_call_qname(ctx, node) or "") in KEY_NAMES \
                    and node.args \
                    and all(isinstance(a, ast.Constant) for a in node.args):
                names = _enclosing_def_names(ctx, node)
                if names and PER_STEP_NAME.search(names[0]):
                    yield self.diag(
                        ctx, node,
                        f"constant {ast.unparse(node.func)}"
                        f"({ast.unparse(node.args[0])}) inside per-step "
                        f"function '{names[0]}' reuses the same key every "
                        f"call")
        # (b) a key variable consumed by two calls with no split between:
        # both consumers see identical randomness
        for scope in self._top_level_functions(ctx):
            yield from self._check_reuse(ctx, scope)

    def _top_level_functions(self, ctx: FileContext):
        for node in ctx.nodes:
            if isinstance(node, ast.FunctionDef) \
                    and not ctx.enclosing_functions(node):
                yield node

    def _check_reuse(self, ctx: FileContext,
                     scope: ast.FunctionDef) -> Iterator[Diagnostic]:
        key_names: Set[str] = set()
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Call) and
                    (_call_qname(ctx, node.value) or "")
                    in KEY_NAMES | KEY_CONSUMERS):
                continue
            for t in node.targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                key_names.update(
                    e.id for e in elts if isinstance(e, ast.Name))
        if not key_names:
            return
        uses: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            if (_call_qname(ctx, node) or "") in KEY_CONSUMERS:
                continue  # split/fold_in is the sanctioned consumption
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in key_names:
                    uses.setdefault(arg.id, []).append(arg)
        for name, sites in uses.items():
            if len(sites) > 1:
                yield self.diag(
                    ctx, sites[1],
                    f"key '{name}' is passed to {len(sites)} calls without "
                    f"an intervening jax.random.split — both consumers see "
                    f"identical randomness")


@register
class MissingDonation(Checker):
    rule = "JAX003"
    title = "jitted train step without donate_argnums"
    hint = ("pass donate_argnums=(0,) (the carried state) so XLA reuses "
            "the input buffers — without it every step holds two copies "
            "of params + optimizer state")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
        aliases: Dict[str, str] = {}
        for node in ctx.nodes:
            if isinstance(node, ast.FunctionDef):
                defs_by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Name):
                aliases[node.targets[0].id] = node.value.id

        for node in ctx.nodes:
            if isinstance(node, ast.Call):
                if (_call_qname(ctx, node) or "") not in JIT_NAMES:
                    continue
                if any(kw.arg is None for kw in node.keywords):
                    continue  # **kwargs: cannot prove donation is missing
                if any(kw.arg in ("donate_argnums", "donate_argnames")
                       for kw in node.keywords):
                    continue
                if not node.args or not isinstance(node.args[0], ast.Name):
                    continue
                target = aliases.get(node.args[0].id, node.args[0].id)
                for d in defs_by_name.get(target, []):
                    if self._is_train_step(ctx, d):
                        yield self.diag(
                            ctx, node,
                            f"jax.jit of train-step-shaped '{d.name}' "
                            f"without donate_argnums")
                        break
            elif isinstance(node, ast.FunctionDef) \
                    and self._is_train_step(ctx, node):
                for dec in node.decorator_list:
                    if self._plain_jit_decorator(ctx, dec):
                        yield self.diag(
                            ctx, dec,
                            f"@jax.jit on train-step-shaped '{node.name}' "
                            f"without donate_argnums")

    def _is_train_step(self, ctx: FileContext, d: ast.FunctionDef) -> bool:
        if not TRAIN_STEP_NAME.search(d.name):
            return False
        if "eval" in d.name.lower():
            return False
        # a step_fn nested inside make_eval_step etc. is not a train step
        return not any("eval" in n.lower()
                       for n in _enclosing_def_names(ctx, d))

    def _plain_jit_decorator(self, ctx: FileContext, dec: ast.expr) -> bool:
        if isinstance(dec, ast.Call):
            name = ctx.qualified_name(dec.func) or ""
            if name in JIT_NAMES:
                return not any(
                    kw.arg is None
                    or kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in dec.keywords)
            if name in ("functools.partial", "partial") and dec.args \
                    and ctx.qualified_name(dec.args[0]) in JIT_NAMES:
                return not any(
                    kw.arg is None
                    or kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in dec.keywords)
            return False
        return (ctx.qualified_name(dec) or "") in JIT_NAMES
