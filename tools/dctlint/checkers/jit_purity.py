"""JAX004 — jit-boundary purity (ISSUE 18).

``jax.jit`` / ``shard_map`` / ``lax.scan`` trace a function once and
replay the recorded computation: anything the Python body does besides
array math either bakes a stale value into the compiled artifact
(reading mutable state, ``time.time()``, ``os.environ``) or silently
runs only at trace time (mutating ``self``, writing a module global,
touching a socket). Lexical JAX001 catches ``print``/side effects in
decorated bodies; JAX004 closes the gap *through the call graph*: it
resolves every function passed to a trace entry point
(:data:`~tools.dctlint.project.TRACE_ENTRIES`), walks the certain call
edges reachable from it, and flags

- a bound method passed to a trace entry (the closure captures
  ``self``, whose mutable state is baked in at trace time),
- stores to ``self`` or module globals anywhere in the traced region,
- reads of *mutable* instance attributes (assigned outside
  ``__init__``; frozen config read-only attrs are fine),
- calls into side-effecting stdlib/platform APIs (``time``, ``os``
  beyond ``os.path``, ``logging``, ``random``, ``socket``,
  ``subprocess``, ``requests``, ``threading``, ``faults.point``,
  ``open``/``input``).

Only *certain* call edges propagate (same discipline as CONC004) so a
heuristic method-name match can never produce a purity diagnostic.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from tools.dctlint.core import Diagnostic, ProjectChecker, register
from tools.dctlint.project import ProjectIndex

_DEPTH_CAP = 8

# stdlib/platform roots whose calls are side effects or trace-time
# constants inside a traced region. os.path is pure path algebra and
# exempt; jax.random is fine (the root here is the ``random`` module).
_IMPURE_ROOTS = frozenset({
    "time", "logging", "random", "socket", "subprocess",
    "requests", "threading", "shutil", "tempfile",
})
_IMPURE_BARE = frozenset({"open", "input"})


def _impure_api(dotted: str) -> Optional[str]:
    root = dotted.split(".", 1)[0]
    if root == "os":
        return None if dotted.startswith("os.path.") else dotted
    if root in _IMPURE_ROOTS:
        return dotted
    # project fault injection: faults.point() sleeps/raises by plan
    if dotted == "faults.point" or dotted.endswith(".faults.point"):
        return dotted
    return None


@register
class JitPurityChecker(ProjectChecker):
    rule = "JAX004"
    title = "impure function reachable from a jit/shard_map/scan boundary"
    hint = ("a traced function must be pure: pass state in as "
            "arguments and return the new state; hoist clocks, RNG "
            "seeds, env reads, and logging out of the traced region "
            "(training/train_step.py's make_train_step is the "
            "pattern)")

    def project_check(self, index: ProjectIndex) -> Iterator[Diagnostic]:
        roots: List[Tuple[str, str, str, int, str]] = []
        emitted: Set[Tuple[str, int, str]] = set()
        n_bound = 0
        for path, facts in sorted(index.files.items()):
            mod = facts.get("module")
            for tgt in facts.get("jit_targets", []):
                desc, entry = tgt["t"], tgt["entry"]
                kind = desc[0]
                if kind == "l":
                    fq = f"{mod}.{desc[1]}" if mod else desc[1]
                    if fq in index.functions:
                        roots.append((fq, entry, path, tgt["line"], mod))
                elif kind == "q":
                    for fq in index.resolve_dotted(desc[1]):
                        roots.append((fq, entry, path, tgt["line"], mod))
                elif kind == "s":
                    n_bound += 1
                    d = self.pdiag(
                        path, tgt["line"],
                        f"bound method self.{desc[1]} passed to "
                        f"{entry} — the traced closure captures self "
                        f"and bakes its mutable state into the "
                        f"compiled artifact",
                        hint="trace a free function (or staticmethod) "
                             "that takes the needed state as explicit "
                             "arguments")
                    key = (d.path, d.line, d.message)
                    if key not in emitted:
                        emitted.add(key)
                        yield d
                # ["n"]/["sa"]: unresolved — nothing sound to say
        reachable: Set[str] = set()
        flagged = 0
        for fq, entry, rpath, rline, _mod in roots:
            for d in self._check_root(index, fq, entry, rpath, rline,
                                      reachable):
                key = (d.path, d.line, d.message)
                if key not in emitted:
                    emitted.add(key)
                    flagged += 1
                    yield d
        index.summaries[self.rule] = (
            f"{len(roots)} traced entry points, {len(reachable)} "
            f"reachable functions, {flagged + n_bound} purity "
            f"violation(s)")

    def _check_root(self, index: ProjectIndex, root_fq: str,
                    entry: str, rpath: str, rline: int,
                    reachable: Set[str]) -> Iterator[Diagnostic]:
        origin = f"traced via {entry} at {rpath}:{rline}"
        stack: List[Tuple[str, int]] = [(root_fq, 0)]
        seen: Set[str] = set()
        while stack:
            fq, depth = stack.pop()
            if fq in seen or depth > _DEPTH_CAP:
                continue
            seen.add(fq)
            reachable.add(fq)
            rec = index.functions.get(fq)
            if rec is None:
                continue
            yield from self._check_fn(index, rec, fq, origin)
            for call in rec["facts"].get("calls", []):
                desc = call[0]
                for callee, certain in index.resolve_call(fq, desc):
                    if certain:
                        stack.append((callee, depth + 1))

    def _check_fn(self, index: ProjectIndex, rec: Dict[str, Any],
                  fq: str, origin: str) -> Iterator[Diagnostic]:
        facts, path = rec["facts"], rec["path"]
        for attr, line in facts.get("stores_self", []):
            yield self.pdiag(
                path, line,
                f"{fq} ({origin}) stores self.{attr} — the write "
                f"happens once at trace time, not per step")
        for name, line in facts.get("stores_global", []):
            yield self.pdiag(
                path, line,
                f"{fq} ({origin}) writes module global {name} inside "
                f"a traced region")
        clsfq = rec.get("cls")
        if clsfq and clsfq in index.classes:
            mutable = index.mutable_attrs(clsfq)
            flagged_attrs: Set[str] = set()
            for attr, line in facts.get("reads_self", []):
                if attr in mutable and attr not in flagged_attrs:
                    flagged_attrs.add(attr)
                    yield self.pdiag(
                        path, line,
                        f"{fq} ({origin}) reads mutable instance "
                        f"attribute self.{attr} (assigned outside "
                        f"__init__) — its trace-time value is baked "
                        f"into the compiled artifact")
        for call in facts.get("calls", []):
            desc, line = call[0], call[1]
            api = None
            if desc[0] == "q":
                api = _impure_api(desc[1])
            elif desc[0] == "n" and desc[1] in _IMPURE_BARE:
                api = desc[1]
            if api:
                yield self.pdiag(
                    path, line,
                    f"{fq} ({origin}) calls side-effecting {api} "
                    f"inside a traced region")
