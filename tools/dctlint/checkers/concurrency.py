"""Concurrency checkers: thread attribution and lock discipline.

CONC001 exists because the conftest thread-leak fixture attributes leaks
*by thread name* — an anonymous ``Thread-3`` survivor is undiagnosable,
and an un-``daemon`` library thread can hang interpreter exit. CONC002 is
the classic leak: an ``acquire()`` whose ``release()`` is skipped by an
exception between them deadlocks every later acquirer.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from tools.dctlint.core import Checker, Diagnostic, FileContext, register

THREAD_NAMES = {"threading.Thread"}


def _thread_ctor_problems(call: ast.Call) -> Optional[List[str]]:
    """Which of daemon=/name= are missing, or None when undecidable
    (a ``**kwargs`` splat may carry them)."""
    if any(kw.arg is None for kw in call.keywords):
        return None
    present = {kw.arg for kw in call.keywords}
    return [k for k in ("daemon", "name") if k not in present]


@register
class ThreadNeedsDaemonAndName(Checker):
    rule = "CONC001"
    title = "threading.Thread without explicit daemon= and name="
    hint = ("pass daemon= (an explicit lifetime decision) and name= (so "
            "the conftest thread-leak fixture can attribute a survivor)")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        thread_classes = set()
        for node in ctx.nodes:
            if isinstance(node, ast.ClassDef) and any(
                    (ctx.qualified_name(b) or "") in THREAD_NAMES
                    for b in node.bases):
                thread_classes.add(node)

        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            if (ctx.qualified_name(node.func) or "") in THREAD_NAMES:
                # direct construction — unless it's the super().__init__
                # pattern's import site; handled below per subclass
                missing = _thread_ctor_problems(node)
                if missing:
                    yield self.diag(
                        ctx, node,
                        f"threading.Thread(...) missing "
                        f"{' and '.join(f'{m}=' for m in missing)}")

        for cls in thread_classes:
            yield from self._check_subclass(ctx, cls)

    def _check_subclass(self, ctx: FileContext,
                        cls: ast.ClassDef) -> Iterator[Diagnostic]:
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            yield self.diag(
                ctx, cls,
                f"Thread subclass '{cls.name}' has no __init__ forwarding "
                f"daemon= and name= to super().__init__")
            return
        for node in ast.walk(init):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "__init__" \
                    and isinstance(node.func.value, ast.Call) \
                    and (ctx.qualified_name(node.func.value.func)
                         == "super"):
                missing = _thread_ctor_problems(node)
                if missing:
                    yield self.diag(
                        ctx, node,
                        f"'{cls.name}.__init__' super().__init__() missing "
                        f"{' and '.join(f'{m}=' for m in missing)}")
                return
        yield self.diag(
            ctx, init,
            f"Thread subclass '{cls.name}.__init__' never calls "
            f"super().__init__(daemon=..., name=...)")


def _enclosing_statement(ctx: FileContext, node: ast.AST) -> ast.stmt:
    """The statement to reason about siblings of: hop out of expressions,
    and out of an If/While *test* to the If/While itself."""
    cur = node
    while True:
        parent = ctx.parents.get(cur)
        if parent is None or isinstance(cur, ast.stmt):
            if isinstance(parent, (ast.If, ast.While)) \
                    and getattr(parent, "test", None) is cur:
                return parent
            if isinstance(cur, ast.stmt):
                return cur
        if parent is None:
            return cur  # pragma: no cover - module node fallback
        if isinstance(parent, (ast.If, ast.While)) and parent.test is cur:
            return parent
        cur = parent


def _try_releases(try_node: ast.Try) -> bool:
    for stmt in try_node.finalbody:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                return True
    return False


@register
class AcquireWithoutRelease(Checker):
    rule = "CONC002"
    title = "Lock.acquire() outside with / try-finally"
    hint = ("prefer `with lock:`; when acquire() must be explicit, the "
            "very next statement must be try/finally: lock.release()")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        for node in ctx.nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            if self._protected(ctx, node):
                continue
            yield self.diag(
                ctx, node,
                f"{ast.unparse(node.func)}() without a guaranteed release "
                f"— an exception before release() deadlocks every later "
                f"acquirer")

    def _protected(self, ctx: FileContext, call: ast.Call) -> bool:
        # inside a Try whose finally releases
        cur: Optional[ast.AST] = call
        while cur is not None:
            parent = ctx.parents.get(cur)
            if isinstance(parent, ast.Try) and cur in parent.body \
                    and _try_releases(parent):
                return True
            cur = parent
        # the statement right after the acquire is try/finally: release
        stmt = _enclosing_statement(ctx, call)
        parent = ctx.parents.get(stmt)
        for field in ("body", "orelse", "finalbody"):
            siblings = getattr(parent, field, None)
            if isinstance(siblings, list) and stmt in siblings:
                i = siblings.index(stmt)
                nxt = siblings[i + 1] if i + 1 < len(siblings) else None
                if isinstance(nxt, ast.Try) and _try_releases(nxt):
                    return True
                # `if lock.acquire(timeout=..):` guarding a try/finally body
                if stmt is not call and isinstance(stmt, (ast.If, ast.While)):
                    body = getattr(stmt, "body", [])
                    if body and isinstance(body[0], ast.Try) \
                            and _try_releases(body[0]):
                        return True
        return False
