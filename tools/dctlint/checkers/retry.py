"""RETRY001 — hand-rolled retry loops must go through utils/retry.py.

A loop that catches an exception and ``time.sleep``s is a retry loop, and
every hand-rolled one reinvents the same bugs: constant delay (thundering
herd), no jitter, no deadline, no telemetry. ``utils/retry.py`` provides
``retry_call`` / ``sleep_backoff`` with exponential backoff, full jitter,
a monotonic deadline, and a per-policy retry counter — that is the one
place retry pacing lives (docs/fault_tolerance.md has the policy table).

Heuristic: a ``time.sleep`` call lexically inside a for/while loop whose
body (not counting nested function scopes) also contains an ``except``
handler. Plain poll loops (sleep without a handler) are fine, as is a
handler that lives in a function merely *called* from the loop.
``utils/retry.py`` itself is exempt — its sleep IS the implementation.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, Set

from tools.dctlint.core import Checker, Diagnostic, FileContext, register

SLEEP = "time.sleep"


@register
class HandRolledRetry(Checker):
    rule = "RETRY001"
    title = "hand-rolled retry loop (sleep + except in a loop)"
    hint = ("use determined_clone_tpu.utils.retry (retry_call / "
            "sleep_backoff with a named RetryPolicy) instead of a "
            "bare time.sleep retry loop")

    def _loop_nodes(self, loop: ast.AST) -> Iterator[ast.AST]:
        """Walk a loop body without descending into nested function
        scopes (the TIME001 scope rule: a handler inside a closure
        defined in the loop is not this loop's retry logic)."""
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        # the retry module's own sleep is the implementation, not a bug
        if Path(ctx.path).as_posix().endswith("utils/retry.py"):
            return
        flagged: Set[ast.AST] = set()  # dedupe sleeps under nested loops
        for loop in ctx.nodes:
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            body = list(self._loop_nodes(loop))
            if not any(isinstance(n, ast.ExceptHandler) for n in body):
                continue
            for node in body:
                if node in flagged:
                    continue
                if isinstance(node, ast.Call) \
                        and ctx.qualified_name(node.func) == SLEEP:
                    flagged.add(node)
                    yield self.diag(
                        ctx, node,
                        "retry loop with hand-rolled time.sleep pacing: "
                        "constant delay, no jitter, no deadline, no "
                        "telemetry")
