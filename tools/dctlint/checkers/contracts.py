"""CONTRACT001/002/003 — cross-layer registry sync (ISSUE 18).

Three registries in this tree live half in code and half in docs or
config, and until now nothing but convention kept the halves in sync:

- **CONTRACT001** fault-point catalog: every ``faults.point("name")``
  site (and constant ``fault_*=`` kwargs naming one) must have a row in
  the "Fault points" table of docs/fault_tolerance.md, and every row
  must still name a point that exists in code — so the chaos matrix the
  docs promise is the chaos matrix that runs.
- **CONTRACT002** metric families: a family name must map to exactly
  one metric type across the whole tree (``counter`` in one module and
  ``gauge`` in another under the same name corrupts scrapes silently),
  and every family must appear backticked in docs/observability.md.
- **CONTRACT003** config schema round-trip: every top-level
  ``properties`` key of ``EXPERIMENT_SCHEMA`` (config/schema.py) must
  be consumed — an ``ExperimentConfig`` field or a ``raw["key"]`` /
  ``raw.get("key")`` read inside the config package — and every
  ``ExperimentConfig`` field must map back to a schema key, so the
  validated surface and the consumed surface are the same surface.

All three skip quietly when the docs/schema artifact is absent from
the linted root, which is how fixture trees opt in: provide the
artifact and the contract is enforced.
"""
from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from tools.dctlint.core import Diagnostic, ProjectChecker, register
from tools.dctlint.project import ProjectIndex

_BACKTICK = re.compile(r"`([^`]+)`")

# Schema keys accepted for Determined-config compatibility and carried
# in ``raw`` without a dedicated consumer. Keep each entry justified,
# or shrink the set.
PASSTHROUGH_KEYS = frozenset({
    "template",    # server-side template merge: validated, kept in raw
    "unmanaged",   # unmanaged-mode marker: read straight off raw
})

# ExperimentConfig fields whose schema key has a different name.
FIELD_TO_KEY_RENAMES = {
    "experiment_seed": "reproducibility",
    "profiling_enabled": "profiling",
}

# Internal bookkeeping fields with no schema surface.
INTERNAL_FIELDS = frozenset({"raw", "deprecations"})


def _read_doc(index: ProjectIndex, rel: str) -> Optional[List[str]]:
    if index.root is None:
        return None
    p = Path(index.root) / rel
    try:
        return p.read_text().splitlines()
    except OSError:
        return None


def _catalog_rows(lines: List[str], heading: str) -> List[Tuple[str, int]]:
    """(backticked name, 1-based line) for each markdown table row in
    the section under ``heading``. A first cell like ```` `a` / `b` ````
    yields both names."""
    rows: List[Tuple[str, int]] = []
    in_section = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if stripped.startswith("#"):
            in_section = stripped.lstrip("#").strip().lower() \
                .startswith(heading.lower())
            continue
        if not in_section or not stripped.startswith("|"):
            continue
        first_cell = stripped.strip("|").split("|", 1)[0]
        if set(first_cell.strip()) <= {"-", ":", " "}:
            continue  # header separator row
        for name in _BACKTICK.findall(first_cell):
            rows.append((name.strip(), i))
    return rows


@register
class FaultCatalogChecker(ProjectChecker):
    rule = "CONTRACT001"
    title = ("fault point missing from docs/fault_tolerance.md "
             "catalog, or stale catalog row")
    hint = ("keep the \"Fault points\" table in docs/fault_tolerance.md "
            "in lockstep with faults.point() sites: add the missing "
            "row / delete the stale one")

    DOC = "docs/fault_tolerance.md"
    SECTION = "fault points"

    def project_check(self, index: ProjectIndex) -> Iterator[Diagnostic]:
        doc = _read_doc(index, self.DOC)
        if doc is None:
            return
        code: Dict[str, Tuple[str, int]] = {}
        for path, facts in index.files.items():
            for name, line in facts.get("fault_points", []):
                code.setdefault(name, (path, line))
        rows = _catalog_rows(doc, self.SECTION)
        documented = {name for name, _ in rows}
        for name in sorted(code):
            if name not in documented:
                path, line = code[name]
                yield self.pdiag(
                    path, line,
                    f'fault point "{name}" has no row in the '
                    f"{self.DOC} catalog")
        # the stale-row direction is only sound when the faults runtime
        # itself is in the linted set — on a subtree run (``dct lint
        # tools/``) the call sites are simply out of view, not gone
        full_view = any(m == "faults" or m.endswith(".faults")
                        for m in index.modules)
        seen_rows = set()
        for name, line in rows:
            if not full_view or name in code or name in seen_rows:
                continue
            seen_rows.add(name)
            yield self.pdiag(
                self.DOC, line,
                f'catalog row "{name}" names a fault point that no '
                f"longer exists in code")
        index.summaries[self.rule] = (
            f"{len(code)} fault points <-> {len(documented)} catalog "
            f"rows")


@register
class MetricRegistryChecker(ProjectChecker):
    rule = "CONTRACT002"
    title = ("metric family type conflict, or family missing from "
             "docs/observability.md")
    hint = ("one family name -> one metric type across the tree; list "
            "every family backticked in the docs/observability.md "
            "metric catalog")

    DOC = "docs/observability.md"

    def project_check(self, index: ProjectIndex) -> Iterator[Diagnostic]:
        families: Dict[str, List[Tuple[str, str, int]]] = {}
        for path, facts in index.files.items():
            for name, kind, line in facts.get("metrics", []):
                families.setdefault(name, []).append((kind, path, line))
        conflicts = 0
        for name in sorted(families):
            defs = sorted(families[name], key=lambda d: (d[1], d[2]))
            kinds = {k for k, _p, _l in defs}
            if len(kinds) <= 1:
                continue
            conflicts += 1
            ref_kind, ref_path, ref_line = defs[0]
            flagged = set()
            for kind, path, line in defs[1:]:
                if kind == ref_kind or kind in flagged:
                    continue
                flagged.add(kind)
                yield self.pdiag(
                    path, line,
                    f'metric family "{name}" registered as {kind} '
                    f"here but as {ref_kind} at {ref_path}:{ref_line} "
                    f"— one name, one type")
        doc = _read_doc(index, self.DOC)
        documented = set()
        if doc is not None:
            for line in doc:
                documented.update(_BACKTICK.findall(line))
            for name in sorted(families):
                if name in documented:
                    continue
                _k, path, line = min(families[name],
                                     key=lambda d: (d[1], d[2]))
                yield self.pdiag(
                    path, line,
                    f'metric family "{name}" is not documented in '
                    f"{self.DOC}")
        index.summaries[self.rule] = (
            f"{len(families)} metric families, {conflicts} type "
            f"conflict(s)")


@register
class SchemaRoundTripChecker(ProjectChecker):
    rule = "CONTRACT003"
    title = "config schema key does not round-trip to ExperimentConfig"
    hint = ("a key validated by EXPERIMENT_SCHEMA must be consumed — "
            'an ExperimentConfig field or a raw.get("key") in the '
            "config package — and every field needs a schema key; "
            "PASSTHROUGH_KEYS / FIELD_TO_KEY_RENAMES in "
            "tools/dctlint/checkers/contracts.py hold the sanctioned "
            "exceptions")

    SCHEMA_NAME = "EXPERIMENT_SCHEMA"
    CONFIG_CLASS = "ExperimentConfig"

    def project_check(self, index: ProjectIndex) -> Iterator[Diagnostic]:
        for path, facts in sorted(index.files.items()):
            if not facts.get("schemas"):
                continue
            if not Path(path).as_posix().endswith("config/schema.py"):
                continue
            yield from self._check_package(index, path, facts)

    def _check_package(self, index: ProjectIndex, schema_path: str,
                       schema_facts) -> Iterator[Diagnostic]:
        pkg_dir = Path(schema_path).parent.as_posix()
        consumed = set()
        fields: List[str] = []
        cfg_path: Optional[str] = None
        cfg_line = 0
        for path, facts in index.files.items():
            if Path(path).parent.as_posix() != pkg_dir:
                continue
            consumed.update(facts.get("str_keys", []))
            cls_fields = facts.get("dataclass_fields", {})
            if self.CONFIG_CLASS in cls_fields:
                fields = cls_fields[self.CONFIG_CLASS]
                cfg_path = path
                cfg_line = facts.get("classes", {}).get(
                    self.CONFIG_CLASS, {}).get("line", 0)
        if cfg_path is None:
            return  # partial view: the config class is out of the
            # linted set, so "unconsumed" would be unsound
        for schema in schema_facts["schemas"]:
            if schema["name"] != self.SCHEMA_NAME:
                continue
            keys = set(schema["keys"])
            field_set = set(fields)
            for key in sorted(keys):
                if key in field_set or key in consumed \
                        or key in PASSTHROUGH_KEYS:
                    continue
                yield self.pdiag(
                    schema_path, schema["line"],
                    f'schema key "{key}" has no {self.CONFIG_CLASS} '
                    f"field and is never consumed in {pkg_dir}/")
            for field in fields:
                if field in INTERNAL_FIELDS:
                    continue
                key = FIELD_TO_KEY_RENAMES.get(field, field)
                if key not in keys:
                    yield self.pdiag(
                        cfg_path, cfg_line,
                        f'{self.CONFIG_CLASS} field "{field}" has '
                        f"no {self.SCHEMA_NAME} key (expected "
                        f'"{key}")')
            index.summaries[self.rule] = (
                f"{len(keys)} schema keys round-trip against "
                f"{len(fields)} {self.CONFIG_CLASS} fields")
