"""TIME001 — durations and deadlines must come from a monotonic clock.

``time.time()`` is wall clock: NTP slews and steps move it, so a delta
(``time.time() - t0``) or a deadline (``time.time() + timeout``) built on
it can be negative, jump hours, or never expire. ``time.monotonic()`` is
the duration clock. Wall-clock values that are *reported* (a ``"time":``
field in a shipped sample, a tfevents timestamp) are fine — only
arithmetic on ``time.time()`` is flagged.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from tools.dctlint.core import Checker, Diagnostic, FileContext, register

WALL_CLOCK = "time.time"
_LAMBDA = object()  # sentinel scope: node lives inside a lambda body


def _is_wall_call(ctx: FileContext, node: ast.AST) -> bool:
    return isinstance(node, ast.Call) \
        and ctx.qualified_name(node.func) == WALL_CLOCK


@register
class WallClockArithmetic(Checker):
    rule = "TIME001"
    title = "time.time() arithmetic (delta/deadline)"
    hint = ("use time.monotonic() for durations and deadlines; keep "
            "time.time() only for reported wall-clock timestamps")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        # per-scope: `now` may be wall clock in one function and monotonic
        # in its neighbor — taint must not leak across function boundaries.
        # Two linear passes over the prebuilt node list (grouping each
        # node under its innermost function via the parent links) replace
        # the old walk-per-scope, which was quadratic in nesting depth.
        wall_names: Dict[Optional[ast.AST], Set[str]] = {}
        for node in ctx.nodes:
            if isinstance(node, ast.Assign) \
                    and _is_wall_call(ctx, node.value):
                scope = self._scope_of(ctx, node)
                if scope is _LAMBDA:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wall_names.setdefault(scope, set()).add(t.id)

        def tainted(scope, expr: ast.AST) -> bool:
            if _is_wall_call(ctx, expr):
                return True
            return isinstance(expr, ast.Name) \
                and expr.id in wall_names.get(scope, ())

        for node in ctx.nodes:
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Add, ast.Sub)):
                scope = self._scope_of(ctx, node)
                if scope is _LAMBDA:
                    continue
                if tainted(scope, node.left) or tainted(scope, node.right):
                    yield self.diag(
                        ctx, node,
                        f"duration/deadline arithmetic on time.time() "
                        f"(`{ast.unparse(node)}`): wall clock can jump "
                        f"under NTP, so the result may be negative or "
                        f"never expire")
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, (ast.Add, ast.Sub)):
                scope = self._scope_of(ctx, node)
                if scope is not _LAMBDA and tainted(scope, node.value):
                    yield self.diag(
                        ctx, node,
                        f"duration accumulation from time.time() "
                        f"(`{ast.unparse(node)}`): use time.monotonic()")

    @staticmethod
    def _scope_of(ctx: FileContext, node: ast.AST):
        """Innermost enclosing function def, None at module scope, or
        the _LAMBDA sentinel (lambda bodies are not scopes here — the
        old walker skipped them entirely)."""
        cur = ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.Lambda):
                return _LAMBDA
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = ctx.parents.get(cur)
        return None
