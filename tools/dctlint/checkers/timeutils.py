"""TIME001 — durations and deadlines must come from a monotonic clock.

``time.time()`` is wall clock: NTP slews and steps move it, so a delta
(``time.time() - t0``) or a deadline (``time.time() + timeout``) built on
it can be negative, jump hours, or never expire. ``time.monotonic()`` is
the duration clock. Wall-clock values that are *reported* (a ``"time":``
field in a shipped sample, a tfevents timestamp) are fine — only
arithmetic on ``time.time()`` is flagged.
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from tools.dctlint.core import Checker, Diagnostic, FileContext, register

WALL_CLOCK = "time.time"


def _is_wall_call(ctx: FileContext, node: ast.AST) -> bool:
    return isinstance(node, ast.Call) \
        and ctx.qualified_name(node.func) == WALL_CLOCK


@register
class WallClockArithmetic(Checker):
    rule = "TIME001"
    title = "time.time() arithmetic (delta/deadline)"
    hint = ("use time.monotonic() for durations and deadlines; keep "
            "time.time() only for reported wall-clock timestamps")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        # per-scope: `now` may be wall clock in one function and monotonic
        # in its neighbor — taint must not leak across function boundaries
        scopes = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._check_scope(ctx, scope)

    def _scope_nodes(self, scope: ast.AST) -> Iterator[ast.AST]:
        """Walk a scope without descending into nested function scopes."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_scope(self, ctx: FileContext,
                     scope: ast.AST) -> Iterator[Diagnostic]:
        # names assigned directly from time.time() in THIS scope
        wall_names: Set[str] = set()
        for node in self._scope_nodes(scope):
            if isinstance(node, ast.Assign) \
                    and _is_wall_call(ctx, node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wall_names.add(t.id)

        def tainted(expr: ast.AST) -> bool:
            if _is_wall_call(ctx, expr):
                return True
            return isinstance(expr, ast.Name) and expr.id in wall_names

        for node in self._scope_nodes(scope):
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Add, ast.Sub)) \
                    and (tainted(node.left) or tainted(node.right)):
                yield self.diag(
                    ctx, node,
                    f"duration/deadline arithmetic on time.time() "
                    f"(`{ast.unparse(node)}`): wall clock can jump under "
                    f"NTP, so the result may be negative or never expire")
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, (ast.Add, ast.Sub)) \
                    and tainted(node.value):
                yield self.diag(
                    ctx, node,
                    f"duration accumulation from time.time() "
                    f"(`{ast.unparse(node)}`): use time.monotonic()")
