"""dctlint core — checker registry, suppressions, baseline, runner.

The framework generalizes ``tools/check_swallowed_exceptions.py`` (PR 2's
single-check gate) into a pluggable AST linter for the project's own
invariants: JAX tracing pitfalls, concurrency hygiene, clock discipline.
Go gets this from ``go vet`` + the race detector; a jitted multi-threaded
JAX pipeline needs the equivalent encoded per-project (docs/
static_analysis.md).

Concepts
--------
- **Checker**: a class with a ``rule`` id (e.g. ``JAX001``) and a
  ``check(ctx)`` generator over :class:`Diagnostic`. Register with
  ``@register``; the registry is what ``--list-checkers`` and ``--select``
  see.
- **FileContext**: one parsed file — source, lines, AST — plus import-alias
  resolution so ``np.sum``/``numpy.sum`` and ``import time as _time`` look
  identical to checkers (:meth:`FileContext.qualified_name`).
- **Suppression**: ``# dctlint: disable=JAX002 <reason>`` on the flagged
  line (or ``disable-next-line=`` on the line above). A reason is
  mandatory — a bare disable is itself reported (rule ``DCT000``).
- **Baseline**: a committed JSON of grandfathered violations keyed by
  (rule, path, message) with a required ``justification``; matching
  diagnostics are filtered so the gate only fails on *new* violations.
"""
from __future__ import annotations

import ast
import collections
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*dctlint:\s*disable(?P<next>-next-line)?="
    r"(?P<rules>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*|all)"
    r"(?P<reason>.*)$"
)


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: rule id, location, message, and a fix hint."""

    rule: str
    path: str
    line: int
    message: str
    hint: str = ""

    def format(self, *, show_hint: bool = True) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if show_hint and self.hint:
            s += f"\n    fix: {self.hint}"
        return s

    def baseline_key(self) -> Tuple[str, str, str]:
        # line numbers are deliberately excluded so a baseline survives
        # unrelated edits above the grandfathered site
        return (self.rule, Path(self.path).as_posix(), self.message)


class FileContext:
    """A parsed source file plus the alias tables checkers share.

    When ``module`` is known (the runner derives it from the path
    relative to the project root), relative imports resolve too:
    ``from .transfer import get_pool`` inside
    ``determined_clone_tpu.storage.cas`` lands in ``name_imports`` as
    ``determined_clone_tpu.storage.transfer.get_pool``, so cross-file
    call-graph edges survive the project's own import style.
    """

    def __init__(self, path: str, source: str, tree: ast.Module, *,
                 module: Optional[str] = None,
                 is_package: bool = False) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.module = module
        self.is_package = is_package
        # module alias -> canonical module ("np" -> "numpy"), and
        # imported name -> canonical dotted name ("scan" -> "jax.lax.scan")
        self.module_aliases: Dict[str, str] = {}
        self.name_imports: Dict[str, str] = {}
        # one traversal builds the flat node list (ast.walk order),
        # parent links, and the import tables — checkers iterate
        # ``self.nodes`` instead of re-walking the tree (the repeated
        # ast.walk per checker dominated the per-file pass)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.nodes: List[ast.AST] = []
        todo = collections.deque([tree])
        while todo:
            n = todo.popleft()
            self.nodes.append(n)
            for child in ast.iter_child_nodes(n):
                self.parents[child] = n
                todo.append(child)
        for node in self.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.name_imports[a.asname or a.name] = (
                        f"{node.module}.{a.name}")
            elif isinstance(node, ast.ImportFrom) and node.level > 0 \
                    and self.module:
                pkg = self.module.split(".")
                if not self.is_package:
                    pkg = pkg[:-1]
                cut = len(pkg) - (node.level - 1)
                if cut < 0:
                    continue  # beyond the root: unresolvable here
                base = pkg[:cut]
                target = ".".join(
                    base + ([node.module] if node.module else []))
                if not target:
                    continue
                for a in node.names:
                    self.name_imports[a.asname or a.name] = (
                        f"{target}.{a.name}")

    def qualified_name(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, with import
        aliases resolved: ``np.linalg.norm`` -> ``numpy.linalg.norm``,
        ``_time.time`` -> ``time.time``, ``scan`` (from ``from jax.lax
        import scan``) -> ``jax.lax.scan``. None for non-name expressions.
        """
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = node.id
        if root in self.name_imports:
            parts.append(self.name_imports[root])
        else:
            parts.append(self.module_aliases.get(root, root))
        return ".".join(reversed(parts))

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of enclosing function defs."""
        out: List[ast.AST] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out


class Checker:
    """Base class. Subclass, set ``rule``/``title``/``hint``, implement
    ``check``; decorate with ``@register`` to enroll."""

    rule: str = "DCT999"
    title: str = ""
    hint: str = ""
    #: True for whole-program checkers (see :class:`ProjectChecker`)
    project: bool = False

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        raise NotImplementedError

    def diag(self, ctx: FileContext, node: ast.AST, message: str,
             hint: Optional[str] = None) -> Diagnostic:
        return Diagnostic(rule=self.rule, path=ctx.path,
                          line=getattr(node, "lineno", 0), message=message,
                          hint=self.hint if hint is None else hint)


class ProjectChecker(Checker):
    """Whole-program checker: sees the :class:`ProjectIndex` built over
    every linted file instead of one FileContext at a time. The
    per-file hook is a no-op; implement ``project_check`` and yield
    diagnostics whose ``path`` is a display path from the index so
    per-line suppressions and the baseline apply as usual."""

    project = True

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        return iter(())

    def project_check(self, index) -> Iterator[Diagnostic]:
        raise NotImplementedError

    def pdiag(self, path: str, line: int, message: str,
              hint: Optional[str] = None) -> Diagnostic:
        return Diagnostic(rule=self.rule, path=path, line=line,
                          message=message,
                          hint=self.hint if hint is None else hint)


CHECKERS: Dict[str, Checker] = {}


def register(cls):
    """Class decorator enrolling a Checker in the global registry."""
    inst = cls()
    if inst.rule in CHECKERS:
        raise ValueError(f"duplicate checker rule {inst.rule}")
    CHECKERS[inst.rule] = inst
    return cls


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def parse_suppressions(lines: Sequence[str], path: str
                       ) -> Tuple[Dict[int, set], List[Diagnostic]]:
    """Per-line suppression map {1-based line -> set of rule ids (or
    {"all"})} plus DCT000 diagnostics for disables missing a reason."""
    suppressed: Dict[int, set] = {}
    bad: List[Diagnostic] = []
    for i, line in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",")}
        target = i + 1 if m.group("next") else i
        if not m.group("reason").strip():
            bad.append(Diagnostic(
                rule="DCT000", path=path, line=i,
                message=f"suppression of {','.join(sorted(rules))} has no "
                        f"reason",
                hint="write `# dctlint: disable=RULE <why this is safe>` — "
                     "an unexplained disable is as opaque as the violation"))
            continue  # a reasonless disable does not suppress
        suppressed.setdefault(target, set()).update(rules)
    return suppressed, bad


def _is_suppressed(d: Diagnostic, suppressed: Dict[int, set]) -> bool:
    rules = suppressed.get(d.line, ())
    return "all" in rules or d.rule in rules


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Optional[Path]) -> List[Dict[str, str]]:
    if path is None or not Path(path).exists():
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("violations", data if isinstance(data, list) else [])
    for e in entries:
        for k in ("rule", "path", "message"):
            if k not in e:
                raise ValueError(f"baseline entry missing {k!r}: {e}")
    return entries


def write_baseline(path: Path, diags: Iterable[Diagnostic]) -> int:
    entries = []
    seen = set()
    for d in sorted(diags, key=lambda d: (d.path, d.line, d.rule)):
        key = d.baseline_key()
        if key in seen:
            continue
        seen.add(key)
        entries.append({
            "rule": d.rule,
            "path": Path(d.path).as_posix(),
            "message": d.message,
            "justification": "TODO: justify or fix",
        })
    payload = {
        "_comment": "dctlint grandfathered violations. Each entry MUST "
                    "carry a real justification; new code never lands "
                    "here — fix or suppress inline with a reason.",
        "violations": entries,
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return len(entries)


def apply_baseline(diags: List[Diagnostic],
                   entries: List[Dict[str, str]]) -> List[Diagnostic]:
    keys = {(e["rule"], Path(e["path"]).as_posix(), e["message"])
            for e in entries}
    return [d for d in diags if d.baseline_key() not in keys]


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def lint_source(source: str, path: str = "<string>", *,
                select: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Lint one source string: parse, run the (selected) checkers, apply
    per-line suppressions. Baseline filtering happens in :func:`run`."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Diagnostic(rule="DCT001", path=path, line=e.lineno or 0,
                           message=f"syntax error: {e.msg}",
                           hint="dctlint only lints parseable files")]
    ctx = FileContext(path, source, tree)
    suppressed, diags = parse_suppressions(ctx.lines, path)
    checkers = [CHECKERS[r] for r in select] if select else \
        list(CHECKERS.values())
    for checker in checkers:
        diags.extend(checker.check(ctx))
    return [d for d in diags if not _is_suppressed(d, suppressed)]


def lint_file(path: Path, *, select: Optional[Sequence[str]] = None,
              relative_to: Optional[Path] = None) -> List[Diagnostic]:
    display = str(path)
    if relative_to is not None:
        try:
            display = str(Path(path).resolve().relative_to(
                Path(relative_to).resolve()))
        except ValueError:
            pass  # outside the root: keep the path as given
    return lint_source(Path(path).read_text(), display, select=select)


def iter_python_files(roots: Sequence[str]) -> Iterator[Path]:
    for root in roots:
        p = Path(root)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        else:
            yield p


# -- per-file worker (runs in the pool; must stay module-level) ------------

def _analyze_source(display: str, source: str, module: Optional[str],
                    is_package: bool) -> Dict[str, object]:
    """Parse + per-file checkers + facts extraction for one file.
    Returns a JSON/pickle-friendly dict (the cache entry payload)."""
    import tools.dctlint  # noqa: F401  (registers checkers in workers)
    from tools.dctlint import project as _project
    try:
        tree = ast.parse(source, filename=display)
    except SyntaxError as e:
        d = Diagnostic(rule="DCT001", path=display, line=e.lineno or 0,
                       message=f"syntax error: {e.msg}",
                       hint="dctlint only lints parseable files")
        return {"display": display, "diags": [dataclasses.asdict(d)],
                "facts": None}
    ctx = FileContext(display, source, tree, module=module,
                      is_package=is_package)
    suppressed, diags = parse_suppressions(ctx.lines, display)
    for checker in CHECKERS.values():
        if not checker.project:
            diags.extend(checker.check(ctx))
    kept = [dataclasses.asdict(d) for d in diags
            if not _is_suppressed(d, suppressed)]
    return {"display": display, "diags": kept,
            "facts": _project.extract_facts(ctx)}


def _analyze_args(args) -> Dict[str, object]:
    return _analyze_source(*args)


def _toolchain_signature() -> str:
    """Fingerprint of the dctlint sources themselves: a cache entry is
    stale the moment any checker or the extractor changes."""
    import hashlib
    h = hashlib.sha256()
    pkg = Path(__file__).resolve().parent
    for f in sorted(pkg.rglob("*.py")):
        st = f.stat()
        h.update(f"{f.name}:{st.st_mtime_ns}:{st.st_size};".encode())
    return h.hexdigest()[:16]


def _load_cache(path: Optional[Path]) -> Dict[str, dict]:
    if path is None or not Path(path).exists():
        return {}
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def _save_cache(path: Optional[Path], entries: Dict[str, dict]) -> None:
    if path is None:
        return
    tmp = Path(str(path) + ".tmp")
    try:
        with open(tmp, "w") as f:
            json.dump(entries, f)
        tmp.replace(path)
    except OSError:
        pass  # a cold cache next run is the only consequence


def _select_rules(select: Optional[Sequence[str]]) -> Optional[set]:
    if not select:
        return None
    # framework diagnostics always surface, whatever the selection
    return set(select) | {"DCT000", "DCT001"}


def run(paths: Sequence[str], *, select: Optional[Sequence[str]] = None,
        baseline: Optional[Path] = None,
        relative_to: Optional[Path] = None,
        jobs: int = 0,
        cache_path: Optional[Path] = None,
        changed_only: Optional[set] = None,
        stats: Optional[dict] = None) -> List[Diagnostic]:
    """Lint ``paths`` (files or directories), minus baseline entries.

    The per-file pass (parse, per-file checkers, facts extraction) runs
    over a worker pool (``jobs``: 0 auto, 1 serial) with a content-hash
    cache at ``cache_path``; the project pass then builds a
    :class:`ProjectIndex` from every file's facts and runs the
    project-scope checkers. ``changed_only`` (a set of display paths)
    filters *reporting* to touched files after the full-index project
    pass, so cross-file checks stay sound under ``--changed``.
    ``stats``, when a dict, is filled with wall/cache/summary info.
    """
    import hashlib
    import time
    from tools.dctlint import project as _project

    t0 = time.perf_counter()
    root = Path(relative_to).resolve() if relative_to else None
    work: List[Tuple[str, str, Optional[str], bool]] = []
    seen: set = set()
    for f in iter_python_files(paths):
        display = str(f)
        rel = None
        if root is not None:
            try:
                rel = Path(f).resolve().relative_to(root)
                display = str(rel)
            except ValueError:
                pass  # outside the root: keep the path as given
        if display in seen:
            continue
        seen.add(display)
        module, is_package = _project.module_name_for(
            rel.as_posix() if rel is not None else display)
        work.append((display, Path(f).read_text(), module, is_package))

    sig = _toolchain_signature()
    cache = _load_cache(cache_path) if cache_path else {}
    results: Dict[str, dict] = {}
    pending: List[Tuple[str, str, Optional[str], bool]] = []
    hashes: Dict[str, str] = {}
    for display, source, module, is_package in work:
        sha = hashlib.sha256(source.encode()).hexdigest()
        hashes[display] = sha
        entry = cache.get(display)
        if entry and entry.get("sha") == sha and entry.get("sig") == sig:
            results[display] = entry["result"]
        else:
            pending.append((display, source, module, is_package))
    cache_hits = len(results)

    if jobs == 0:
        import os
        jobs = min(8, os.cpu_count() or 1) if len(pending) >= 24 else 1
    if jobs > 1 and len(pending) > 1:
        import concurrent.futures
        try:
            with concurrent.futures.ProcessPoolExecutor(
                    max_workers=jobs) as pool:
                for res in pool.map(_analyze_args, pending,
                                    chunksize=8):
                    results[res["display"]] = res
        except (OSError, concurrent.futures.process.BrokenProcessPool):
            jobs = 1  # fall back below on whatever is still missing
    if jobs <= 1 or any(d not in results for d, *_ in pending):
        for args in pending:
            if args[0] not in results:
                results[args[0]] = _analyze_args(args)

    if cache_path is not None:
        _save_cache(cache_path, {
            display: {"sha": hashes[display], "sig": sig,
                      "result": results[display]}
            for display, *_ in work})

    rules = _select_rules(select)
    diags: List[Diagnostic] = []
    files_facts: Dict[str, dict] = {}
    for display, *_ in work:
        res = results[display]
        for d in res["diags"]:
            if rules is None or d["rule"] in rules:
                diags.append(Diagnostic(**d))
        if res.get("facts"):
            files_facts[display] = res["facts"]

    index = _project.build_index(files_facts, root=root)
    project_checkers = [c for c in CHECKERS.values() if c.project
                        and (select is None or c.rule in select)]
    for checker in project_checkers:
        for d in checker.project_check(index):
            if not _is_suppressed(d, index.suppressed_for(d.path)):
                diags.append(d)

    if changed_only is not None:
        changed = {Path(p).as_posix() for p in changed_only}
        diags = [d for d in diags
                 if Path(d.path).as_posix() in changed]
    if baseline is not None:
        diags = apply_baseline(diags, load_baseline(baseline))
    diags = sorted(diags, key=lambda d: (d.path, d.line, d.rule))
    if stats is not None:
        stats.update({
            "files": len(work),
            "cache_hits": cache_hits,
            "analyzed": len(pending),
            "jobs": max(jobs, 1),
            "wall_s": time.perf_counter() - t0,
            "project_checkers": sorted(c.rule for c in project_checkers),
            "summaries": dict(index.summaries),
            "violations": len(diags),
        })
    return diags
