"""dctlint core — checker registry, suppressions, baseline, runner.

The framework generalizes ``tools/check_swallowed_exceptions.py`` (PR 2's
single-check gate) into a pluggable AST linter for the project's own
invariants: JAX tracing pitfalls, concurrency hygiene, clock discipline.
Go gets this from ``go vet`` + the race detector; a jitted multi-threaded
JAX pipeline needs the equivalent encoded per-project (docs/
static_analysis.md).

Concepts
--------
- **Checker**: a class with a ``rule`` id (e.g. ``JAX001``) and a
  ``check(ctx)`` generator over :class:`Diagnostic`. Register with
  ``@register``; the registry is what ``--list-checkers`` and ``--select``
  see.
- **FileContext**: one parsed file — source, lines, AST — plus import-alias
  resolution so ``np.sum``/``numpy.sum`` and ``import time as _time`` look
  identical to checkers (:meth:`FileContext.qualified_name`).
- **Suppression**: ``# dctlint: disable=JAX002 <reason>`` on the flagged
  line (or ``disable-next-line=`` on the line above). A reason is
  mandatory — a bare disable is itself reported (rule ``DCT000``).
- **Baseline**: a committed JSON of grandfathered violations keyed by
  (rule, path, message) with a required ``justification``; matching
  diagnostics are filtered so the gate only fails on *new* violations.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*dctlint:\s*disable(?P<next>-next-line)?="
    r"(?P<rules>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*|all)"
    r"(?P<reason>.*)$"
)


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: rule id, location, message, and a fix hint."""

    rule: str
    path: str
    line: int
    message: str
    hint: str = ""

    def format(self, *, show_hint: bool = True) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if show_hint and self.hint:
            s += f"\n    fix: {self.hint}"
        return s

    def baseline_key(self) -> Tuple[str, str, str]:
        # line numbers are deliberately excluded so a baseline survives
        # unrelated edits above the grandfathered site
        return (self.rule, Path(self.path).as_posix(), self.message)


class FileContext:
    """A parsed source file plus the alias tables checkers share."""

    def __init__(self, path: str, source: str, tree: ast.Module) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # module alias -> canonical module ("np" -> "numpy"), and
        # imported name -> canonical dotted name ("scan" -> "jax.lax.scan")
        self.module_aliases: Dict[str, str] = {}
        self.name_imports: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.name_imports[a.asname or a.name] = (
                        f"{node.module}.{a.name}")
        # parent links let checkers walk enclosing scopes
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    def qualified_name(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, with import
        aliases resolved: ``np.linalg.norm`` -> ``numpy.linalg.norm``,
        ``_time.time`` -> ``time.time``, ``scan`` (from ``from jax.lax
        import scan``) -> ``jax.lax.scan``. None for non-name expressions.
        """
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = node.id
        if root in self.name_imports:
            parts.append(self.name_imports[root])
        else:
            parts.append(self.module_aliases.get(root, root))
        return ".".join(reversed(parts))

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of enclosing function defs."""
        out: List[ast.AST] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out


class Checker:
    """Base class. Subclass, set ``rule``/``title``/``hint``, implement
    ``check``; decorate with ``@register`` to enroll."""

    rule: str = "DCT999"
    title: str = ""
    hint: str = ""

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        raise NotImplementedError

    def diag(self, ctx: FileContext, node: ast.AST, message: str,
             hint: Optional[str] = None) -> Diagnostic:
        return Diagnostic(rule=self.rule, path=ctx.path,
                          line=getattr(node, "lineno", 0), message=message,
                          hint=self.hint if hint is None else hint)


CHECKERS: Dict[str, Checker] = {}


def register(cls):
    """Class decorator enrolling a Checker in the global registry."""
    inst = cls()
    if inst.rule in CHECKERS:
        raise ValueError(f"duplicate checker rule {inst.rule}")
    CHECKERS[inst.rule] = inst
    return cls


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def parse_suppressions(lines: Sequence[str], path: str
                       ) -> Tuple[Dict[int, set], List[Diagnostic]]:
    """Per-line suppression map {1-based line -> set of rule ids (or
    {"all"})} plus DCT000 diagnostics for disables missing a reason."""
    suppressed: Dict[int, set] = {}
    bad: List[Diagnostic] = []
    for i, line in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",")}
        target = i + 1 if m.group("next") else i
        if not m.group("reason").strip():
            bad.append(Diagnostic(
                rule="DCT000", path=path, line=i,
                message=f"suppression of {','.join(sorted(rules))} has no "
                        f"reason",
                hint="write `# dctlint: disable=RULE <why this is safe>` — "
                     "an unexplained disable is as opaque as the violation"))
            continue  # a reasonless disable does not suppress
        suppressed.setdefault(target, set()).update(rules)
    return suppressed, bad


def _is_suppressed(d: Diagnostic, suppressed: Dict[int, set]) -> bool:
    rules = suppressed.get(d.line, ())
    return "all" in rules or d.rule in rules


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Optional[Path]) -> List[Dict[str, str]]:
    if path is None or not Path(path).exists():
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("violations", data if isinstance(data, list) else [])
    for e in entries:
        for k in ("rule", "path", "message"):
            if k not in e:
                raise ValueError(f"baseline entry missing {k!r}: {e}")
    return entries


def write_baseline(path: Path, diags: Iterable[Diagnostic]) -> int:
    entries = []
    seen = set()
    for d in sorted(diags, key=lambda d: (d.path, d.line, d.rule)):
        key = d.baseline_key()
        if key in seen:
            continue
        seen.add(key)
        entries.append({
            "rule": d.rule,
            "path": Path(d.path).as_posix(),
            "message": d.message,
            "justification": "TODO: justify or fix",
        })
    payload = {
        "_comment": "dctlint grandfathered violations. Each entry MUST "
                    "carry a real justification; new code never lands "
                    "here — fix or suppress inline with a reason.",
        "violations": entries,
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return len(entries)


def apply_baseline(diags: List[Diagnostic],
                   entries: List[Dict[str, str]]) -> List[Diagnostic]:
    keys = {(e["rule"], Path(e["path"]).as_posix(), e["message"])
            for e in entries}
    return [d for d in diags if d.baseline_key() not in keys]


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def lint_source(source: str, path: str = "<string>", *,
                select: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Lint one source string: parse, run the (selected) checkers, apply
    per-line suppressions. Baseline filtering happens in :func:`run`."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Diagnostic(rule="DCT001", path=path, line=e.lineno or 0,
                           message=f"syntax error: {e.msg}",
                           hint="dctlint only lints parseable files")]
    ctx = FileContext(path, source, tree)
    suppressed, diags = parse_suppressions(ctx.lines, path)
    checkers = [CHECKERS[r] for r in select] if select else \
        list(CHECKERS.values())
    for checker in checkers:
        diags.extend(checker.check(ctx))
    return [d for d in diags if not _is_suppressed(d, suppressed)]


def lint_file(path: Path, *, select: Optional[Sequence[str]] = None,
              relative_to: Optional[Path] = None) -> List[Diagnostic]:
    display = str(path)
    if relative_to is not None:
        try:
            display = str(Path(path).resolve().relative_to(
                Path(relative_to).resolve()))
        except ValueError:
            pass  # outside the root: keep the path as given
    return lint_source(Path(path).read_text(), display, select=select)


def iter_python_files(roots: Sequence[str]) -> Iterator[Path]:
    for root in roots:
        p = Path(root)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        else:
            yield p


def run(paths: Sequence[str], *, select: Optional[Sequence[str]] = None,
        baseline: Optional[Path] = None,
        relative_to: Optional[Path] = None) -> List[Diagnostic]:
    """Lint ``paths`` (files or directories), minus baseline entries."""
    diags: List[Diagnostic] = []
    for f in iter_python_files(paths):
        diags.extend(lint_file(f, select=select, relative_to=relative_to))
    if baseline is not None:
        diags = apply_baseline(diags, load_baseline(baseline))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))
