"""Whole-program analysis for dctlint (ISSUE 18): per-file facts and the
ProjectIndex.

PR 3's dctlint is per-file AST only: it cannot see that ``ServingFleet``
holds ``_lock`` while calling into a ``Replica`` whose ``drain`` blocks
on the engine condition, or that a ``faults.point("x")`` site has no row
in docs/fault_tolerance.md. This module adds the project pass:

- :func:`extract_facts` reduces one parsed file to a JSON-serializable
  **facts** dict — symbols (classes, functions, typed ``self`` attrs,
  locks/queues/events), alias-resolved call descriptors per function,
  lock-acquisition events with the lexically-held lock stack, candidate
  blocking calls, fault points, metric families, jit/shard_map/scan
  trace targets, config-schema literals, and the per-line suppression
  map. Facts are small and picklable, so the per-file pass can run in a
  worker pool and be cached keyed by content hash (see core.run).
- :class:`ProjectIndex` stitches the facts of every file into a symbol
  table and an import-aware call graph (``self.m`` via the class MRO,
  typed attributes/locals via recorded constructor calls, bare names via
  module scope, imports via alias resolution including relative
  imports), then offers the primitives project-scope checkers build on:
  :meth:`resolve_call`, :meth:`resolve_lockref`,
  :meth:`eventual_acquires`, :meth:`eventual_blocking`.

Design notes (docs/static_analysis.md "Whole-program analysis"):

- The call graph is *may-call* and deliberately over-approximate, but
  every edge carries a confidence bit: **certain** edges come from
  ``self`` calls, typed receivers, module functions and imports;
  **heuristic** edges come from method-name matching on untyped
  receivers and are capped (a name defined on more than
  ``HEURISTIC_CLASS_CAP`` classes, or in ``HEURISTIC_STOPLIST``, makes
  no edge). Checkers choose which confidence they propagate over.
- Lock identity is the *defining site*: ``module.Class.attr`` or
  ``module.varname``. ``Condition(self._lock)`` aliases to the wrapped
  lock's identity, so waiting on the condition and holding the lock are
  the same lock to the analysis (storage/transfer.py does exactly
  this).
"""
from __future__ import annotations

import ast
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

# Bump whenever the shape of the facts dict changes: the content-hash
# cache in core.run keys on (source sha, FACTS_VERSION, toolchain sig).
FACTS_VERSION = 1

# Constructor qualified-names that give a ``self`` attribute (or module
# global) a kind the concurrency checkers understand.
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Event": "event",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "threading.Thread": "thread",
    "queue.Queue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "queue.SimpleQueue": "queue",
}
_HELD_KINDS = {"lock", "rlock", "condition"}

# Entry points whose first function argument is traced by XLA.
TRACE_ENTRIES = {
    "jax.jit", "jax.pjit", "pjit", "jax.experimental.pjit.pjit",
    "jax.pmap", "jax.shard_map", "shard_map",
    "jax.experimental.shard_map.shard_map", "jax.lax.scan",
}

# Attribute-call fallback: a bare ``x.m()`` with unknown receiver type
# only resolves heuristically when ``m`` is defined on few classes and
# is not a ubiquitous protocol name.
HEURISTIC_CLASS_CAP = 3
HEURISTIC_STOPLIST = frozenset({
    "get", "put", "set", "add", "remove", "close", "start", "stop",
    "run", "join", "items", "keys", "values", "append", "pop",
    "update", "copy", "clear", "read", "write", "send", "recv",
    "result", "wait", "acquire", "release", "notify", "notify_all",
    "observe", "inc", "dec", "format", "validate", "dump", "load",
    "open", "next", "reset", "flush", "name", "info", "debug",
    "warning", "error", "exists", "submit", "encode", "decode",
})

_METRIC_METHODS = {"counter", "gauge", "histogram"}
_METRIC_CLASSES = {"Counter": "counter", "Gauge": "gauge",
                   "Histogram": "histogram"}
_HTTP_PREFIXES = ("requests.", "urllib.request.", "http.client.")
_SUBPROCESS_BLOCKING = {"subprocess.run", "subprocess.check_call",
                        "subprocess.check_output", "subprocess.call"}


def module_name_for(display_path: str) -> Tuple[Optional[str], bool]:
    """(dotted module name, is_package) for a root-relative path.

    ``determined_clone_tpu/serving/fleet.py`` ->
    ``determined_clone_tpu.serving.fleet``; ``pkg/__init__.py`` ->
    ``pkg`` (is_package=True); non-``.py`` or absolute-ish paths fall
    back to the stem so fixture files still get a namespace.
    """
    p = display_path.replace("\\", "/")
    if not p.endswith(".py"):
        return None, False
    parts = [s for s in p[:-3].split("/") if s and s != "."]
    if not parts:
        return None, False
    if parts[-1] == "__init__":
        parts = parts[:-1]
        return (".".join(parts) or None), True
    return ".".join(parts), False


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _attr_chain(func: ast.Attribute) -> Tuple[Optional[str], List[str]]:
    """(base Name id or None, attribute parts outermost-last)."""
    chain: List[str] = []
    cur: ast.AST = func
    while isinstance(cur, ast.Attribute):
        chain.append(cur.attr)
        cur = cur.value
    chain.reverse()
    if isinstance(cur, ast.Name):
        return cur.id, chain
    return None, chain


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or _kw(call, "timeout") is not None


class _Extractor:
    """One file -> facts dict. Drives an explicit recursive walk so the
    lexically-held lock stack is tracked through ``with`` nesting and
    reset at nested function boundaries (a closure defined under a lock
    does not *run* under it)."""

    def __init__(self, ctx) -> None:
        self.ctx = ctx
        self.module: Optional[str] = getattr(ctx, "module", None)
        if self.module is None:
            self.module, _ = module_name_for(ctx.path)
        self.functions: Dict[str, Dict[str, Any]] = {}
        self.classes: Dict[str, Dict[str, Any]] = {}
        self.module_locks: Dict[str, Dict[str, Any]] = {}
        self.fault_points: List[List[Any]] = []
        self.metrics: List[List[Any]] = []
        self.jit_targets: List[Dict[str, Any]] = []
        self.schemas: List[Dict[str, Any]] = []
        self.dataclass_fields: Dict[str, List[str]] = {}
        self.str_keys: Set[str] = set()
        # module-scope names defined in this file: name -> local path
        self.module_defs: Dict[str, str] = {}
        self.module_classes: Set[str] = set()
        # transient per-function state
        self._fn: Optional[Dict[str, Any]] = None
        self._cls: Optional[str] = None
        self._held: List[List[Any]] = []
        self._local_types: Dict[str, str] = {}
        self._nested: Dict[str, str] = {}
        self._globals: Set[str] = set()

    # -- top level ----------------------------------------------------

    def extract(self) -> Dict[str, Any]:
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_defs[stmt.name] = stmt.name
            elif isinstance(stmt, ast.ClassDef):
                self.module_defs[stmt.name] = stmt.name
                self.module_classes.add(stmt.name)
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit_function(stmt, stmt.name, None)
            elif isinstance(stmt, ast.ClassDef):
                self._visit_class(stmt)
            else:
                self._module_level_stmt(stmt)
        suppressed, _ = _parse_suppressions(self.ctx)
        return {
            "v": FACTS_VERSION,
            "path": self.ctx.path,
            "module": self.module,
            "name_imports": dict(self.ctx.name_imports),
            "module_aliases": dict(self.ctx.module_aliases),
            "classes": self.classes,
            "module_locks": self.module_locks,
            "functions": self.functions,
            "fault_points": self.fault_points,
            "metrics": self.metrics,
            "jit_targets": self.jit_targets,
            "schemas": self.schemas,
            "dataclass_fields": self.dataclass_fields,
            "str_keys": sorted(self.str_keys),
            "suppressed": {str(k): sorted(v)
                           for k, v in suppressed.items()},
        }

    def _module_level_stmt(self, stmt: ast.stmt) -> None:
        # module-global locks/queues: ``_pool_lock = threading.Lock()``
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Call):
            q = self.ctx.qualified_name(stmt.value.func)
            kind = LOCK_FACTORIES.get(q or "")
            if kind:
                self.module_locks[stmt.targets[0].id] = {
                    "kind": kind, "line": stmt.lineno,
                    "alias_of": self._cond_alias(stmt.value, None),
                }
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id.endswith("_SCHEMA") \
                and isinstance(stmt.value, ast.Dict):
            # walk the AST instead of literal_eval: property values may
            # reference other *_SCHEMA names, only the keys must be
            # constant strings
            props = None
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if _const_str(k) == "properties" \
                        and isinstance(v, ast.Dict):
                    props = v
                    break
            if props is not None:
                keys = [s for s in (_const_str(k) for k in props.keys)
                        if s is not None]
                self.schemas.append({
                    "name": stmt.targets[0].id,
                    "line": stmt.lineno,
                    "keys": sorted(keys),
                })
        # still collect calls (metrics/fault points at module scope)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._walk_expr(child)

    # -- classes ------------------------------------------------------

    def _visit_class(self, node: ast.ClassDef, prefix: str = "") -> None:
        name = prefix + node.name
        bases = [b for b in
                 (self.ctx.qualified_name(x) for x in node.bases) if b]
        info = {"line": node.lineno, "bases": bases,
                "attrs": {}, "methods": []}
        self.classes[name] = info
        if self._is_dataclass(node):
            fields = [s.target.id for s in node.body
                      if isinstance(s, ast.AnnAssign)
                      and isinstance(s.target, ast.Name)]
            self.dataclass_fields[name] = fields
        self._prescan_class_attrs(node, name)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info["methods"].append(stmt.name)
                self._visit_function(stmt, f"{name}.{stmt.name}", name)
            elif isinstance(stmt, ast.ClassDef):
                self._visit_class(stmt, prefix=name + ".")

    def _prescan_class_attrs(self, node: ast.ClassDef,
                             name: str) -> None:
        """Collect ``self.X = factory()`` attrs from every method before
        any body is analyzed, so a method defined above ``__init__`` can
        still classify ``self._cond.wait()`` receivers."""
        saved = self._cls
        self._cls = name
        try:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self._class_attr_assign(
                            t.attr, sub.value, t.lineno)
        finally:
            self._cls = saved

    def _is_dataclass(self, node: ast.ClassDef) -> bool:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            q = self.ctx.qualified_name(target)
            if q in ("dataclasses.dataclass", "dataclass"):
                return True
        return False

    # -- functions ----------------------------------------------------

    def _visit_function(self, node, local: str,
                        cls: Optional[str]) -> None:
        outer = (self._fn, self._cls, self._held, self._local_types,
                 self._nested, self._globals)
        decorators = []
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            q = self.ctx.qualified_name(target)
            if q:
                decorators.append(q)
            if isinstance(dec, ast.Call):  # @partial(jax.jit, ...)
                inner = self._unwrap_partial_entry(dec)
                if inner:
                    decorators.append(inner)
        fn = {"line": node.lineno, "cls": cls, "calls": [],
              "acquires": [], "blocking": [], "stores_self": [],
              "reads_self": [], "stores_global": [],
              "decorators": decorators}
        self.functions[local] = fn
        self._fn, self._cls = fn, cls
        self._held = []
        self._local_types = {}
        self._nested = {}
        self._globals = set()
        if any(q in TRACE_ENTRIES for q in decorators):
            self.jit_targets.append({"t": ["l", local],
                                     "line": node.lineno,
                                     "entry": "decorator"})
        self._prescan_nested(node.body, local)
        self._walk_stmts(node.body, local, cls)
        (self._fn, self._cls, self._held, self._local_types,
         self._nested, self._globals) = outer

    def _unwrap_partial_entry(self, call: ast.Call) -> Optional[str]:
        q = self.ctx.qualified_name(call.func)
        if q in ("functools.partial", "partial") and call.args:
            inner = self.ctx.qualified_name(call.args[0])
            if inner in TRACE_ENTRIES:
                return inner
        return None

    def _prescan_nested(self, body, local: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._nested[stmt.name] = f"{local}.<locals>.{stmt.name}"

    # -- statement walk with a held-lock stack ------------------------

    def _walk_stmts(self, body, local: str, cls: Optional[str]) -> None:
        i = 0
        while i < len(body):
            stmt = body[i]
            consumed = self._maybe_acquire_try(body, i, local, cls)
            if consumed:
                i += consumed
                continue
            self._walk_stmt(stmt, local, cls)
            i += 1

    def _maybe_acquire_try(self, body, i, local, cls) -> int:
        """Handle ``X.acquire(); try: ... finally: X.release()`` as a
        lock region (the shape CONC002 enforces). Returns number of
        statements consumed, 0 if the pattern does not match."""
        stmt = body[i]
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)):
            return 0
        func = stmt.value.func
        if not (isinstance(func, ast.Attribute)
                and func.attr == "acquire"):
            return 0
        ref = self._lockref(func.value, cls)
        if ref is None or i + 1 >= len(body) \
                or not isinstance(body[i + 1], ast.Try):
            return 0
        self._record_acquire(ref, stmt.lineno)
        self._held.append(ref)
        try:
            self._walk_stmt(body[i + 1], local, cls)
        finally:
            self._held.pop()
        return 2

    def _walk_stmt(self, stmt: ast.stmt, local: str,
                   cls: Optional[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested_local = self._nested.get(
                stmt.name, f"{local}.<locals>.{stmt.name}")
            self._visit_function(stmt, nested_local, cls)
            return
        if isinstance(stmt, ast.ClassDef):
            return  # classes defined inside functions: out of scope
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._walk_with(stmt, local, cls)
            return
        if isinstance(stmt, (ast.Global, ast.Nonlocal)):
            self._globals.update(stmt.names)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._walk_assign(stmt)
            return
        # generic: walk child expressions, recurse into child stmt lists
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._walk_expr(child)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(child, local, cls)
            elif isinstance(child, (ast.excepthandler, ast.match_case)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.stmt):
                        self._walk_stmt(sub, local, cls)
                    elif isinstance(sub, ast.expr):
                        self._walk_expr(sub)

    def _walk_with(self, stmt, local: str, cls: Optional[str]) -> None:
        pushed = 0
        for item in stmt.items:
            ref = self._lockref(item.context_expr, cls)
            if ref is not None:
                self._record_acquire(ref, item.context_expr.lineno)
                self._held.append(ref)
                pushed += 1
            else:
                self._walk_expr(item.context_expr)
        try:
            self._walk_stmts(stmt.body, local, cls)
        finally:
            for _ in range(pushed):
                self._held.pop()

    def _record_acquire(self, ref, line: int) -> None:
        if self._fn is not None:
            self._fn["acquires"].append(
                {"l": ref, "line": line, "held": list(self._held)})

    def _lockref(self, expr: ast.AST,
                 cls: Optional[str]) -> Optional[List[Any]]:
        """A lock-identity reference for an acquired expression:
        ``["c", Class, attr]`` for ``self.attr``, ``["g", name]`` for a
        module-level lock, ``["i", dotted]`` for an imported one."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls:
            return ["c", cls, expr.attr]
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return ["g", expr.id]
            dotted = self.ctx.name_imports.get(expr.id)
            if dotted:
                return ["i", dotted]
        return None

    # -- assignments --------------------------------------------------

    def _walk_assign(self, stmt) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        if value is not None:
            self._walk_expr(value)
        for t in targets:
            self._assign_target(t, value, aug=isinstance(
                stmt, ast.AugAssign))

    def _assign_target(self, t, value, *, aug: bool) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._assign_target(el, None, aug=aug)
            return
        fn = self._fn
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
            if t.value.id == "self" and self._cls:
                if fn is not None:
                    fn["stores_self"].append([t.attr, t.lineno])
                    if aug:
                        fn["reads_self"].append([t.attr, t.lineno])
                self._class_attr_assign(t.attr, value, t.lineno)
            elif t.value.id in self.ctx.module_aliases:
                # ``mod.GLOBAL = x`` — a module-attribute store
                if fn is not None:
                    dotted = self.ctx.qualified_name(t)
                    fn["stores_global"].append(
                        [dotted or t.attr, t.lineno])
            return
        if isinstance(t, ast.Name):
            if fn is not None and t.id in self._globals:
                fn["stores_global"].append([t.id, t.lineno])
            inst = self._instance_type(value)
            if inst:
                self._local_types[t.id] = inst
            elif not aug:
                self._local_types.pop(t.id, None)
            return
        if isinstance(t, ast.Subscript):
            self._walk_expr(t.value)
            self._walk_expr(t.slice)
            key = _const_str(t.slice)
            if key is not None:
                self.str_keys.add(key)

    def _class_attr_assign(self, attr: str, value, line: int) -> None:
        cls = self._cls
        if cls is None or cls not in self.classes:
            return
        attrs = self.classes[cls]["attrs"]
        if isinstance(value, ast.Call):
            q = self.ctx.qualified_name(value.func)
            kind = LOCK_FACTORIES.get(q or "")
            if kind:
                attrs[attr] = {"kind": kind, "line": line,
                               "alias_of": self._cond_alias(value, cls)}
                return
            inst = self._instance_type(value)
            if inst and attr not in attrs:
                attrs[attr] = {"kind": "instance", "of": inst,
                               "line": line}
                return
        # plain data attribute: remember the store site for mutability
        if attr not in attrs:
            attrs[attr] = {"kind": "data", "line": line}

    def _cond_alias(self, call: ast.Call,
                    cls: Optional[str]) -> Optional[List[Any]]:
        """``threading.Condition(self._lock)`` -> the wrapped lockref."""
        q = self.ctx.qualified_name(call.func)
        if q != "threading.Condition" or not call.args:
            return None
        return self._lockref(call.args[0], cls)

    def _instance_type(self, value) -> Optional[str]:
        """``v = ClassName(...)`` -> dotted class name, for receiver
        typing. Only names that look like classes (Capitalized last
        part) count."""
        if not isinstance(value, ast.Call):
            return None
        func = value.func
        if isinstance(func, ast.Name) and func.id in self.module_classes:
            base = f"{self.module}." if self.module else ""
            return base + func.id
        q = self.ctx.qualified_name(func)
        if q and "." in q:
            last = q.rsplit(".", 1)[1]
            if last[:1].isupper() and q.split(".", 1)[0] not in (
                    "typing", "collections"):
                return q
        elif q and q[:1].isupper():
            return q
        return None

    # -- expressions --------------------------------------------------

    def _walk_expr(self, expr: ast.AST) -> None:
        if expr is None:
            return
        if isinstance(expr, ast.Lambda):
            # a lambda runs later: analyze its body with no held locks
            saved = self._held
            self._held = []
            try:
                self._walk_expr(expr.body)
            finally:
                self._held = saved
            return
        if isinstance(expr, ast.Call):
            self._walk_call(expr)
            return
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and self._fn is not None:
                self._fn["reads_self"].append([expr.attr, expr.lineno])
            self._walk_expr(expr.value)
            return
        if isinstance(expr, ast.Subscript):
            key = _const_str(expr.slice)
            if key is not None:
                self.str_keys.add(key)
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._walk_expr(child)
            elif isinstance(child, ast.comprehension):
                self._walk_expr(child.iter)
                for cond in child.ifs:
                    self._walk_expr(cond)

    def _walk_call(self, call: ast.Call) -> None:
        desc = self._call_desc(call.func)
        if desc is not None and self._fn is not None:
            rec = [desc, call.lineno]
            if self._held:
                rec.append(list(self._held))
            self._fn["calls"].append(rec)
        # ``raw.get("key", ...)`` is dict consumption just like
        # ``raw["key"]`` — CONTRACT003 counts both
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "get" and call.args:
            key = _const_str(call.args[0])
            if key is not None:
                self.str_keys.add(key)
        self._domain_facts(call, desc)
        blk = self._blocking_event(call, desc)
        if blk is not None and self._fn is not None:
            blk["line"] = call.lineno
            blk["held"] = list(self._held)
            self._fn["blocking"].append(blk)
        # receiver attribute reads (``self.router.submit()`` reads
        # ``self.router``) and argument expressions
        if isinstance(call.func, ast.Attribute):
            self._walk_expr(call.func.value)
        for a in call.args:
            self._walk_expr(a)
        for k in call.keywords:
            self._walk_expr(k.value)

    def _call_desc(self, func: ast.AST) -> Optional[List[Any]]:
        """Call descriptor for later graph resolution:
        ``["l", localpath]`` same-file def, ``["q", dotted]`` resolved
        import, ``["s", meth]`` self method, ``["sa", attr, meth]``
        method on a self attribute, ``["t", classdotted, meth]`` method
        on a constructor-typed local, ``["m", meth]`` unknown-receiver
        method (heuristic), ``["n", name]`` unresolved bare name."""
        if isinstance(func, ast.Name):
            nid = func.id
            if nid in self._nested:
                return ["l", self._nested[nid]]
            if nid in self.module_defs:
                return ["l", self.module_defs[nid]]
            if nid in self.ctx.name_imports:
                return ["q", self.ctx.name_imports[nid]]
            if nid in self.ctx.module_aliases:
                return ["q", self.ctx.module_aliases[nid]]
            return ["n", nid]
        if isinstance(func, ast.Attribute):
            base, chain = _attr_chain(func)
            if base == "self" and self._cls:
                if len(chain) == 1:
                    return ["s", chain[0]]
                if len(chain) == 2:
                    return ["sa", chain[0], chain[1]]
                return ["m", chain[-1]]
            if base is not None and len(chain) == 1 \
                    and base in self._local_types:
                return ["t", self._local_types[base], chain[0]]
            if base is not None and (base in self.ctx.name_imports
                                     or base in self.ctx.module_aliases):
                q = self.ctx.qualified_name(func)
                if q:
                    return ["q", q]
            if base is not None and base in self.module_classes:
                mod = f"{self.module}." if self.module else ""
                return ["q", f"{mod}{base}." + ".".join(chain)]
            return ["m", chain[-1]]
        return None

    # -- domain facts: faults, metrics, jit targets -------------------

    def _domain_facts(self, call: ast.Call,
                      desc: Optional[List[Any]]) -> None:
        q = desc[1] if desc and desc[0] == "q" else None
        # fault points: ``faults.point("name")`` however imported
        if q and (q == "faults.point" or q.endswith(".faults.point")):
            name = _const_str(call.args[0]) if call.args else None
            if name:
                self.fault_points.append([name, call.lineno])
        # fault names passed as configuration: ``fault_store="cas..."``
        for k in call.keywords:
            if k.arg and k.arg.startswith("fault_"):
                name = _const_str(k.value)
                if name:
                    self.fault_points.append([name, k.value.lineno])
        # metric families
        self._metric_fact(call, desc, q)
        # trace entry points: jit(f) / shard_map(f, ...) / scan(f, ...)
        entry = q if q in TRACE_ENTRIES else None
        if entry is None and q in ("functools.partial", "partial") \
                and call.args:
            inner = self.ctx.qualified_name(call.args[0])
            if inner in TRACE_ENTRIES:
                # partial(jax.jit, static_argnums=...)(f) — rare; the
                # outer call carries the traced fn, not this one
                entry = None
        if entry is not None and call.args:
            target = self._trace_target(call.args[0])
            if target is not None:
                self.jit_targets.append(
                    {"t": target, "line": call.lineno, "entry": entry})

    def _trace_target(self, arg: ast.AST) -> Optional[List[Any]]:
        if isinstance(arg, ast.Call):
            q = self.ctx.qualified_name(arg.func)
            if q in ("functools.partial", "partial") and arg.args:
                return self._trace_target(arg.args[0])
            return None
        if isinstance(arg, ast.Lambda):
            return None  # lexical JAX001 already covers lambda bodies
        if isinstance(arg, ast.Name):
            nid = arg.id
            if nid in self._nested:
                return ["l", self._nested[nid]]
            if nid in self.module_defs:
                return ["l", self.module_defs[nid]]
            if nid in self.ctx.name_imports:
                return ["q", self.ctx.name_imports[nid]]
            return ["n", nid]
        if isinstance(arg, ast.Attribute):
            base, chain = _attr_chain(arg)
            if base == "self" and len(chain) == 1:
                return ["s", chain[0]]
            q = self.ctx.qualified_name(arg)
            if q and base is not None and (
                    base in self.ctx.name_imports
                    or base in self.ctx.module_aliases):
                return ["q", q]
        return None

    def _metric_fact(self, call: ast.Call, desc, q) -> None:
        name = _const_str(call.args[0]) if call.args else None
        if name is None:
            return
        if desc and desc[0] in ("s", "sa", "m", "t"):
            meth = desc[-1]
            if meth in _METRIC_METHODS:
                self.metrics.append([name, meth, call.lineno])
                return
        last = None
        if q:
            root = q.split(".", 1)[0]
            if root in ("collections", "typing"):
                return
            last = q.rsplit(".", 1)[-1]
        elif desc and desc[0] in ("l", "n"):
            last = desc[1]
        if last in _METRIC_CLASSES:
            self.metrics.append(
                [name, _METRIC_CLASSES[last], call.lineno])

    # -- blocking-call classification ---------------------------------

    def _blocking_event(self, call: ast.Call,
                        desc) -> Optional[Dict[str, Any]]:
        q = desc[1] if desc and desc[0] == "q" else None
        if q == "time.sleep":
            return {"api": "time.sleep", "kind": "sleep"}
        if q and (q == "faults.point" or q.endswith(".faults.point")):
            # a delay-action fault rule sleeps inside point(); holding
            # a lock across it stalls every thread sharing the lock
            return {"api": "faults.point", "kind": "sleep"}
        if q == "jax.block_until_ready":
            return {"api": q, "kind": "block_until_ready"}
        if q in _SUBPROCESS_BLOCKING or q == "socket.create_connection":
            return {"api": q, "kind": "http"}
        if q and q.startswith(_HTTP_PREFIXES):
            return {"api": q, "kind": "http"}
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        if meth == "block_until_ready":
            return {"api": ".block_until_ready()",
                    "kind": "block_until_ready"}
        recv = self._recv_kind(call.func.value)
        if recv is None:
            return None
        ref, kind = recv
        if kind == "queue" and meth in ("get", "put"):
            blk = _kw(call, "block")
            if isinstance(blk, ast.Constant) and blk.value is False:
                return None
            return {"api": f"Queue.{meth}", "kind": "queue", "ref": ref,
                    "bounded": _has_timeout(call)}
        if kind == "condition" and meth in ("wait", "wait_for"):
            return {"api": f"Condition.{meth}", "kind": "cond_wait",
                    "ref": ref}
        if kind == "event" and meth == "wait":
            return {"api": "Event.wait", "kind": "event_wait",
                    "ref": ref, "bounded": _has_timeout(call)}
        if kind == "thread" and meth == "join":
            return {"api": "Thread.join", "kind": "join", "ref": ref}
        return None

    def _recv_kind(self, recv: ast.AST):
        """(lockref, kind) when the receiver is a known lock/queue/
        event/thread attribute or module global; None otherwise."""
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" and self._cls:
            info = self.classes.get(self._cls, {}).get(
                "attrs", {}).get(recv.attr)
            if info and info.get("kind") in LOCK_FACTORIES.values():
                return ["c", self._cls, recv.attr], info["kind"]
            return None
        if isinstance(recv, ast.Name):
            info = self.module_locks.get(recv.id)
            if info:
                return ["g", recv.id], info["kind"]
        return None


def _parse_suppressions(ctx) -> Tuple[Dict[int, set], list]:
    from tools.dctlint.core import parse_suppressions
    return parse_suppressions(ctx.lines, ctx.path)


def extract_facts(ctx) -> Dict[str, Any]:
    """Reduce a parsed FileContext to the JSON facts the project pass
    consumes. Pure function of the file content (cache-safe)."""
    return _Extractor(ctx).extract()


# ---------------------------------------------------------------------------
# the index
# ---------------------------------------------------------------------------

_ACQ_DEPTH = 8
_BLOCK_DEPTH = 5


class ProjectIndex:
    """Facts of every file stitched into a queryable whole-program
    view. Built once per run (from fresh extraction or the per-file
    cache) and handed to every project-scope checker."""

    def __init__(self, files: Dict[str, Dict[str, Any]],
                 root=None) -> None:
        self.files = files            # display path -> facts
        self.root = root              # Path the display paths hang off
        self.modules: Dict[str, str] = {}
        self.functions: Dict[str, Dict[str, Any]] = {}
        self.classes: Dict[str, Dict[str, Any]] = {}
        self.method_index: Dict[str, List[str]] = {}
        # checkers may leave a one-line human summary here; --stats and
        # the tests surface it (e.g. the verified lock hierarchy)
        self.summaries: Dict[str, str] = {}
        self._acq_memo: Dict[Tuple[str, bool], Dict[str, Any]] = {}
        self._blk_memo: Dict[str, List[Dict[str, Any]]] = {}
        for path, facts in files.items():
            mod = facts.get("module")
            if mod and mod not in self.modules:
                self.modules[mod] = path
            for local, info in facts.get("classes", {}).items():
                self.classes[f"{mod}.{local}" if mod else local] = {
                    "path": path, "module": mod, "local": local,
                    "info": info,
                }
            for local, fn in facts.get("functions", {}).items():
                fq = f"{mod}.{local}" if mod else local
                cls = fn.get("cls")
                self.functions[fq] = {
                    "path": path, "module": mod, "local": local,
                    "cls": f"{mod}.{cls}" if mod and cls else cls,
                    "facts": fn,
                }
                if cls and "<locals>" not in local:
                    meth = local.rsplit(".", 1)[-1]
                    self.method_index.setdefault(meth, []).append(fq)

    # -- symbols ------------------------------------------------------

    def suppressed_for(self, path: str) -> Dict[int, set]:
        facts = self.files.get(path, {})
        return {int(k): set(v)
                for k, v in facts.get("suppressed", {}).items()}

    def class_mro(self, clsfq: str) -> List[str]:
        """The project-visible part of a class's MRO (BFS, self
        first). External bases (threading.Thread) simply end a path."""
        out, queue = [], [clsfq]
        while queue:
            c = queue.pop(0)
            if c in out or c not in self.classes:
                continue
            out.append(c)
            rec = self.classes[c]
            for base in rec["info"].get("bases", []):
                resolved = self._resolve_class_dotted(
                    base, rec["module"])
                if resolved:
                    queue.append(resolved)
        return out

    def _resolve_class_dotted(self, dotted: str,
                              from_module: Optional[str],
                              depth: int = 0) -> Optional[str]:
        if depth > 4:
            return None
        if from_module:
            cand = f"{from_module}.{dotted}"
            if cand in self.classes:
                return cand
        if dotted in self.classes:
            return dotted
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod not in self.modules:
                continue
            rest = ".".join(parts[i:])
            cand = f"{mod}.{rest}"
            if cand in self.classes:
                return cand
            facts = self.files[self.modules[mod]]
            ni = facts.get("name_imports", {}).get(rest)
            if ni:
                return self._resolve_class_dotted(ni, None, depth + 1)
            break
        return None

    def find_attr(self, clsfq: str, attr: str):
        """(defining class fq, attr info) through the MRO, or None."""
        for c in self.class_mro(clsfq):
            info = self.classes[c]["info"]["attrs"].get(attr)
            if info is not None:
                return c, info
        return None

    def find_method(self, clsfq: str, meth: str) -> Optional[str]:
        for c in self.class_mro(clsfq):
            fq = f"{c}.{meth}"
            if fq in self.functions:
                return fq
        return None

    def mutable_attrs(self, clsfq: str) -> Set[str]:
        """Attributes stored outside ``__init__``/``__post_init__`` —
        mutable instance state a jitted body must not read."""
        out: Set[str] = set()
        for c in self.class_mro(clsfq):
            rec = self.classes[c]
            local = rec["local"]
            facts = self.files[rec["path"]]
            for fnlocal, fn in facts.get("functions", {}).items():
                if fn.get("cls") != local:
                    continue
                meth = fnlocal.rsplit(".", 1)[-1]
                if meth in ("__init__", "__post_init__", "__new__"):
                    continue
                for attr, _line in fn.get("stores_self", []):
                    out.add(attr)
        return out

    # -- call resolution ----------------------------------------------

    def _resolve_export(self, module: str, name: str,
                        depth: int = 0) -> List[str]:
        if depth > 4 or module not in self.modules:
            return []
        fq = f"{module}.{name}"
        if fq in self.functions:
            return [fq]
        if fq in self.classes:
            init = self.find_method(fq, "__init__")
            return [init] if init else []
        facts = self.files[self.modules[module]]
        ni = facts.get("name_imports", {}).get(name)
        if ni:
            mod, _, nm = ni.rpartition(".")
            return self._resolve_export(mod, nm, depth + 1)
        return []

    def resolve_dotted(self, dotted: str) -> List[str]:
        """Project functions a fully-qualified dotted call resolves to
        (module function, re-export, Class() ctor, Class.method)."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod not in self.modules:
                continue
            rest = parts[i:]
            if len(rest) == 1:
                return self._resolve_export(mod, rest[0])
            if len(rest) == 2:
                clsfq = self._resolve_class_dotted(rest[0], mod)
                if clsfq:
                    m = self.find_method(clsfq, rest[1])
                    return [m] if m else []
                return []
            break
        return []

    def _heuristic_targets(self, meth: str) -> List[str]:
        if meth in HEURISTIC_STOPLIST:
            return []
        cands = self.method_index.get(meth, [])
        owners = {fq.rsplit(".", 1)[0] for fq in cands}
        if not cands or len(owners) > HEURISTIC_CLASS_CAP:
            return []
        return cands

    def resolve_call(self, caller_fq: str,
                     desc: List[Any]) -> List[Tuple[str, bool]]:
        """Callee candidates for one call descriptor: a list of
        (function fq, certain). Heuristic method-name matches come back
        with certain=False."""
        rec = self.functions.get(caller_fq)
        if rec is None or not desc:
            return []
        module, clsfq = rec["module"], rec["cls"]
        kind = desc[0]
        if kind == "l":
            fq = f"{module}.{desc[1]}" if module else desc[1]
            if fq in self.functions:
                return [(fq, True)]
            if fq in self.classes:
                init = self.find_method(fq, "__init__")
                return [(init, True)] if init else []
            return []
        if kind == "q":
            return [(fq, True) for fq in self.resolve_dotted(desc[1])]
        if kind == "s" and clsfq:
            m = self.find_method(clsfq, desc[1])
            return [(m, True)] if m else []
        if kind == "sa" and clsfq:
            attr, meth = desc[1], desc[2]
            found = self.find_attr(clsfq, attr)
            if found and found[1].get("kind") == "instance":
                tfq = self._resolve_class_dotted(
                    found[1]["of"], self.classes[found[0]]["module"])
                if tfq:
                    m = self.find_method(tfq, meth)
                    return [(m, True)] if m else []
            return [(fq, False)
                    for fq in self._heuristic_targets(meth)]
        if kind == "t":
            tfq = self._resolve_class_dotted(desc[1], module)
            if tfq:
                m = self.find_method(tfq, desc[2])
                return [(m, True)] if m else []
            return [(fq, False)
                    for fq in self._heuristic_targets(desc[2])]
        if kind == "m":
            return [(fq, False)
                    for fq in self._heuristic_targets(desc[1])]
        return []

    # -- lock identity ------------------------------------------------

    def resolve_lockref(self, module: Optional[str], ref: List[Any],
                        depth: int = 0):
        """(lock id, kind) for a lockref from a file in ``module``.
        Lock identity is the defining site; Condition aliases collapse
        onto the wrapped lock. None for refs that are not locks."""
        if ref is None or depth > 3:
            return None
        if ref[0] == "c":
            clsfq = f"{module}.{ref[1]}" if module else ref[1]
            found = self.find_attr(clsfq, ref[2])
            if not found:
                return None
            defcls, info = found
            kind = info.get("kind")
            if kind not in LOCK_FACTORIES.values():
                return None
            alias = info.get("alias_of")
            if kind == "condition" and alias:
                sub = self.resolve_lockref(
                    self.classes[defcls]["module"], alias, depth + 1)
                if sub:
                    return sub
            return f"{defcls}.{ref[2]}", kind
        if ref[0] == "g":
            if module not in self.modules:
                return None
            info = self.files[self.modules[module]].get(
                "module_locks", {}).get(ref[1])
            if not info:
                return None
            alias = info.get("alias_of")
            if info["kind"] == "condition" and alias:
                sub = self.resolve_lockref(module, alias, depth + 1)
                if sub:
                    return sub
            return f"{module}.{ref[1]}", info["kind"]
        if ref[0] == "i":
            mod, _, nm = ref[1].rpartition(".")
            if mod in self.modules:
                return self.resolve_lockref(mod, ["g", nm], depth + 1)
        return None

    def held_lock_ids(self, fq: str,
                      held: List[List[Any]]) -> List[Tuple[str, str]]:
        """Resolve a held-lockref stack to [(lock id, kind)] keeping
        only kinds that actually exclude other threads."""
        rec = self.functions.get(fq)
        if rec is None:
            return []
        out: List[Tuple[str, str]] = []
        for ref in held:
            resolved = self.resolve_lockref(rec["module"], ref)
            if resolved and resolved[1] in _HELD_KINDS:
                if resolved[0] not in [x[0] for x in out]:
                    out.append(resolved)
        return out

    # -- transitive lock / blocking propagation -----------------------

    def eventual_acquires(self, fq: str, *, certain_only: bool = False,
                          _depth: int = 0,
                          _stack: Optional[Set[str]] = None
                          ) -> Dict[str, Dict[str, Any]]:
        """All lock ids a call to ``fq`` may end up acquiring, each
        with the call chain that reaches the acquire:
        ``{lock_id: {"kind", "certain", "chain": [(fq, line), ...]}}``.
        The chain's last element is the acquiring function and the
        acquire line itself."""
        key = (fq, certain_only)
        if key in self._acq_memo:
            return self._acq_memo[key]
        if _depth > _ACQ_DEPTH:
            return {}
        stack = _stack if _stack is not None else set()
        if fq in stack:
            return {}
        rec = self.functions.get(fq)
        if rec is None:
            return {}
        stack.add(fq)
        result: Dict[str, Dict[str, Any]] = {}
        facts = rec["facts"]
        for acq in facts.get("acquires", []):
            resolved = self.resolve_lockref(rec["module"], acq["l"])
            if resolved and resolved[1] in _HELD_KINDS:
                lid, kind = resolved
                result.setdefault(lid, {
                    "kind": kind, "certain": True,
                    "chain": [(fq, acq["line"])]})
        for call in facts.get("calls", []):
            desc, line = call[0], call[1]
            for callee, certain in self.resolve_call(fq, desc):
                if certain_only and not certain:
                    continue
                if callee in stack:
                    continue
                sub = self.eventual_acquires(
                    callee, certain_only=certain_only,
                    _depth=_depth + 1, _stack=stack)
                for lid, info in sub.items():
                    if lid in result:
                        continue
                    result[lid] = {
                        "kind": info["kind"],
                        "certain": certain and info["certain"],
                        "chain": [(fq, line)] + list(info["chain"]),
                    }
        stack.discard(fq)
        if _depth == 0 or _stack is None:
            self._acq_memo[key] = result
        return result

    def eventual_blocking(self, fq: str, *, _depth: int = 0,
                          _stack: Optional[Set[str]] = None
                          ) -> List[Dict[str, Any]]:
        """Blocking events a call to ``fq`` may reach (lexical plus
        propagated through certain call edges), each with a resolved
        lock id for wait-style events and the reaching call chain."""
        if fq in self._blk_memo:
            return self._blk_memo[fq]
        if _depth > _BLOCK_DEPTH:
            return []
        stack = _stack if _stack is not None else set()
        if fq in stack:
            return []
        rec = self.functions.get(fq)
        if rec is None:
            return []
        stack.add(fq)
        out: List[Dict[str, Any]] = []
        facts = rec["facts"]
        for ev in facts.get("blocking", []):
            ref = ev.get("ref")
            resolved = self.resolve_lockref(rec["module"], ref) \
                if ref else None
            out.append({
                "api": ev["api"], "kind": ev["kind"],
                "line": ev["line"], "bounded": ev.get("bounded", False),
                "lock": resolved[0] if resolved else None,
                "chain": [(fq, ev["line"])],
            })
        for call in facts.get("calls", []):
            desc, line = call[0], call[1]
            for callee, certain in self.resolve_call(fq, desc):
                if not certain or callee in stack:
                    continue
                for ev in self.eventual_blocking(
                        callee, _depth=_depth + 1, _stack=stack):
                    if len(out) >= 64:
                        break
                    out.append(dict(
                        ev, chain=[(fq, line)] + list(ev["chain"])))
        stack.discard(fq)
        if _depth == 0 or _stack is None:
            self._blk_memo[fq] = out
        return out

    def fn_display(self, fq: str) -> str:
        """Human-readable location for a function: qualified name."""
        return fq


def build_index(files: Dict[str, Dict[str, Any]],
                root=None) -> ProjectIndex:
    return ProjectIndex(files, root=root)
