"""dctlint — project-specific AST static analysis for JAX & concurrency
pitfalls (ISSUE 3; catalog + workflow in docs/static_analysis.md).

Run as ``python -m tools.dctlint [paths...]`` or ``dct lint``. Tier-1
runs it over ``determined_clone_tpu/``, ``tools/`` and ``bench.py`` via
tests/test_static_checks.py, so new violations fail CI.
"""
from tools.dctlint import checkers  # noqa: F401  (registers all checkers)
from tools.dctlint.core import (  # noqa: F401
    CHECKERS,
    Checker,
    Diagnostic,
    FileContext,
    ProjectChecker,
    apply_baseline,
    lint_file,
    lint_source,
    load_baseline,
    register,
    run,
    write_baseline,
)
from tools.dctlint.project import (  # noqa: F401
    ProjectIndex,
    extract_facts,
)

DEFAULT_PATHS = ("determined_clone_tpu", "tools", "bench.py")
