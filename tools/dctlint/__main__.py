"""CLI entry: ``python -m tools.dctlint [paths...]`` (also ``dct lint``).

Exit codes: 0 clean, 1 violations, 2 usage error.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from tools.dctlint import CHECKERS, DEFAULT_PATHS, core

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dctlint",
        description="project-specific AST static analysis "
                    "(docs/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help=f"files/directories (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                   help="baseline JSON of grandfathered violations")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined violations too")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current violations to the baseline file "
                        "(each entry then needs a real justification)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--no-hints", action="store_true",
                   help="omit fix hints from text output")
    p.add_argument("--list-checkers", action="store_true")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_checkers:
        for rule in sorted(CHECKERS):
            c = CHECKERS[rule]
            print(f"{rule}  {c.title}")
        return 0

    select: Optional[List[str]] = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in CHECKERS]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-checkers)", file=sys.stderr)
            return 2

    paths = args.paths or [str(REPO_ROOT / p) for p in DEFAULT_PATHS]
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    baseline = None if args.no_baseline else Path(args.baseline)
    if args.write_baseline:
        diags = core.run(paths, select=select, baseline=None,
                         relative_to=REPO_ROOT)
        n = core.write_baseline(Path(args.baseline), diags)
        print(f"wrote {n} baseline entr{'y' if n == 1 else 'ies'} to "
              f"{args.baseline} — fill in the justifications")
        return 0

    diags = core.run(paths, select=select, baseline=baseline,
                     relative_to=REPO_ROOT)

    if args.format == "json":
        print(json.dumps([dataclasses.asdict(d) for d in diags], indent=2))
    else:
        for d in diags:
            print(d.format(show_hint=not args.no_hints))
        if diags:
            rules = sorted({d.rule for d in diags})
            print(f"\n{len(diags)} violation(s) [{', '.join(rules)}]. "
                  f"Fix, suppress inline with "
                  f"`# dctlint: disable=RULE <reason>`, or baseline with "
                  f"a justification (docs/static_analysis.md).")
    return 1 if diags else 0


if __name__ == "__main__":
    sys.exit(main())
