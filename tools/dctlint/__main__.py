"""CLI entry: ``python -m tools.dctlint [paths...]`` (also ``dct lint``).

Exit codes: 0 clean, 1 violations, 2 usage error.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from tools.dctlint import CHECKERS, DEFAULT_PATHS, core

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dctlint",
        description="project-specific AST static analysis "
                    "(docs/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help=f"files/directories (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                   help="baseline JSON of grandfathered violations")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined violations too")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current violations to the baseline file "
                        "(each entry then needs a real justification)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--no-hints", action="store_true",
                   help="omit fix hints from text output")
    p.add_argument("--list-checkers", action="store_true")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="only report diagnostics in files changed vs "
                        "REF (default HEAD) plus untracked files; the "
                        "full ProjectIndex is still built so cross-file "
                        "checks stay sound — the fast pre-commit path")
    p.add_argument("--stats", action="store_true",
                   help="print wall-time/files/cache-hit stats and "
                        "project-checker summaries (e.g. the verified "
                        "lock hierarchy) to stderr")
    p.add_argument("--jobs", type=int, default=0, metavar="N",
                   help="worker processes for the per-file pass "
                        "(0 auto, 1 serial)")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and do not write the per-file facts "
                        "cache (.dctlint_cache.json)")
    return p


def _changed_files(ref: str) -> Optional[set]:
    """Display paths (relative to the repo root) changed vs ``ref``,
    plus untracked files. None if git is unavailable."""
    import subprocess
    out: set = set()
    for cmd in (["git", "diff", "--name-only", ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(
                cmd, cwd=REPO_ROOT, capture_output=True, text=True,
                timeout=30, check=True)
        except (OSError, subprocess.SubprocessError):
            return None
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip().endswith(".py"))
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_checkers:
        for rule in sorted(CHECKERS):
            c = CHECKERS[rule]
            print(f"{rule}  {c.title}")
        return 0

    select: Optional[List[str]] = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in CHECKERS]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-checkers)", file=sys.stderr)
            return 2

    paths = args.paths or [str(REPO_ROOT / p) for p in DEFAULT_PATHS]
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    baseline = None if args.no_baseline else Path(args.baseline)
    cache_path = None if args.no_cache else \
        REPO_ROOT / ".dctlint_cache.json"
    changed_only = None
    if args.changed is not None:
        changed_only = _changed_files(args.changed)
        if changed_only is None:
            print("--changed: git unavailable, linting everything",
                  file=sys.stderr)
    if args.write_baseline:
        diags = core.run(paths, select=select, baseline=None,
                         relative_to=REPO_ROOT, jobs=args.jobs,
                         cache_path=cache_path)
        n = core.write_baseline(Path(args.baseline), diags)
        print(f"wrote {n} baseline entr{'y' if n == 1 else 'ies'} to "
              f"{args.baseline} — fill in the justifications")
        return 0

    stats: dict = {}
    diags = core.run(paths, select=select, baseline=baseline,
                     relative_to=REPO_ROOT, jobs=args.jobs,
                     cache_path=cache_path, changed_only=changed_only,
                     stats=stats)
    if args.stats:
        print(f"dctlint: {stats['files']} files in "
              f"{stats['wall_s']:.2f}s ({stats['cache_hits']} cached, "
              f"{stats['analyzed']} analyzed, {stats['jobs']} worker"
              f"{'s' if stats['jobs'] != 1 else ''}); project pass: "
              f"{', '.join(stats['project_checkers']) or 'none'}",
              file=sys.stderr)
        for rule, summary in sorted(stats["summaries"].items()):
            print(f"  {rule}: {summary}", file=sys.stderr)

    if args.format == "json":
        print(json.dumps([dataclasses.asdict(d) for d in diags], indent=2))
    else:
        for d in diags:
            print(d.format(show_hint=not args.no_hints))
        if diags:
            rules = sorted({d.rule for d in diags})
            print(f"\n{len(diags)} violation(s) [{', '.join(rules)}]. "
                  f"Fix, suppress inline with "
                  f"`# dctlint: disable=RULE <reason>`, or baseline with "
                  f"a justification (docs/static_analysis.md).")
    return 1 if diags else 0


if __name__ == "__main__":
    sys.exit(main())
