#!/usr/bin/env python3
"""Chaos conductor CLI for the self-healing serving fleet.

Drives the seeded scenario catalog in serving/chaos.py — kill -9
mid-decode, wedged scheduler, torn warm-start blob, supervisor+replica
double fault, poison pill, deadline storm — and reports the invariant
audit for each: zero lost accepted requests, bit-identical recovered
outputs, zero leaked KV blocks, bounded MTTR. Exit 0 iff every scenario
passed (docs/serving.md "Self-healing" for the catalog).

Usage:
    python tools/chaosfleet.py --list
    python tools/chaosfleet.py                       # the full catalog
    python tools/chaosfleet.py --scenario kill_replica_mid_decode
    python tools/chaosfleet.py --seed 7 --json
    python tools/chaosfleet.py --selftest            # tier-1 smoke

Importable: ``main(argv) -> int`` (tests/test_self_healing.py calls it);
``run()`` in serving/chaos.py for in-process use (bench.py's advisory
``recovery`` section rides the same runner).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the conductor is a CPU tool: force the host platform before jax loads
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _print_result(res) -> None:
    mark = "PASS" if res.passed else "FAIL"
    print(f"[{mark}] {res.scenario} "
          f"(seed={res.seed} {res.duration_s:.1f}s "
          f"mttr_max={res.mttr_max_s:.2f}s)")
    for c in res.checks:
        flag = "ok  " if c.ok else "FAIL"
        line = f"    {flag} {c.name}"
        if c.detail and not c.ok:
            line += f": {c.detail}"
        print(line)


def main(argv=None) -> int:
    from determined_clone_tpu.serving.chaos import SCENARIOS, run_scenarios

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--scenario", action="append", default=None,
                        help="scenario name (repeatable; default: all)")
    parser.add_argument("--seed", type=int, default=0,
                        help="FaultPlan + workload seed")
    parser.add_argument("--requests", type=int, default=6,
                        help="concurrent requests per scenario workload")
    parser.add_argument("--mttr-budget", type=float, default=30.0,
                        help="max seconds a replica replacement may take")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable results on stdout")
    parser.add_argument("--list", action="store_true",
                        help="print the scenario catalog and exit")
    parser.add_argument("--selftest", action="store_true",
                        help="tier-1 smoke: the acceptance scenario "
                             "(kill -9 mid-decode) with a small workload")
    args = parser.parse_args(argv)

    if args.list:
        for name, fn in SCENARIOS.items():
            doc = (fn.__doc__ or "").strip().splitlines()[0]
            print(f"{name:28s} {doc}")
        return 0

    names = args.scenario
    requests = args.requests
    if args.selftest:
        names = ["kill_replica_mid_decode"]
        requests = min(requests, 4)

    try:
        results = run_scenarios(names, seed=args.seed,
                                mttr_budget_s=args.mttr_budget,
                                requests=requests)
    except KeyError as exc:
        print(f"error: {exc.args[0]}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([r.to_dict() for r in results], indent=2))
    else:
        for res in results:
            _print_result(res)
        n_pass = sum(r.passed for r in results)
        print(f"{n_pass}/{len(results)} scenarios passed")
    return 0 if all(r.passed for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
