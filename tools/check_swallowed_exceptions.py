#!/usr/bin/env python3
"""Static check: no silently swallowed exceptions without a stated reason.

Flags every ``except Exception:`` / ``except BaseException:`` / bare
``except:`` handler whose body is only ``pass`` (or ``...``) unless a
justification comment sits adjacent to it. "Adjacent" means any ``#``
comment in the window from three lines above the ``except`` line through
one line below the handler body — that covers a comment on the ``pass``
line, on the ``except`` line, a block comment just above the ``try``, or
a trailing note after the handler.

Motivated by the telemetry work (docs/observability.md): a swallowed
exception with no counter and no comment is exactly how sample drops went
invisible in the profiler's ``_post``. Narrow handlers (``except
KeyError:`` etc.) are fine — catching a specific error and ignoring it is
a statement in itself; catching *everything* and ignoring it needs words.

Usage: ``python tools/check_swallowed_exceptions.py [paths...]``
Defaults to ``determined_clone_tpu/``. Exit 0 = clean, 1 = violations.
Runs in tier-1 via tests/test_static_checks.py.
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Iterator, List, Tuple

BROAD = ("Exception", "BaseException")
COMMENT_WINDOW_ABOVE = 3


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD for e in t.elts)
    return False


def _is_noop_body(body: List[ast.stmt]) -> bool:
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _has_adjacent_comment(lines: List[str], handler: ast.ExceptHandler) -> bool:
    start = max(0, handler.lineno - 1 - COMMENT_WINDOW_ABOVE)
    end = min(len(lines), (handler.body[-1].end_lineno or handler.lineno) + 1)
    return any("#" in line for line in lines[start:end])


def check_file(path: Path) -> Iterator[Tuple[int, str]]:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        yield (e.lineno or 0, f"syntax error: {e.msg}")
        return
    lines = source.splitlines()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _is_broad(node) and _is_noop_body(node.body) \
                and not _has_adjacent_comment(lines, node):
            what = ast.unparse(node.type) if node.type else "<bare>"
            yield (node.lineno,
                   f"swallowed `except {what}: pass` with no adjacent "
                   f"justification comment")


def main(argv: List[str]) -> int:
    roots = [Path(p) for p in (argv or ["determined_clone_tpu"])]
    violations = []
    for root in roots:
        files = sorted(root.rglob("*.py")) if root.is_dir() else [root]
        for f in files:
            for lineno, msg in check_file(f):
                violations.append(f"{f}:{lineno}: {msg}")
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} swallowed-exception violation(s). "
              f"Either narrow the handler, count the drop in a telemetry "
              f"counter, or add a comment saying why silence is correct.")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
