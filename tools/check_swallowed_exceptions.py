#!/usr/bin/env python3
"""Static check: no silently swallowed exceptions without a stated reason.

Thin compatibility shim — the check now lives in the dctlint framework as
rule **EXC001** (tools/dctlint/checkers/exceptions.py; catalog in
docs/static_analysis.md). Existing invocations keep working:

Usage: ``python tools/check_swallowed_exceptions.py [paths...]``
Defaults to ``determined_clone_tpu/``. Exit 0 = clean, 1 = violations.
Prefer ``python -m tools.dctlint`` for the full checker suite.
"""
from __future__ import annotations

import sys
from pathlib import Path
from typing import Iterator, List, Tuple

# importable both as `tools.check_swallowed_exceptions` and as a top-level
# module with tools/ on sys.path (how tests/test_static_checks.py loads it)
_REPO_ROOT = str(Path(__file__).resolve().parent.parent)
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tools.dctlint import core  # noqa: E402  (registers checkers on import)


def check_file(path: Path) -> Iterator[Tuple[int, str]]:
    """(lineno, message) per violation — the original script's contract."""
    for d in core.lint_file(Path(path), select=["EXC001"]):
        yield (d.line, d.message)


def main(argv: List[str]) -> int:
    roots = argv or ["determined_clone_tpu"]
    violations = core.run(roots, select=["EXC001"], baseline=None)
    for d in violations:
        print(f"{d.path}:{d.line}: {d.message}")
    if violations:
        print(f"\n{len(violations)} swallowed-exception violation(s). "
              f"Either narrow the handler, count the drop in a telemetry "
              f"counter, or add a comment saying why silence is correct.")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
