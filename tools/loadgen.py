#!/usr/bin/env python3
"""Synthetic control-plane load harness for the C++ master.

Drives a REAL ``dct-master`` binary (spawned here, or an existing one via
``--master``) with simulated agents and thousands of no-op trials, then
reads the scheduler's own telemetry back out of
``GET /api/v1/cluster/scheduler`` to produce the ``control_plane``
section of BENCH (docs/observability.md):

- **submits/sec admitted** — trials minted through the custom-searcher
  operations route over the submission wall time;
- **decisions/sec** — scheduler decision passes over the run;
- **p50/p99 submit→running** — the master's own lifecycle-timestamp
  latency reservoir (``dct_master_sched_submit_to_running_seconds``);
- **peak queue depth** — max of the queue-depth gauge polled over the run.

The simulated agent protocol is the real one: ``POST
/api/v1/agents/register``, heartbeats that receive derived ``start``
commands, ``task_event running`` → ``searcher/completed_op`` →
``task_event exited``. Completing the searcher op before the clean exit
parks each trial instead of requeueing it, so slots recycle and the
queue drains at scheduler speed, not harness speed.

Usage:
    python tools/loadgen.py --trials 1000 --agents 8 --slots 8
    python tools/loadgen.py --trials 10000 --budget 300   # the 10k run

Importable: ``run_load(trials=1000, ...) -> dict`` (bench.py calls this).
Never raises on an unavailable master build — returns ``{"error": ...}``.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MASTER_DIR = os.path.join(REPO, "determined_clone_tpu", "master")
MASTER_BIN = os.path.join(MASTER_DIR, "build", "dct-master")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from determined_clone_tpu.utils.retry import (  # noqa: E402
    RetryPolicy, retry_call, sleep_backoff)

OPS_PER_BATCH = 200  # creates per searcher/operations POST

# boot wait: steady sampling, no jitter (the deploy_wait pattern in
# docs/fault_tolerance.md); ValueError covers a half-up server returning
# a torn JSON body
_MASTER_UP = RetryPolicy(
    name="loadgen_master_up", max_attempts=1_000_000, base_delay_s=0.2,
    multiplier=1.0, max_delay_s=0.2, jitter="none",
    retryable=(OSError, ValueError))
_HEARTBEAT = RetryPolicy(name="loadgen_heartbeat", base_delay_s=0.1,
                         max_delay_s=2.0, retryable=(OSError, ValueError))


def _req(port: int, method: str, path: str, body=None, timeout: float = 30):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


def ensure_master_binary() -> str | None:
    if os.path.exists(MASTER_BIN):
        return MASTER_BIN
    r = subprocess.run(["make", "-C", MASTER_DIR], capture_output=True)
    return MASTER_BIN if r.returncode == 0 and os.path.exists(MASTER_BIN) \
        else None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_up(port: int, deadline_s: float = 15.0) -> bool:
    policy = dataclasses.replace(_MASTER_UP, deadline_s=deadline_s)
    try:
        retry_call(_req, port, "GET", "/api/v1/master", timeout=3,
                   policy=policy)
        return True
    except (OSError, ValueError):
        return False


def _sched(port: int) -> dict:
    return _req(port, "GET", "/api/v1/cluster/scheduler")


class _AgentSim(threading.Thread):
    """One fake agent: heartbeats, runs every ``start`` it receives as a
    no-op (running → completed_op → clean exit), all inside one beat."""

    def __init__(self, port: int, agent_id: str, stop: threading.Event):
        super().__init__(daemon=True, name=f"loadgen-{agent_id}")
        self.port = port
        self.agent_id = agent_id
        self.stop_ev = stop
        self.ran = 0
        self.errors = 0

    def run(self) -> None:
        hb_failures = 0
        while not self.stop_ev.is_set():
            try:
                resp = _req(self.port, "POST",
                            f"/api/v1/agents/{self.agent_id}/heartbeat",
                            {"exited": [], "running": []})
                hb_failures = 0
            except (OSError, ValueError):
                self.errors += 1
                hb_failures += 1
                sleep_backoff(_HEARTBEAT, hb_failures)
                continue
            cmds = [c for c in resp.get("commands", [])
                    if c.get("type") == "start"]
            for cmd in cmds:
                try:
                    self._run_task(cmd)
                    self.ran += 1
                except (OSError, ValueError):
                    self.errors += 1
            # beat fast while work flows, back off when idle — poll pacing
            # (the Event doubles as the stop signal)
            self.stop_ev.wait(0.02 if cmds else 0.1)

    def _run_task(self, cmd: dict) -> None:
        alloc_id = cmd["allocation_id"]
        trial = cmd.get("trial") or {}
        _req(self.port, "POST",
             f"/api/v1/agents/{self.agent_id}/task_event",
             {"allocation_id": alloc_id, "event": "running"})
        tid = trial.get("id")
        if tid:
            # satisfy the searcher op BEFORE exiting: units_done reaches
            # target, so the clean exit completes the trial leg instead of
            # requeueing it — the slot frees for the next queued trial
            _req(self.port, "POST",
                 f"/api/v1/trials/{tid}/searcher/completed_op",
                 {"metric": 0.0, "units": trial.get("target_units", 1)})
        _req(self.port, "POST",
             f"/api/v1/agents/{self.agent_id}/task_event",
             {"allocation_id": alloc_id, "event": "exited", "exit_code": 0})


def _counters(summary: dict) -> dict:
    return summary.get("counters") or {}


def run_load(trials: int = 1000, agents: int = 8, slots_per_agent: int = 8,
             budget_s: float = 180.0, master_port: int | None = None,
             keep_master: bool = False) -> dict:
    """Run the synthetic load and return the control-plane measurement.

    Spawns its own master (``--db sqlite``) unless ``master_port`` points
    at a live one. Always returns a dict; ``error`` is set (and the
    latency fields None) when the master can't be built or reached.
    """
    t_total0 = time.monotonic()
    proc = None
    tmp = None
    port = master_port
    try:
        if port is None:
            binary = ensure_master_binary()
            if binary is None:
                return {"error": "dct-master build unavailable"}
            tmp = tempfile.mkdtemp(prefix="dct-loadgen-")
            port = _free_port()
            proc = subprocess.Popen(
                [binary, "--port", str(port), "--data-dir",
                 os.path.join(tmp, "data"), "--db", "sqlite"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            if not _wait_up(port):
                return {"error": "spawned master did not come up"}
        elif not _wait_up(port, 5.0):
            return {"error": f"no master on port {port}"}

        base = _sched(port)
        base_c = _counters(base)

        for i in range(agents):
            _req(port, "POST", "/api/v1/agents/register",
                 {"id": f"loadgen-agent-{i}", "slots": slots_per_agent,
                  "topology": f"fake-{slots_per_agent}",
                  "address": "127.0.0.1:0", "resource_pool": "default"})

        stop = threading.Event()
        sims = [_AgentSim(port, f"loadgen-agent-{i}", stop)
                for i in range(agents)]
        for s in sims:
            s.start()

        exp = _req(port, "POST", "/api/v1/experiments", {"config": {
            "name": "loadgen", "entrypoint": "noop:Noop",
            "searcher": {"name": "custom", "metric": "loss"},
            "resources": {"slots_per_trial": 1},
            "hyperparameters": {},
        }})
        exp_id = (exp.get("experiment") or exp)["id"]

        # -- submission phase: mint trials through the searcher ops route --
        t_sub0 = time.monotonic()
        submitted = 0
        rid = 0
        while submitted < trials:
            if time.monotonic() - t_total0 > budget_s:
                break
            n = min(OPS_PER_BATCH, trials - submitted)
            ops = []
            for _ in range(n):
                ops.append({"type": "create", "request_id": rid,
                            "hparams": {}})
                ops.append({"type": "validate_after", "request_id": rid,
                            "units": 1})
                rid += 1
            _req(port, "POST",
                 f"/api/v1/experiments/{exp_id}/searcher/operations",
                 {"ops": ops}, timeout=60)
            submitted += n
        submit_wall = max(time.monotonic() - t_sub0, 1e-9)

        # -- drain phase: poll the scheduler summary until done/budget ----
        peak_queue = 0
        done = 0
        incomplete = False
        while True:
            s = _sched(port)
            gauges = s.get("gauges") or {}
            peak_queue = max(peak_queue, int(gauges.get("queue_depth") or 0))
            done = int(_counters(s).get("completed", 0)
                       - base_c.get("completed", 0))
            if done >= submitted:
                break
            if time.monotonic() - t_total0 > budget_s:
                incomplete = True
                break
            time.sleep(0.25)
        stop.set()
        for s_ in sims:
            s_.join(timeout=5)

        final = _sched(port)
        wall = max(time.monotonic() - t_total0, 1e-9)
        fc, lat = _counters(final), final.get("latency") or {}

        def delta(name: str) -> int:
            return int(fc.get(name, 0) - base_c.get(name, 0))

        s2r = lat.get("submit_to_running_seconds") or {}
        return {
            "trials": trials,
            "submitted": delta("submitted"),
            "completed": done,
            "agents": agents,
            "slots": agents * slots_per_agent,
            "duration_s": round(wall, 3),
            "submit_wall_s": round(submit_wall, 3),
            "submits_per_sec": round(submitted / submit_wall, 2),
            "decisions": delta("decisions"),
            "decisions_per_sec": round(delta("decisions") / wall, 2),
            "considered": delta("considered"),
            "scheduled": delta("scheduled"),
            "reschedules": delta("reschedules"),
            "preemptions": delta("preemptions"),
            "peak_queue_depth": peak_queue,
            "submit_to_running_s": {
                "p50": s2r.get("p50"), "p95": s2r.get("p95"),
                "p99": s2r.get("p99"), "count": s2r.get("count"),
            },
            "queue_wait_s": {
                k: (lat.get("queue_wait_seconds") or {}).get(k)
                for k in ("p50", "p95", "p99", "count")
            },
            "decision_s": {
                k: (lat.get("decision_seconds") or {}).get(k)
                for k in ("p50", "p95", "p99", "count")
            },
            "agent_errors": sum(s_.errors for s_ in sims),
            "incomplete": incomplete,
        }
    except (OSError, ValueError, KeyError) as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}
    finally:
        if proc is not None and not keep_master:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
        if tmp is not None and not keep_master:
            shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--trials", type=int, default=1000)
    parser.add_argument("--agents", type=int, default=8)
    parser.add_argument("--slots", type=int, default=8,
                        help="slots per simulated agent")
    parser.add_argument("--budget", type=float, default=180.0,
                        help="total wall-clock budget in seconds")
    parser.add_argument("--master", default=None,
                        help="PORT of a live master (default: spawn one)")
    args = parser.parse_args(argv)
    result = run_load(trials=args.trials, agents=args.agents,
                      slots_per_agent=args.slots, budget_s=args.budget,
                      master_port=int(args.master) if args.master else None)
    print(json.dumps(result, indent=2))
    return 1 if result.get("error") else 0


if __name__ == "__main__":
    sys.exit(main())
