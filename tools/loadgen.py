#!/usr/bin/env python3
"""Synthetic control-plane load harness for the C++ master.

Drives a REAL ``dct-master`` binary (spawned here, or an existing one via
``--master``) with simulated agents and thousands of no-op trials, then
reads the scheduler's own telemetry back out of
``GET /api/v1/cluster/scheduler`` to produce the ``control_plane``
section of BENCH (docs/observability.md):

- **submits/sec admitted** — trials minted through the custom-searcher
  operations route over the submission wall time;
- **decisions/sec** — scheduler decision passes over the run;
- **p50/p99 submit→running** — the master's own lifecycle-timestamp
  latency reservoir (``dct_master_sched_submit_to_running_seconds``);
- **peak queue depth** — max of the queue-depth gauge polled over the run.

The simulated agent protocol is the real one: ``POST
/api/v1/agents/register``, heartbeats that receive derived ``start``
commands, ``task_event running`` → ``searcher/completed_op`` →
``task_event exited``. Completing the searcher op before the clean exit
parks each trial instead of requeueing it, so slots recycle and the
queue drains at scheduler speed, not harness speed.

Usage:
    python tools/loadgen.py --trials 1000 --agents 8 --slots 8
    python tools/loadgen.py --trials 10000 --budget 300   # the 10k run

Importable: ``run_load(trials=1000, ...) -> dict`` (bench.py calls this).
Never raises on an unavailable master build — returns ``{"error": ...}``.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MASTER_DIR = os.path.join(REPO, "determined_clone_tpu", "master")
MASTER_BIN = os.path.join(MASTER_DIR, "build", "dct-master")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from determined_clone_tpu.utils.retry import (  # noqa: E402
    RetryPolicy, retry_call, sleep_backoff)

OPS_PER_BATCH = 200  # creates per searcher/operations POST

# boot wait: steady sampling, no jitter (the deploy_wait pattern in
# docs/fault_tolerance.md); ValueError covers a half-up server returning
# a torn JSON body
_MASTER_UP = RetryPolicy(
    name="loadgen_master_up", max_attempts=1_000_000, base_delay_s=0.2,
    multiplier=1.0, max_delay_s=0.2, jitter="none",
    retryable=(OSError, ValueError))
_HEARTBEAT = RetryPolicy(name="loadgen_heartbeat", base_delay_s=0.1,
                         max_delay_s=2.0, retryable=(OSError, ValueError))


def _req(port: int, method: str, path: str, body=None, timeout: float = 30):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


def ensure_master_binary() -> str | None:
    if os.path.exists(MASTER_BIN):
        return MASTER_BIN
    r = subprocess.run(["make", "-C", MASTER_DIR], capture_output=True)
    return MASTER_BIN if r.returncode == 0 and os.path.exists(MASTER_BIN) \
        else None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_up(port: int, deadline_s: float = 15.0) -> bool:
    policy = dataclasses.replace(_MASTER_UP, deadline_s=deadline_s)
    try:
        retry_call(_req, port, "GET", "/api/v1/master", timeout=3,
                   policy=policy)
        return True
    except (OSError, ValueError):
        return False


def _sched(port: int) -> dict:
    return _req(port, "GET", "/api/v1/cluster/scheduler")


class _AgentSim(threading.Thread):
    """One fake agent: heartbeats, runs every ``start`` it receives as a
    no-op (running → completed_op → clean exit), all inside one beat."""

    def __init__(self, port: int, agent_id: str, stop: threading.Event):
        super().__init__(daemon=True, name=f"loadgen-{agent_id}")
        self.port = port
        self.agent_id = agent_id
        self.stop_ev = stop
        self.ran = 0
        self.errors = 0

    def run(self) -> None:
        hb_failures = 0
        while not self.stop_ev.is_set():
            try:
                resp = _req(self.port, "POST",
                            f"/api/v1/agents/{self.agent_id}/heartbeat",
                            {"exited": [], "running": []})
                hb_failures = 0
            except (OSError, ValueError):
                self.errors += 1
                hb_failures += 1
                sleep_backoff(_HEARTBEAT, hb_failures)
                continue
            cmds = [c for c in resp.get("commands", [])
                    if c.get("type") == "start"]
            for cmd in cmds:
                try:
                    self._run_task(cmd)
                    self.ran += 1
                except (OSError, ValueError):
                    self.errors += 1
            # beat fast while work flows, back off when idle — poll pacing
            # (the Event doubles as the stop signal)
            self.stop_ev.wait(0.02 if cmds else 0.1)

    def _run_task(self, cmd: dict) -> None:
        alloc_id = cmd["allocation_id"]
        trial = cmd.get("trial") or {}
        _req(self.port, "POST",
             f"/api/v1/agents/{self.agent_id}/task_event",
             {"allocation_id": alloc_id, "event": "running"})
        tid = trial.get("id")
        if tid:
            # satisfy the searcher op BEFORE exiting: units_done reaches
            # target, so the clean exit completes the trial leg instead of
            # requeueing it — the slot frees for the next queued trial
            _req(self.port, "POST",
                 f"/api/v1/trials/{tid}/searcher/completed_op",
                 {"metric": 0.0, "units": trial.get("target_units", 1)})
        _req(self.port, "POST",
             f"/api/v1/agents/{self.agent_id}/task_event",
             {"allocation_id": alloc_id, "event": "exited", "exit_code": 0})


def _counters(summary: dict) -> dict:
    return summary.get("counters") or {}


def run_load(trials: int = 1000, agents: int = 8, slots_per_agent: int = 8,
             budget_s: float = 180.0, master_port: int | None = None,
             keep_master: bool = False) -> dict:
    """Run the synthetic load and return the control-plane measurement.

    Spawns its own master (``--db sqlite``) unless ``master_port`` points
    at a live one. Always returns a dict; ``error`` is set (and the
    latency fields None) when the master can't be built or reached.
    """
    t_total0 = time.monotonic()
    proc = None
    tmp = None
    port = master_port
    try:
        if port is None:
            binary = ensure_master_binary()
            if binary is None:
                return {"error": "dct-master build unavailable"}
            tmp = tempfile.mkdtemp(prefix="dct-loadgen-")
            port = _free_port()
            proc = subprocess.Popen(
                [binary, "--port", str(port), "--data-dir",
                 os.path.join(tmp, "data"), "--db", "sqlite"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            if not _wait_up(port):
                return {"error": "spawned master did not come up"}
        elif not _wait_up(port, 5.0):
            return {"error": f"no master on port {port}"}

        base = _sched(port)
        base_c = _counters(base)

        for i in range(agents):
            _req(port, "POST", "/api/v1/agents/register",
                 {"id": f"loadgen-agent-{i}", "slots": slots_per_agent,
                  "topology": f"fake-{slots_per_agent}",
                  "address": "127.0.0.1:0", "resource_pool": "default"})

        stop = threading.Event()
        sims = [_AgentSim(port, f"loadgen-agent-{i}", stop)
                for i in range(agents)]
        for s in sims:
            s.start()

        exp = _req(port, "POST", "/api/v1/experiments", {"config": {
            "name": "loadgen", "entrypoint": "noop:Noop",
            "searcher": {"name": "custom", "metric": "loss"},
            "resources": {"slots_per_trial": 1},
            "hyperparameters": {},
        }})
        exp_id = (exp.get("experiment") or exp)["id"]

        # -- submission phase: mint trials through the searcher ops route --
        t_sub0 = time.monotonic()
        submitted = 0
        rid = 0
        while submitted < trials:
            if time.monotonic() - t_total0 > budget_s:
                break
            n = min(OPS_PER_BATCH, trials - submitted)
            ops = []
            for _ in range(n):
                ops.append({"type": "create", "request_id": rid,
                            "hparams": {}})
                ops.append({"type": "validate_after", "request_id": rid,
                            "units": 1})
                rid += 1
            _req(port, "POST",
                 f"/api/v1/experiments/{exp_id}/searcher/operations",
                 {"ops": ops}, timeout=60)
            submitted += n
        submit_wall = max(time.monotonic() - t_sub0, 1e-9)

        # -- drain phase: poll the scheduler summary until done/budget ----
        peak_queue = 0
        done = 0
        incomplete = False
        while True:
            s = _sched(port)
            gauges = s.get("gauges") or {}
            peak_queue = max(peak_queue, int(gauges.get("queue_depth") or 0))
            done = int(_counters(s).get("completed", 0)
                       - base_c.get("completed", 0))
            if done >= submitted:
                break
            if time.monotonic() - t_total0 > budget_s:
                incomplete = True
                break
            time.sleep(0.25)
        stop.set()
        for s_ in sims:
            s_.join(timeout=5)

        final = _sched(port)
        wall = max(time.monotonic() - t_total0, 1e-9)
        fc, lat = _counters(final), final.get("latency") or {}

        def delta(name: str) -> int:
            return int(fc.get(name, 0) - base_c.get(name, 0))

        s2r = lat.get("submit_to_running_seconds") or {}
        return {
            "trials": trials,
            "submitted": delta("submitted"),
            "completed": done,
            "agents": agents,
            "slots": agents * slots_per_agent,
            "duration_s": round(wall, 3),
            "submit_wall_s": round(submit_wall, 3),
            "submits_per_sec": round(submitted / submit_wall, 2),
            "decisions": delta("decisions"),
            "decisions_per_sec": round(delta("decisions") / wall, 2),
            "considered": delta("considered"),
            "scheduled": delta("scheduled"),
            "reschedules": delta("reschedules"),
            "preemptions": delta("preemptions"),
            "peak_queue_depth": peak_queue,
            "submit_to_running_s": {
                "p50": s2r.get("p50"), "p95": s2r.get("p95"),
                "p99": s2r.get("p99"), "count": s2r.get("count"),
            },
            "queue_wait_s": {
                k: (lat.get("queue_wait_seconds") or {}).get(k)
                for k in ("p50", "p95", "p99", "count")
            },
            "decision_s": {
                k: (lat.get("decision_seconds") or {}).get(k)
                for k in ("p50", "p95", "p99", "count")
            },
            "agent_errors": sum(s_.errors for s_ in sims),
            "incomplete": incomplete,
        }
    except (OSError, ValueError, KeyError) as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}
    finally:
        if proc is not None and not keep_master:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
        if tmp is not None and not keep_master:
            shutil.rmtree(tmp, ignore_errors=True)


def _percentiles(samples: list) -> dict:
    """p50/p95/p99 with numpy-style linear interpolation (no numpy dep —
    loadgen must run beside a master with nothing but the stdlib)."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None, "count": 0}
    s = sorted(samples)

    def pct(q: float) -> float:
        pos = q / 100.0 * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        return s[lo] * (1 - (pos - lo)) + s[hi] * (pos - lo)

    return {"p50": round(pct(50), 6), "p95": round(pct(95), 6),
            "p99": round(pct(99), 6), "count": len(s)}


def run_mixed_load(trials: int = 400, agents: int = 4,
                   slots_per_agent: int = 8, serving_replicas: int = 2,
                   serving_requests: int = 120,
                   tokens_per_request: int = 8,
                   iteration_floor_s: float = 0.01,
                   budget_s: float = 240.0,
                   master_port: int | None = None,
                   shared_prefix: bool = False) -> dict:
    """Trials AND a serving fleet on one simulated cluster.

    ``shared_prefix`` switches the serving traffic to the "millions of
    users, one system prompt" shape: every request opens with the same
    system prefix (a whole KV block) followed by a varied tail, and the
    fleet's engines run with the COW prefix cache on — the serving
    numbers then report the aggregate block hit-rate next to the p99,
    which is the pair the prefix cache is supposed to move.

    The trial half is :func:`run_load`'s machinery (simulated agents in
    the ``default`` pool, trials minted through the searcher ops route).
    The serving half is REAL: a ``ServingFleet`` of tiny-GPT engines
    whose replicas are master ``serving`` gang allocations in their own
    ``serving`` pool (the standard serving/training pool split), driven
    through the least-loaded router while the trial storm is in flight.
    Both sides contend for the master's decision loop and this host's
    CPU, which is the contention the mixed numbers measure: trial
    submit→running p95 from the master's own reservoir, serving p99 from
    client-observed request latencies. Also returns the fleet rollup the
    aggregator computes from the per-replica registries (what ``dct
    metrics`` shows) and the master's serving counters (what proves the
    gang allocations went through the scheduler).
    """
    t_total0 = time.monotonic()
    proc = None
    tmp = None
    port = master_port
    fleet = None
    link = None
    try:
        # serving imports are deliberately lazy: the control_plane lane
        # must keep working on hosts without jax
        import jax

        from determined_clone_tpu.models import gpt
        from determined_clone_tpu.serving import MasterLink, ServingFleet
        from determined_clone_tpu.serving.bucketing import BucketSpec
        from determined_clone_tpu.serving.kv_cache import KVCacheConfig
        from determined_clone_tpu.telemetry.aggregate import (
            ClusterMetricsAggregator,
        )

        if port is None:
            binary = ensure_master_binary()
            if binary is None:
                return {"error": "dct-master build unavailable"}
            tmp = tempfile.mkdtemp(prefix="dct-loadgen-")
            port = _free_port()
            proc = subprocess.Popen(
                [binary, "--port", str(port), "--data-dir",
                 os.path.join(tmp, "data"), "--db", "sqlite"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            if not _wait_up(port):
                return {"error": "spawned master did not come up"}
        elif not _wait_up(port, 5.0):
            return {"error": f"no master on port {port}"}

        base_c = _counters(_sched(port))

        for i in range(agents):
            _req(port, "POST", "/api/v1/agents/register",
                 {"id": f"loadgen-agent-{i}", "slots": slots_per_agent,
                  "topology": f"fake-{slots_per_agent}",
                  "address": "127.0.0.1:0", "resource_pool": "default"})

        # -- the serving half: real engines, master-managed ---------------
        cfg = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32,
                            n_heads=4, d_ff=64, max_seq_len=48,
                            remat=False, attention_impl="mha")
        params = gpt.init(jax.random.PRNGKey(0), cfg)
        aggregator = ClusterMetricsAggregator()
        fleet = ServingFleet(
            params, cfg, name="loadgen",
            buckets=BucketSpec.build(4, 16),
            cache=KVCacheConfig(num_blocks=24, block_size=8),
            max_queue_depth=max(64, serving_requests),
            iteration_floor_s=iteration_floor_s, aggregator=aggregator,
            prefix_cache=shared_prefix)
        link = MasterLink(fleet, port, replicas=serving_replicas,
                          resource_pool="serving")
        link.wait_replicas(serving_replicas, timeout=60)
        fleet.sample_telemetry()  # baseline for the tokens/sec delta

        stop = threading.Event()
        sims = [_AgentSim(port, f"loadgen-agent-{i}", stop)
                for i in range(agents)]
        for s in sims:
            s.start()

        serving_lat: list = []          # (total_s, request_id) pairs
        serving_errors = [0]

        # one KV block (block_size=8) of common system prompt; tails vary
        system_prefix = [7, 3, 5, 2, 9, 4, 6, 8]

        def drive_serving() -> None:
            handles = []
            for i in range(serving_requests):
                if stop.is_set():
                    break
                prompt = [1 + (i % 7), 2, 3]
                if shared_prefix:
                    prompt = system_prefix + prompt
                try:
                    handles.append(fleet.submit(
                        prompt, tokens_per_request, timeout=30.0))
                except Exception:  # noqa: BLE001 — counted, not fatal
                    serving_errors[0] += 1
            for h in handles:
                try:
                    res = h.result(60.0)
                    serving_lat.append((res.total_s, res.request_id))
                except Exception:  # noqa: BLE001
                    serving_errors[0] += 1

        serving_thread = threading.Thread(target=drive_serving,
                                          name="loadgen-serving",
                                          daemon=True)
        t_serving0 = time.monotonic()
        serving_thread.start()

        # -- the trial half, concurrent with the serving traffic ----------
        exp = _req(port, "POST", "/api/v1/experiments", {"config": {
            "name": "loadgen-mixed", "entrypoint": "noop:Noop",
            "searcher": {"name": "custom", "metric": "loss"},
            "resources": {"slots_per_trial": 1},
            "hyperparameters": {},
        }})
        exp_id = (exp.get("experiment") or exp)["id"]
        t_sub0 = time.monotonic()
        submitted = 0
        rid = 0
        while submitted < trials:
            if time.monotonic() - t_total0 > budget_s:
                break
            n = min(OPS_PER_BATCH, trials - submitted)
            ops = []
            for _ in range(n):
                ops.append({"type": "create", "request_id": rid,
                            "hparams": {}})
                ops.append({"type": "validate_after", "request_id": rid,
                            "units": 1})
                rid += 1
            _req(port, "POST",
                 f"/api/v1/experiments/{exp_id}/searcher/operations",
                 {"ops": ops}, timeout=60)
            submitted += n
        submit_wall = max(time.monotonic() - t_sub0, 1e-9)

        peak_queue = 0
        done = 0
        incomplete = False
        while True:
            s = _sched(port)
            gauges = s.get("gauges") or {}
            peak_queue = max(peak_queue, int(gauges.get("queue_depth") or 0))
            done = int(_counters(s).get("completed", 0)
                       - base_c.get("completed", 0))
            # completed_total counts every terminal allocation, serving
            # replicas included — subtract them to see the trial side
            serving_done = int(_counters(s).get("serving_completed", 0)
                               - base_c.get("serving_completed", 0))
            trial_done = (done - serving_done) >= submitted
            if trial_done and not serving_thread.is_alive():
                break
            if time.monotonic() - t_total0 > budget_s:
                incomplete = True
                break
            time.sleep(0.25)
        serving_thread.join(timeout=60)
        serving_wall = max(time.monotonic() - t_serving0, 1e-9)
        stop.set()
        for s_ in sims:
            s_.join(timeout=5)

        fleet.sample_telemetry()
        fleet_roll = aggregator.serving_fleet_rollup()
        fleet_stats = fleet.stats()
        # prefix-cache effectiveness, summed over the replicas' engines —
        # the hit-rate to read next to the serving p99 below
        prefix_hits = prefix_misses = 0
        for r in fleet.replicas():
            st = r.engine.stats()
            prefix_hits += st.prefix_hit_blocks
            prefix_misses += st.prefix_miss_blocks
        prefix_total = prefix_hits + prefix_misses
        prefix_hit_rate = (round(prefix_hits / prefix_total, 4)
                           if prefix_total else None)

        # the observed p99-slowest request, by id — paste it straight into
        # ``dct trace request <id>`` to pull the stitched per-request trace
        lat_pcts = _percentiles([t for t, _ in serving_lat])
        p99_slowest = None
        if serving_lat and lat_pcts["p99"] is not None:
            at_or_above = [(t, r) for t, r in serving_lat
                           if t >= lat_pcts["p99"]]
            pool = at_or_above or serving_lat
            p99_slowest = max(pool, key=lambda p: p[0])[1]

        final = _sched(port)
        fc, lat = _counters(final), final.get("latency") or {}
        # the acceptance probe: serving gang allocations visible in the
        # master's own scheduler families
        metrics_text = ""
        try:
            r = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
            with urllib.request.urlopen(r, timeout=10) as resp:
                metrics_text = resp.read().decode()
        except (OSError, ValueError):
            pass
        serving_families = sorted({
            line.split("{")[0].split(" ")[0]
            for line in metrics_text.splitlines()
            if line.startswith("dct_master_sched_serving")})

        def delta(name: str) -> int:
            return int(fc.get(name, 0) - base_c.get(name, 0))

        s2r = lat.get("submit_to_running_seconds") or {}
        return {
            "trials": {
                "requested": trials,
                "submitted": submitted,
                "completed": done,
                "submits_per_sec": round(submitted / submit_wall, 2),
                "peak_queue_depth": peak_queue,
                "submit_to_running_s": {
                    "p50": s2r.get("p50"), "p95": s2r.get("p95"),
                    "p99": s2r.get("p99"), "count": s2r.get("count"),
                },
            },
            "serving": {
                "replicas": serving_replicas,
                "requests": serving_requests,
                "errors": serving_errors[0],
                "completed": fleet_stats.completed,
                "tokens_generated": fleet_stats.tokens_generated,
                "tokens_per_sec": round(
                    fleet_stats.tokens_generated / serving_wall, 2),
                "request_total_s": lat_pcts,
                "p99_slowest_request_id": p99_slowest,
                "shared_prefix": shared_prefix,
                "prefix_hit_blocks": prefix_hits,
                "prefix_miss_blocks": prefix_misses,
                "prefix_hit_rate": prefix_hit_rate,
                "master_counters": {
                    "serving_submitted": delta("serving_submitted"),
                    "serving_running": delta("serving_running"),
                    "serving_completed": delta("serving_completed"),
                },
                "sched_serving_families": serving_families,
            },
            "fleet_rollup": fleet_roll,
            "duration_s": round(time.monotonic() - t_total0, 3),
            "agent_errors": sum(s_.errors for s_ in sims),
            "incomplete": incomplete,
        }
    except (OSError, ValueError, KeyError, ImportError) as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}
    finally:
        if link is not None:
            link.close(kill_fleet=True)
        if fleet is not None:
            fleet.close()
        if proc is not None:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def make_zipf_prompts(count: int, *, templates: int = 12,
                      skew: float = 1.1, seed: int = 0,
                      block_size: int = 8, shared_blocks: int = 1,
                      tail_len: int = 3) -> list:
    """Seeded Zipf-shaped prompt stream over a template pool.

    Every prompt opens with the same ``shared_blocks`` KV blocks of
    system prefix (the "millions of users, one system prompt" head),
    then one block of per-template body drawn Zipf(``skew``) — rank 1
    dominates — then a short per-request tail. The shape is what the
    KV hierarchy and router affinity are built for: a few hot chains
    plus a long cold tail, fully deterministic per ``seed``.
    """
    rnd = random.Random(seed)
    weights = [1.0 / (r ** skew) for r in range(1, max(1, templates) + 1)]
    total = sum(weights)
    system = [(7 * i + 3) % 89 + 1 for i in range(shared_blocks * block_size)]
    pool = []
    for t in range(max(1, templates)):
        body_rnd = random.Random(10_000 + t)
        pool.append(system
                    + [body_rnd.randrange(1, 90) for _ in range(block_size)])
    prompts = []
    for _ in range(count):
        x = rnd.random() * total
        acc = 0.0
        idx = 0
        for i, w in enumerate(weights):
            acc += w
            if x <= acc:
                idx = i
                break
        prompts.append(pool[idx]
                       + [rnd.randrange(1, 90) for _ in range(tail_len)])
    return prompts


def run_zipf_load(requests: int = 160, replicas: int = 4,
                  templates: int = 12, skew: float = 1.1, seed: int = 0,
                  tokens_per_request: int = 8, shared_blocks: int = 1,
                  iteration_floor_s: float = 0.01, kv_store=False,
                  restart_at: float | None = None,
                  budget_s: float = 300.0) -> dict:
    """Zipf-shaped serving load against a standalone fleet (no master).

    The measurement the KV memory hierarchy is judged by: fleet-wide
    prefix hit rate printed beside the request p99, under a seeded Zipf
    over a prompt-template pool whose heads share a system prefix.
    ``kv_store=False`` is the per-replica prefix-cache baseline;
    ``kv_store=True`` (or a ``KVBlockStore``) turns on the shared
    host/CAS tier plus router prefix affinity — the A/B bench.py runs.

    ``restart_at`` (a fraction of the burst) restarts one replica
    mid-burst through the drain protocol: the departing replica flushes
    its resident blocks to the tier, and the report's ``restart`` block
    shows how many blocks the replacement promoted back instead of
    re-prefilling (``kv_promoted_blocks`` > 0 with ``kv_miss_blocks``
    low is the warm-failover signature).
    """
    t0 = time.monotonic()
    fleet = None
    try:
        import jax

        from determined_clone_tpu.models import gpt
        from determined_clone_tpu.serving import ServingFleet
        from determined_clone_tpu.serving.bucketing import BucketSpec
        from determined_clone_tpu.serving.kv_cache import KVCacheConfig

        cfg = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32,
                            n_heads=4, d_ff=64, max_seq_len=64,
                            remat=False, attention_impl="mha")
        params = gpt.init(jax.random.PRNGKey(0), cfg)
        cache = KVCacheConfig(num_blocks=32, block_size=8)
        fleet = ServingFleet(
            params, cfg, name="zipf", buckets=BucketSpec.build(4, 32),
            cache=cache, max_queue_depth=max(64, requests),
            iteration_floor_s=iteration_floor_s,
            prefix_cache=True, kv_store=kv_store)
        fleet.scale_up(replicas)
        prompts = make_zipf_prompts(
            requests, templates=templates, skew=skew, seed=seed,
            block_size=cache.block_size, shared_blocks=shared_blocks)
        restart_idx = (min(requests - 1, max(1, int(requests * restart_at)))
                       if restart_at is not None else None)

        lat: list = []
        errors = [0]
        # engine counters survive replica teardown only if snapshotted
        # first — the burst's fleet-wide totals fold these back in
        retired = {"prefix_hits": 0, "prefix_misses": 0, "kv_host": 0,
                   "kv_cas": 0, "kv_miss": 0, "kv_promoted": 0,
                   "kv_spilled": 0}

        def drain(handles: list) -> None:
            for h in handles:
                try:
                    lat.append(h.result(60.0).total_s)
                except Exception:  # noqa: BLE001 — counted, not fatal
                    errors[0] += 1

        def snapshot(rep) -> None:
            st = rep.engine.stats()
            retired["prefix_hits"] += st.prefix_hit_blocks
            retired["prefix_misses"] += st.prefix_miss_blocks
            retired["kv_host"] += st.kv_host_hit_blocks
            retired["kv_cas"] += st.kv_cas_hit_blocks
            retired["kv_miss"] += st.kv_miss_blocks
            retired["kv_promoted"] += st.kv_promoted_blocks
            retired["kv_spilled"] += st.kv_spilled_blocks

        restarted = None
        handles: list = []
        for i, prompt in enumerate(prompts):
            if restart_idx is not None and i == restart_idx:
                # quiesce in-flight work, then restart one replica
                # through the drain protocol (stop_replica flushes its
                # resident blocks to the tier on the way down)
                drain(handles)
                handles = []
                victim_id = fleet.replica_ids()[0]
                with fleet._lock:
                    victim = fleet._replicas[victim_id]
                # flush before snapshotting so the victim's spill
                # counters land in the totals (stop_replica's own flush
                # then dedups as duplicate_puts)
                fleet._flush_kv(victim)
                snapshot(victim)
                fleet.stop_replica(victim_id)
                restarted = fleet.scale_up(1)[0]
            if time.monotonic() - t0 > budget_s:
                break
            try:
                handles.append(fleet.submit(prompt, tokens_per_request,
                                            timeout=30.0))
            except Exception:  # noqa: BLE001
                errors[0] += 1
        drain(handles)

        hits = retired["prefix_hits"]
        misses = retired["prefix_misses"]
        kv = dict(retired)
        warm = None
        for rep in fleet.replicas():
            st = rep.engine.stats()
            hits += st.prefix_hit_blocks
            misses += st.prefix_miss_blocks
            kv["kv_host"] += st.kv_host_hit_blocks
            kv["kv_cas"] += st.kv_cas_hit_blocks
            kv["kv_miss"] += st.kv_miss_blocks
            kv["kv_promoted"] += st.kv_promoted_blocks
            kv["kv_spilled"] += st.kv_spilled_blocks
            if rep.replica_id == restarted:
                warm = {
                    "replica": restarted,
                    "kv_promoted_blocks": st.kv_promoted_blocks,
                    "kv_host_hit_blocks": st.kv_host_hit_blocks,
                    "kv_cas_hit_blocks": st.kv_cas_hit_blocks,
                    "kv_miss_blocks": st.kv_miss_blocks,
                    "prefix_hit_blocks": st.prefix_hit_blocks,
                }
        looked = hits + misses
        kv_looked = kv["kv_host"] + kv["kv_cas"] + kv["kv_miss"]
        return {
            "requests": requests,
            "completed": len(lat),
            "errors": errors[0],
            "replicas": replicas,
            "templates": templates,
            "skew": skew,
            "seed": seed,
            "kv_store": bool(kv_store),
            "request_total_s": _percentiles(lat),
            "prefix_hit_blocks": hits,
            "prefix_miss_blocks": misses,
            "prefix_hit_rate": (round(hits / looked, 4)
                                if looked else None),
            "kv_tier_hit_rate": (round(
                (kv["kv_host"] + kv["kv_cas"]) / kv_looked, 4)
                if kv_looked else None),
            "kv_host_hit_blocks": kv["kv_host"],
            "kv_cas_hit_blocks": kv["kv_cas"],
            "kv_miss_blocks": kv["kv_miss"],
            "kv_promoted_blocks": kv["kv_promoted"],
            "kv_spilled_blocks": kv["kv_spilled"],
            "kv_stats": fleet.kv_stats(),
            "restart": warm,
            "duration_s": round(time.monotonic() - t0, 3),
        }
    except ImportError as exc:
        return {"error": f"ImportError: {exc}"}
    finally:
        if fleet is not None:
            fleet.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--trials", type=int, default=1000)
    parser.add_argument("--agents", type=int, default=8)
    parser.add_argument("--slots", type=int, default=8,
                        help="slots per simulated agent")
    parser.add_argument("--budget", type=float, default=180.0,
                        help="total wall-clock budget in seconds")
    parser.add_argument("--master", default=None,
                        help="PORT of a live master (default: spawn one)")
    parser.add_argument("--mixed", action="store_true",
                        help="mixed traffic: trials + a real serving "
                             "fleet on one simulated cluster")
    parser.add_argument("--serving-replicas", type=int, default=2)
    parser.add_argument("--serving-requests", type=int, default=120)
    parser.add_argument("--shared-prefix", action="store_true",
                        help="serving traffic shares a common system "
                             "prompt (exercises the COW prefix cache; "
                             "reports block hit-rate beside p99)")
    parser.add_argument("--zipf", action="store_true",
                        help="master-free Zipf serving load: seeded Zipf "
                             "over a prompt-template pool with shared "
                             "system-prefix heads; reports fleet-wide "
                             "prefix hit rate beside p99")
    parser.add_argument("--zipf-templates", type=int, default=12)
    parser.add_argument("--zipf-skew", type=float, default=1.1)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--kv-store", action="store_true",
                        help="with --zipf: turn on the fleet-wide KV "
                             "memory hierarchy (host tier + router "
                             "prefix affinity)")
    parser.add_argument("--restart-at", type=float, default=None,
                        help="with --zipf: restart one replica after "
                             "this fraction of the burst (warm-failover "
                             "leg)")
    args = parser.parse_args(argv)
    if args.zipf:
        result = run_zipf_load(
            requests=args.serving_requests,
            replicas=args.serving_replicas,
            templates=args.zipf_templates, skew=args.zipf_skew,
            seed=args.seed, kv_store=args.kv_store,
            restart_at=args.restart_at, budget_s=args.budget)
    elif args.mixed:
        result = run_mixed_load(
            trials=args.trials, agents=args.agents,
            slots_per_agent=args.slots,
            serving_replicas=args.serving_replicas,
            serving_requests=args.serving_requests, budget_s=args.budget,
            master_port=int(args.master) if args.master else None,
            shared_prefix=args.shared_prefix)
    else:
        result = run_load(trials=args.trials, agents=args.agents,
                          slots_per_agent=args.slots, budget_s=args.budget,
                          master_port=int(args.master) if args.master
                          else None)
    print(json.dumps(result, indent=2))
    return 1 if result.get("error") else 0


if __name__ == "__main__":
    sys.exit(main())
