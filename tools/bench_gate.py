#!/usr/bin/env python3
"""Bench regression gate — compare two BENCH rounds.

First enforcement of ROADMAP item 5's "every perf PR must move MFU or
tokens/sec": given the previous and the new bench result, fail (exit 1)
when

- the new round's throughput (samples/sec/chip) dropped more than the
  tolerance (default -5%) against the old round on the *same platform*
  (platform changed, e.g. TPU came back → throughput compare is skipped
  with a warning, not failed: cross-platform numbers are incomparable);
- the new round has a null ``mfu`` — the analytic FLOPs engine makes the
  field unconditional, so null means the accounting regressed.

Accepts either the raw bench.py JSON line or the driver's ``BENCH_rN.json``
wrapper ({"n", "cmd", "rc", "tail"}), where the result is the last JSON
object with a "metric" key inside ``tail``.

Usage:
    python tools/bench_gate.py OLD.json NEW.json [--tolerance -0.05]
    python tools/bench_gate.py            # two newest BENCH_r*.json in cwd
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, Optional, Tuple

DEFAULT_TOLERANCE = -0.05


def _last_metric_line(text: str) -> Optional[Dict[str, Any]]:
    result = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            result = obj
    return result


def load_bench(path: str) -> Dict[str, Any]:
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and "metric" in obj:
        return obj
    if isinstance(obj, dict) and "tail" in obj:
        inner = _last_metric_line(str(obj["tail"]))
        if inner is not None:
            return inner
        raise ValueError(f"{path}: wrapper 'tail' holds no bench result line")
    raise ValueError(f"{path}: neither a bench result nor a BENCH_rN wrapper")


def newest_rounds(directory: str = ".") -> Tuple[str, str]:
    rounds = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    rounds.sort()
    if len(rounds) < 2:
        raise ValueError(
            f"need two BENCH_r*.json rounds in {directory!r}, "
            f"found {len(rounds)}")
    return rounds[-2][1], rounds[-1][1]


# Optional detail sections that come and go with the environment (TPU
# tunnel mood, master build availability). A round missing one that the
# previous round carried is a skip-with-note, never a gate failure — the
# headline throughput/mfu checks below are the contract.
OPTIONAL_SECTIONS = ("control_plane", "checkpoint_io", "pipeline",
                     "mnist_cnn", "tpu_probe_telemetry", "xla", "goodput",
                     "serving", "serving_fleet", "exec_cache", "multichip",
                     "tsdb", "recovery", "kv_hierarchy")


def _section_notes(old_detail: Dict[str, Any], new_detail: Dict[str, Any],
                   report: list) -> None:
    for name in OPTIONAL_SECTIONS:
        if old_detail.get(name) is not None and new_detail.get(name) is None:
            report.append(
                f"note: section {name!r} present in the previous round is "
                f"missing in the new one; compare skipped")


def _control_plane_lines(old_detail: Dict[str, Any],
                         new_detail: Dict[str, Any], report: list) -> None:
    """Advisory control-plane reporting (tools/loadgen.py section): the
    numbers land in the report so regressions are visible in BENCH
    history, but only a round that errored where the previous one
    succeeded warrants a WARN — the synthetic load shares the box with
    the bench itself, so absolute latency is too noisy to hard-gate."""
    cp_new = new_detail.get("control_plane")
    if not isinstance(cp_new, dict):
        return
    if cp_new.get("error"):
        report.append(f"WARN: control_plane errored: {cp_new['error']}")
        return
    s2r = cp_new.get("submit_to_running_s") or {}

    def _f(v: Any) -> str:
        return f"{v:.3f}" if isinstance(v, (int, float)) else "null"

    report.append(
        f"ok: control_plane {cp_new.get('completed')}/{cp_new.get('trials')} "
        f"trials: {cp_new.get('submits_per_sec')} submits/s, "
        f"{cp_new.get('decisions_per_sec')} decisions/s, "
        f"submit→running p50={_f(s2r.get('p50'))}s p99={_f(s2r.get('p99'))}s, "
        f"peak queue {cp_new.get('peak_queue_depth')}")
    cp_old = old_detail.get("control_plane")
    if (isinstance(cp_old, dict) and not cp_old.get("error")
            and isinstance(s2r.get("p99"), (int, float))):
        old_p99 = (cp_old.get("submit_to_running_s") or {}).get("p99")
        if isinstance(old_p99, (int, float)) and old_p99 > 0 \
                and s2r["p99"] > 2.0 * old_p99:
            report.append(
                f"WARN: control_plane submit→running p99 "
                f"{old_p99:.3f}s → {s2r['p99']:.3f}s (>2x)")


def _xla_lines(old_detail: Dict[str, Any],
               new_detail: Dict[str, Any], report: list) -> None:
    """Advisory XLA-section reporting: compile time and measured MFU land
    in the report so drift is visible in BENCH history, with WARNs on a
    compile-time blowup (>2x — what ROADMAP item 4's executable cache is
    meant to erase) or a measured-MFU drop beyond the throughput
    tolerance. Advisory-only: compile time shares the box with everything
    else, and a fingerprint change legitimately resets both numbers."""
    xla_new = new_detail.get("xla")
    if not isinstance(xla_new, dict):
        return
    ct = xla_new.get("compile_time_s")
    mm = xla_new.get("measured_mfu")
    fp = xla_new.get("fingerprint")
    report.append(
        f"ok: xla compile={ct}s measured_mfu={mm} "
        f"program={fp or '?'} peak_mem={xla_new.get('peak_memory_bytes')}")
    xla_old = old_detail.get("xla")
    if not isinstance(xla_old, dict):
        return
    same_program = fp and xla_old.get("fingerprint") == fp
    old_ct = xla_old.get("compile_time_s")
    if (isinstance(old_ct, (int, float)) and old_ct > 0
            and isinstance(ct, (int, float)) and ct > 2.0 * old_ct):
        note = "" if same_program else " (program fingerprint changed)"
        report.append(
            f"WARN: xla compile time {old_ct:.3f}s → {ct:.3f}s (>2x){note}")
    old_mm = xla_old.get("measured_mfu")
    if (same_program and isinstance(old_mm, (int, float)) and old_mm > 0
            and isinstance(mm, (int, float))
            and mm / old_mm - 1.0 < DEFAULT_TOLERANCE):
        report.append(
            f"WARN: measured MFU {old_mm:.6f} → {mm:.6f} on the same "
            f"program fingerprint ({mm / old_mm - 1.0:+.1%})")


def _goodput_lines(old_detail: Dict[str, Any],
                   new_detail: Dict[str, Any], report: list) -> None:
    """Advisory goodput-section reporting (telemetry/goodput.py, measured
    on a real trainer mini-run inside bench): the fraction lands in the
    report so badput drift is visible in BENCH history. WARNs when the
    section errored, when the conservation invariant broke (the ledger
    over-counted — a wiring bug, not an environment mood), when the
    fraction is null, or when it dropped more than 10 points against the
    previous round. Advisory-only: the mini-run shares the box with the
    bench ladder, so absolute goodput is noisy; the enforced contract is
    the tier-1 conservation test."""
    gp_new = new_detail.get("goodput")
    if not isinstance(gp_new, dict):
        return
    if gp_new.get("error"):
        report.append(f"WARN: goodput errored: {gp_new['error']}")
        return
    frac = gp_new.get("goodput_fraction")
    if not gp_new.get("conservation_ok", False):
        report.append(
            "WARN: goodput conservation violated "
            f"(error_fraction={gp_new.get('conservation_error_fraction')})")
    if not isinstance(frac, (int, float)):
        report.append("WARN: goodput_fraction is null")
        return
    cats = gp_new.get("categories") or {}
    badput = sorted(((c, s) for c, s in cats.items()
                     if c != "productive" and isinstance(s, (int, float))),
                    key=lambda kv: -kv[1])[:2]
    bad_s = " ".join(f"{c}={s:.2f}s" for c, s in badput)
    report.append(
        f"ok: goodput fraction={frac:.4f} over {gp_new.get('wall_s')}s "
        f"(top badput: {bad_s or 'none'})")
    gp_old = old_detail.get("goodput")
    if isinstance(gp_old, dict):
        old_frac = gp_old.get("goodput_fraction")
        if (isinstance(old_frac, (int, float))
                and frac < old_frac - 0.10):
            report.append(
                f"WARN: goodput fraction {old_frac:.4f} → {frac:.4f} "
                f"(dropped more than 10 points)")


def _serving_lines(old_detail: Dict[str, Any],
                   new_detail: Dict[str, Any], report: list) -> None:
    """Advisory serving-section reporting (serving/engine.py measured by
    bench's latency-vs-load sweep): tokens/sec and p50/p99 at the highest
    offered load land in the report, with WARNs when the section errored,
    when continuous batching stopped beating the static run-to-completion
    baseline (continuous_over_static < 1 — the whole point of the
    scheduler), or when tokens/sec dropped / p99 grew more than 10%
    against the previous round at the same offered load. Advisory-only:
    the tiny-model sweep shares the box with the bench ladder; the
    enforced contracts are the tier-1 parity and compile-discipline
    tests."""
    sv_new = new_detail.get("serving")
    if not isinstance(sv_new, dict):
        return
    if sv_new.get("error"):
        report.append(f"WARN: serving errored: {sv_new['error']}")
        return
    points = [p for p in (sv_new.get("load_points") or [])
              if isinstance(p, dict)]
    if not points:
        report.append("WARN: serving section has no load points")
        return
    top = points[-1]
    report.append(
        f"ok: serving {len(points)} load points, top "
        f"{top.get('offered_rps')} req/s: {top.get('tokens_per_sec')} tok/s, "
        f"p50={top.get('p50_total_s')}s p99={top.get('p99_total_s')}s, "
        f"programs {sv_new.get('programs_compiled')}/"
        f"{sv_new.get('program_budget')}")
    ratio = sv_new.get("continuous_over_static")
    if isinstance(ratio, (int, float)) and ratio < 1.0:
        report.append(
            f"WARN: continuous batching no longer beats static "
            f"run-to-completion (continuous_over_static={ratio})")
    # observability lane (docs/observability.md "Request tracing & SLOs"):
    # per-request tracing must stay near-free at top load, and the round's
    # simulated-clock SLO verdict must not be burning its fast windows
    overhead = sv_new.get("tracing_overhead")
    if not isinstance(overhead, (int, float)):
        report.append("WARN: tracing_overhead is null — the traced/"
                      "untraced A/B did not run")
    elif overhead > 0.02:
        report.append(
            f"WARN: tracing overhead {overhead:.1%} > 2% at top load "
            f"({top.get('tokens_per_sec')} → "
            f"{sv_new.get('traced_tokens_per_sec')} tok/s traced)")
    else:
        report.append(f"ok: tracing overhead {overhead:.1%} at top load")
    slo = sv_new.get("slo")
    if not isinstance(slo, dict) or slo.get("verdict") is None:
        report.append("WARN: serving SLO verdict is null")
    elif slo.get("burning_fast"):
        report.append(
            f"WARN: serving SLO fast windows burning "
            f"(verdict={slo.get('verdict')}, 5m latency burn "
            f"{slo.get('latency_burn_5m')}x over threshold "
            f"{slo.get('latency_threshold_s')}s)")
    else:
        report.append(
            f"ok: serving SLO verdict {slo.get('verdict')} "
            f"(latency threshold {slo.get('latency_threshold_s')}s)")
    sv_old = old_detail.get("serving")
    if not isinstance(sv_old, dict) or sv_old.get("error"):
        sv_old = {}
    _serving_optimized_lines(sv_old, sv_new, report)
    if not sv_old:
        return
    old_by_rate = {p.get("offered_rps"): p
                   for p in (sv_old.get("load_points") or [])
                   if isinstance(p, dict)}
    for p in points:
        q = old_by_rate.get(p.get("offered_rps"))
        if not isinstance(q, dict):
            continue
        rate = p.get("offered_rps")
        tps_old, tps_new = q.get("tokens_per_sec"), p.get("tokens_per_sec")
        if (isinstance(tps_old, (int, float)) and tps_old > 0
                and isinstance(tps_new, (int, float))
                and tps_new / tps_old - 1.0 < -0.10):
            report.append(
                f"WARN: serving tokens/sec at {rate} req/s "
                f"{tps_old} → {tps_new} ({tps_new / tps_old - 1.0:+.1%})")
        p99_old, p99_new = q.get("p99_total_s"), p.get("p99_total_s")
        if (isinstance(p99_old, (int, float)) and p99_old > 0
                and isinstance(p99_new, (int, float))
                and p99_new / p99_old - 1.0 > 0.10):
            report.append(
                f"WARN: serving p99 at {rate} req/s "
                f"{p99_old}s → {p99_new}s ({p99_new / p99_old - 1.0:+.1%})")


def _serving_optimized_lines(sv_old: Dict[str, Any],
                             sv_new: Dict[str, Any], report: list) -> None:
    """The raw-speed lane (prefix sharing + speculative decoding +
    chunked prefill, docs/serving.md): report the optimized engine's
    top-load tokens/sec and its ratio over the features-off baseline
    measured in the SAME round, and WARN when

    - the speculative acceptance rate is null or below 0.3 (the draft
      is wasting more verify work than it saves — time to retrain or
      shrink it),
    - the prefix-cache hit rate regressed vs the previous round (the
      hashing/eviction path stopped matching what it used to), or
    - p99 at the top offered load grew more than 2x vs the previous
      round (chunked prefill exists precisely to keep tail latency flat
      under load — a 2x jump means long prompts are blocking decode
      again).

    Old rounds without the optimized section skip the cross-round
    checks (the section landed with the raw-speed PR)."""
    opt_new = sv_new.get("optimized")
    if not isinstance(opt_new, dict):
        return
    pts = [p for p in (opt_new.get("load_points") or [])
           if isinstance(p, dict)]
    top = pts[-1] if pts else {}
    acc = opt_new.get("acceptance_rate")
    hit_rate = opt_new.get("prefix_hit_rate")
    report.append(
        f"ok: serving-optimized top {top.get('offered_rps')} req/s: "
        f"{top.get('tokens_per_sec')} tok/s "
        f"({sv_new.get('optimized_over_baseline')}x baseline), "
        f"acceptance={acc}, prefix_hit_rate={hit_rate}, programs "
        f"{opt_new.get('programs_compiled')}/"
        f"{opt_new.get('program_budget')}")
    if not isinstance(acc, (int, float)):
        report.append(
            "WARN: speculative acceptance rate is null with speculation "
            "enabled — the verify path banked no decisions")
    elif acc < 0.3:
        report.append(
            f"WARN: speculative acceptance rate {acc} < 0.3 — the draft "
            f"wastes more verify work than it saves")
    opt_old = sv_old.get("optimized")
    if not isinstance(opt_old, dict):
        return
    hit_old = opt_old.get("prefix_hit_rate")
    if (isinstance(hit_old, (int, float))
            and isinstance(hit_rate, (int, float))
            and hit_rate < hit_old - 0.05):
        report.append(
            f"WARN: prefix-cache hit rate {hit_old} → {hit_rate} "
            f"(regressed — hashing or eviction path changed behavior)")
    old_pts = [p for p in (opt_old.get("load_points") or [])
               if isinstance(p, dict)]
    if old_pts and pts:
        p99_old = old_pts[-1].get("p99_total_s")
        p99_new = top.get("p99_total_s")
        if (isinstance(p99_old, (int, float)) and p99_old > 0
                and isinstance(p99_new, (int, float))
                and p99_new / p99_old > 2.0):
            report.append(
                f"WARN: optimized p99 at top load {p99_old}s → {p99_new}s "
                f"(more than 2x — chunked prefill is no longer keeping "
                f"tail latency flat)")


def _serving_fleet_lines(old_detail: Dict[str, Any],
                         new_detail: Dict[str, Any], report: list) -> None:
    """Advisory fleet-section reporting (serving/fleet.py measured by
    bench's replica-scaling ladder): aggregate tokens/sec at 1/2/4
    replicas plus the mid-burst blue-green rollout. WARNs when the
    section errored, when throughput stopped scaling monotonically with
    replica count, when 2 replicas deliver under 1.6x of 1 (the paced
    engines should land ~2x — below 1.6x the router or the drain path is
    eating the gain), or when the rollout dropped requests / broke
    greedy version parity. Advisory-only: the ladder shares the box with
    the bench itself; the enforced contracts are the tier-1 fleet
    tests."""
    sf_new = new_detail.get("serving_fleet")
    if not isinstance(sf_new, dict):
        return
    if sf_new.get("error"):
        report.append(f"WARN: serving_fleet errored: {sf_new['error']}")
        return
    points = [p for p in (sf_new.get("points") or [])
              if isinstance(p, dict)]
    if not points:
        report.append("WARN: serving_fleet section has no points")
        return
    ladder = " ".join(
        f"{p.get('replicas')}x={p.get('tokens_per_sec')}tok/s"
        f"(p99={p.get('p99_total_s')}s)" for p in points)
    report.append(
        f"ok: serving_fleet {ladder}, speedup@2={sf_new.get('speedup_2')} "
        f"@4={sf_new.get('speedup_4')}")
    if not sf_new.get("monotonic", False):
        report.append(
            "WARN: serving_fleet tokens/sec is not monotonic in replica "
            "count — adding replicas should add capacity")
    sp2 = sf_new.get("speedup_2")
    if isinstance(sp2, (int, float)) and sp2 < 1.6:
        report.append(
            f"WARN: serving_fleet 2-replica speedup {sp2} < 1.6x")
    ro = sf_new.get("rollout")
    if isinstance(ro, dict):
        failed = ro.get("failed")
        if isinstance(failed, (int, float)) and failed > 0:
            report.append(
                f"WARN: blue-green rollout dropped {failed} requests "
                f"(the drain protocol promises zero)")
        if not ro.get("parity_ok", False):
            report.append(
                "WARN: blue-green rollout broke greedy version parity "
                "(a response mixed old and new params)")
        else:
            report.append(
                f"ok: rollout under load: {ro.get('failed')} failed, "
                f"{ro.get('old_version_responses')} old / "
                f"{ro.get('new_version_responses')} new responses, "
                f"{ro.get('rollout_duration_s')}s")


def _exec_cache_lines(old_detail: Dict[str, Any],
                      new_detail: Dict[str, Any], report: list) -> None:
    """Advisory executable-cache reporting (storage/exec_cache.py via
    bench's cold/warm replica-start A/B): WARNs when the section
    errored, when the warm leg hit rate is zero (every program
    recompiled — the persistent cache did nothing), when any warm
    program fell back to a plain compile, when the warm leg's greedy
    tokens diverged from the cold leg's (a deserialized executable must
    be the same program, so the same bits), or when the warm replica
    start regressed more than 2x against the previous round. Advisory
    only: wall-times share the box with the bench; the enforced
    contracts are the tier-1 exec-cache tests."""
    ec_new = new_detail.get("exec_cache")
    if not isinstance(ec_new, dict):
        return
    if ec_new.get("error"):
        report.append(f"WARN: exec_cache errored: {ec_new['error']}")
        return
    report.append(
        f"ok: exec_cache cold {ec_new.get('cold_replica_start_s')}s → warm "
        f"{ec_new.get('warm_replica_start_s')}s "
        f"({ec_new.get('speedup')}x), {ec_new.get('exec_cache_hits')} hits/"
        f"{ec_new.get('exec_cache_misses')} misses, saved "
        f"{ec_new.get('compile_time_saved_s')}s of compile")
    rate = ec_new.get("warm_hit_rate")
    if isinstance(rate, (int, float)) and rate <= 0:
        report.append(
            "WARN: exec_cache warm leg hit rate is 0 — every program "
            "recompiled; the persistent cache is not being consulted")
    fallbacks = ec_new.get("fallback_compiles")
    if isinstance(fallbacks, (int, float)) and fallbacks > 0:
        report.append(
            f"WARN: exec_cache warm leg fell back to plain compile "
            f"{fallbacks} time(s) — a cached executable failed to "
            f"load or dispatch")
    if ec_new.get("tokens_match") is False:
        report.append(
            "WARN: exec_cache warm-leg greedy tokens diverged from the "
            "cold leg — a deserialized executable produced different bits")
    ec_old = old_detail.get("exec_cache")
    warm_new = ec_new.get("warm_replica_start_s")
    warm_old = (ec_old.get("warm_replica_start_s")
                if isinstance(ec_old, dict) else None)
    if (isinstance(warm_old, (int, float)) and warm_old > 0
            and isinstance(warm_new, (int, float))
            and warm_new > 2.0 * warm_old):
        report.append(
            f"WARN: exec_cache warm replica start regressed "
            f"{warm_old}s → {warm_new}s (>2x) — deserialization or "
            f"blob-store reads got slower")


def _multichip_lines(old_detail: Dict[str, Any],
                     new_detail: Dict[str, Any], report: list) -> bool:
    """Multichip scaling-lane gate (parallel/scaling_bench.py via bench's
    ``multichip`` section, one artifact per simulated mesh size). Unlike
    the advisory sections this one ENFORCES: per-axis scaling efficiency
    dropping more than 5 points against the previous round on the same
    mesh size fails the gate — the simulated mesh timeshares one host, so
    the absolute numbers are pessimistic but *stable*, and a 5-point drop
    means the sharded program itself got worse (more collective volume,
    lost overlap), which real ICI will amplify. Collective-structure
    drift on an unchanged program fingerprint stays an advisory WARN: new
    collectives can be a legitimate partitioner change, but it is exactly
    what to look at first when the efficiency line fails.

    Returns False when the gate should fail, True otherwise."""
    mc_new = new_detail.get("multichip")
    if not isinstance(mc_new, dict):
        return True
    if mc_new.get("error"):
        report.append(f"WARN: multichip errored: {mc_new['error']}")
        return True
    ok = True
    mc_old = old_detail.get("multichip")
    old_runs = (mc_old.get("runs") or {}) if isinstance(mc_old, dict) else {}
    for size, run in sorted((mc_new.get("runs") or {}).items(),
                            key=lambda kv: int(kv[0])
                            if str(kv[0]).isdigit() else 0):
        if not isinstance(run, dict):
            continue
        if run.get("error"):
            report.append(f"WARN: multichip[{size}] errored: {run['error']}")
            continue
        if run.get("schema_errors"):
            report.append(f"WARN: multichip[{size}] artifact failed schema "
                          f"validation: {run['schema_errors']}")
        meshes = run.get("meshes") or {}
        effs = " ".join(
            f"{ax}={m.get('scaling_efficiency'):.3f}"
            if isinstance(m.get("scaling_efficiency"), (int, float))
            else f"{ax}=null"
            for ax, m in sorted(meshes.items()) if isinstance(m, dict))
        report.append(f"ok: multichip {size} devices: {effs}")
        old_run = old_runs.get(size)
        old_meshes = (old_run.get("meshes") or {}) \
            if isinstance(old_run, dict) else {}
        for axis, m in sorted(meshes.items()):
            if not isinstance(m, dict):
                continue
            eff = m.get("scaling_efficiency")
            old_m = old_meshes.get(axis)
            if not isinstance(old_m, dict):
                continue
            old_eff = old_m.get("scaling_efficiency")
            if (isinstance(old_eff, (int, float))
                    and isinstance(eff, (int, float))
                    and eff < old_eff - 0.05):
                ok = False
                report.append(
                    f"FAIL: multichip {size}-device {axis} scaling "
                    f"efficiency {old_eff:.3f} → {eff:.3f} (dropped more "
                    f"than 5 points — the sharded program regressed)")
            fp_new, fp_old = m.get("program_fingerprint"), \
                old_m.get("program_fingerprint")
            coll_new = (m.get("collectives") or {}).get("fingerprint")
            coll_old = (old_m.get("collectives") or {}).get("fingerprint")
            if (fp_new and fp_new == fp_old
                    and coll_new and coll_old and coll_new != coll_old):
                report.append(
                    f"WARN: multichip {size}-device {axis} collective "
                    f"structure drifted on an unchanged program "
                    f"({coll_old} → {coll_new}) — the partitioner is "
                    f"emitting different collectives for the same trace")
    return ok


def _tsdb_lines(old_detail: Dict[str, Any],
                new_detail: Dict[str, Any], report: list) -> None:
    """Advisory time-series-layer reporting (telemetry/tsdb.py measured
    by bench's synthetic scrape soak): WARNs when the section errored,
    when the scrape+store+rule-evaluation duty cycle exceeds 2% of the
    scrape period (the scrape loop shares the master process — it must
    stay invisible next to request handling), or when the store ended
    the soak over its memory budget (eviction stopped keeping up).
    Advisory-only: wall-times share the box with the bench; the
    enforced contracts are the tier-1 TSDB tests."""
    ts_new = new_detail.get("tsdb")
    if not isinstance(ts_new, dict):
        return
    if ts_new.get("error"):
        report.append(f"WARN: tsdb errored: {ts_new['error']}")
        return
    duty = ts_new.get("duty_fraction")
    duty_s = f"{duty:.3%}" if isinstance(duty, (int, float)) else "null"
    report.append(
        f"ok: tsdb {ts_new.get('series')} series, "
        f"{ts_new.get('samples_per_scrape')} samples/scrape, "
        f"scrape {ts_new.get('scrape_ms')}ms → duty {duty_s} of the "
        f"{ts_new.get('scrape_period_s')}s period")
    if not isinstance(duty, (int, float)):
        report.append("WARN: tsdb duty_fraction is null — the scrape "
                      "soak banked no timing")
    elif duty > 0.02:
        report.append(
            f"WARN: tsdb scrape duty cycle {duty:.2%} > 2% of the "
            f"scrape period — storing the cluster view is crowding "
            f"the master")
    if ts_new.get("within_budget") is False:
        report.append(
            f"WARN: tsdb ended the soak over its memory budget "
            f"({ts_new.get('bytes_estimate')} > "
            f"{ts_new.get('memory_budget_bytes')} bytes) — eviction "
            f"is not keeping up with series churn")


def _recovery_lines(old_detail: Dict[str, Any],
                    new_detail: Dict[str, Any], report: list) -> None:
    """Advisory self-healing reporting (serving/supervisor.py measured
    by bench's fault-storm section): WARNs when the section errored,
    when any leg lost an accepted request or left a ledger entry open
    (exactly-once failover is the tentpole contract), when KV blocks
    leaked through a crash teardown, when MTTR blew the budget, or when
    the supervised leg recovered to under half the clean leg's
    throughput. Advisory-only: the enforced contracts are the chaos
    lane's scenario asserts (tests/test_self_healing.py)."""
    rec = new_detail.get("recovery")
    if not isinstance(rec, dict):
        return
    if rec.get("error"):
        report.append(f"WARN: recovery errored: {rec['error']}")
        return
    healed = rec.get("supervised") or {}
    frac = rec.get("recovered_throughput_fraction")
    report.append(
        f"ok: recovery supervised leg {healed.get('completed')}/"
        f"{rec.get('requests')} completed, p99 {healed.get('p99_s')}s, "
        f"mttr {healed.get('mttr_s')}s, "
        f"{healed.get('replacements')} replacement(s), "
        f"throughput x{frac} of clean")
    budget = float(rec.get("mttr_budget_s") or 30.0)
    for leg_name in ("clean", "unsupervised", "supervised"):
        leg = rec.get(leg_name)
        if not isinstance(leg, dict):
            continue
        lost = int(leg.get("lost") or 0)
        open_n = int(leg.get("open_ledger_entries") or 0)
        if lost or open_n:
            report.append(
                f"WARN: recovery {leg_name} leg lost {lost} request(s) "
                f"({open_n} ledger entries left open) — exactly-once "
                f"failover dropped accepted work")
        leaked = int(leg.get("leaked_blocks") or 0)
        if leaked:
            report.append(
                f"WARN: recovery {leg_name} leg leaked {leaked} KV "
                f"block(s) — a crash teardown dropped refs")
        mttr = leg.get("mttr_s")
        if isinstance(mttr, (int, float)) and mttr > budget:
            report.append(
                f"WARN: recovery {leg_name} leg MTTR {mttr}s > "
                f"{budget}s budget — replacement warm-start regressed")
    if isinstance(frac, (int, float)) and frac < 0.5:
        report.append(
            f"WARN: recovery supervised throughput only x{frac} of the "
            f"clean run — self-healing is not restoring capacity")


def _kv_hierarchy_lines(old_detail: Dict[str, Any],
                        new_detail: Dict[str, Any], report: list) -> None:
    """Advisory KV-memory-hierarchy reporting (serving/kv_store.py
    measured by bench's Zipf A/B + restart leg): WARNs when the section
    errored, when the tiered leg's fleet-wide prefix hit rate fell more
    than 0.05 below the prefix-cache-only baseline (the tier should
    only ever add hits), when the tiered p99 regressed more than 2x
    against the baseline leg of the SAME round (both legs share the
    box, so cross-round wall-time compares are noise), or when the
    mid-burst replacement replica promoted nothing from the tier (a
    cold restart — the hierarchy's whole point is the warm one).
    Advisory-only: the enforced contracts are tests/test_kv_store.py
    and the kv_warm_failover chaos scenario."""
    kv = new_detail.get("kv_hierarchy")
    if not isinstance(kv, dict):
        return
    if kv.get("error"):
        report.append(f"WARN: kv_hierarchy errored: {kv['error']}")
        return
    restart = kv.get("restart") or {}
    report.append(
        f"ok: kv_hierarchy prefix hit rate "
        f"{kv.get('baseline_prefix_hit_rate')} → "
        f"{kv.get('tiered_prefix_hit_rate')} with tier "
        f"(tier hit rate {kv.get('kv_tier_hit_rate')}), restart promoted "
        f"{restart.get('kv_promoted_blocks')} block(s) from the tier")
    base_rate = kv.get("baseline_prefix_hit_rate")
    tier_rate = kv.get("tiered_prefix_hit_rate")
    if (isinstance(base_rate, (int, float))
            and isinstance(tier_rate, (int, float))
            and tier_rate < base_rate - 0.05):
        report.append(
            f"WARN: kv_hierarchy tiered prefix hit rate {tier_rate} fell "
            f"more than 0.05 below the baseline {base_rate} — promotion "
            f"or affinity routing is losing coverage it should add")
    base_p99 = kv.get("baseline_p99_s")
    tier_p99 = kv.get("tiered_p99_s")
    if (isinstance(base_p99, (int, float)) and base_p99 > 0
            and isinstance(tier_p99, (int, float))
            and tier_p99 > 2.0 * base_p99):
        report.append(
            f"WARN: kv_hierarchy tiered p99 {tier_p99}s > 2x baseline "
            f"{base_p99}s — tier lookups/promotion are stalling the "
            f"admission path")
    if restart and not kv.get("restart_warm"):
        report.append(
            "WARN: kv_hierarchy restarted replica promoted 0 blocks from "
            "the tier — the mid-burst replacement came up cold")
    errs = int(kv.get("tiered_errors") or 0)
    if errs:
        report.append(
            f"WARN: kv_hierarchy tiered leg failed {errs} request(s)")


def gate(old: Dict[str, Any], new: Dict[str, Any], *,
         tolerance: float = DEFAULT_TOLERANCE,
         allow_null_mfu: bool = False) -> Tuple[bool, list]:
    """Returns (ok, report_lines)."""
    report = []
    ok = True
    old_detail = old.get("detail") or {}
    new_detail = new.get("detail") or {}
    old_plat = old_detail.get("platform", "")
    new_plat = new_detail.get("platform", "")
    old_v = float(old.get("value") or 0.0)
    new_v = float(new.get("value") or 0.0)

    if new_detail.get("mfu") is None:
        if allow_null_mfu:
            report.append("WARN: new round has mfu=null (allowed by flag)")
        else:
            ok = False
            report.append(
                "FAIL: new round has mfu=null — the analytic FLOPs engine "
                "must always produce one (check mfu_peak_assumed wiring)")
    else:
        report.append(
            f"ok: mfu={new_detail['mfu']} "
            f"(peak {new_detail.get('mfu_peak_assumed', '?')})")

    if old_plat and new_plat and old_plat != new_plat:
        report.append(
            f"WARN: platform changed {old_plat!r} → {new_plat!r}; "
            f"throughput compare skipped (numbers not comparable)")
    elif old_v <= 0:
        report.append(
            "WARN: old round banked no throughput; compare skipped")
    elif new_v <= 0:
        ok = False
        report.append(
            f"FAIL: new round banked no throughput (old: {old_v:.3f})")
    else:
        delta = new_v / old_v - 1.0
        line = (f"throughput {old_v:.3f} → {new_v:.3f} samples/sec/chip "
                f"({delta:+.1%}, tolerance {tolerance:+.1%})")
        if delta < tolerance:
            ok = False
            report.append(f"FAIL: {line}")
        else:
            report.append(f"ok: {line}")
    _section_notes(old_detail, new_detail, report)
    _control_plane_lines(old_detail, new_detail, report)
    _xla_lines(old_detail, new_detail, report)
    _goodput_lines(old_detail, new_detail, report)
    _serving_lines(old_detail, new_detail, report)
    _serving_fleet_lines(old_detail, new_detail, report)
    _exec_cache_lines(old_detail, new_detail, report)
    _tsdb_lines(old_detail, new_detail, report)
    _recovery_lines(old_detail, new_detail, report)
    _kv_hierarchy_lines(old_detail, new_detail, report)
    ok = _multichip_lines(old_detail, new_detail, report) and ok
    return ok, report


# the gate's report lines are prefix-tagged prose; --json re-emits them
# as one structured object per line without touching the text format
_LINE_LEVELS = (("ok: ", "ok"), ("WARN: ", "warn"), ("FAIL: ", "fail"),
                ("note: ", "note"))
_SECTION_WORDS = set(OPTIONAL_SECTIONS) | {"serving-optimized", "rollout",
                                           "throughput", "tracing", "mfu"}


def report_line_to_json(line: str) -> Dict[str, Any]:
    """One report line → {"level", "section", "message"}. The section is
    recovered from the line's leading word (every section helper starts
    its lines with the section name); headline checks that carry no
    section name are tagged "headline"."""
    level, msg = "info", line
    for prefix, lvl in _LINE_LEVELS:
        if line.startswith(prefix):
            level, msg = lvl, line[len(prefix):]
            break
    word = msg.split(None, 1)[0] if msg.split() else ""
    word = word.split("[")[0].split("=")[0].rstrip(":,")
    if word == "section":
        m = re.search(r"section '([^']+)'", msg)
        section = m.group(1) if m else "headline"
    elif word in _SECTION_WORDS:
        section = word
    else:
        section = "headline"
    return {"level": level, "section": section, "message": msg}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("old", nargs="?", default=None,
                        help="previous round (BENCH_rN.json or raw result)")
    parser.add_argument("new", nargs="?", default=None,
                        help="new round")
    parser.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                        help="max allowed relative throughput change, "
                             "negative = allowed drop (default -0.05)")
    parser.add_argument("--allow-null-mfu", action="store_true",
                        help="demote the null-mfu failure to a warning")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON object per report line "
                             "({level, section, message}) instead of text")
    args = parser.parse_args(argv)

    try:
        if args.old is None or args.new is None:
            old_path, new_path = newest_rounds()
            if args.json:
                print(json.dumps({"level": "info", "section": "gate",
                                  "message": f"auto-selected rounds: "
                                             f"{old_path} → {new_path}"}))
            else:
                print(f"auto-selected rounds: {old_path} → {new_path}")
        else:
            old_path, new_path = args.old, args.new
        old = load_bench(old_path)
        new = load_bench(new_path)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    ok, report = gate(old, new, tolerance=args.tolerance,
                      allow_null_mfu=args.allow_null_mfu)
    if args.json:
        for line in report:
            print(json.dumps(report_line_to_json(line)))
        print(json.dumps({"level": "verdict", "section": "gate",
                          "message": "PASS" if ok else "FAIL", "ok": ok}))
    else:
        for line in report:
            print(line)
        print("bench gate: " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
