"""Repo-local developer tooling (not shipped with the library).

A package so ``python -m tools.dctlint`` resolves from the repo root.
"""
