"""Async device-prefetch + fused multi-step dispatch: the hot-loop overhaul.

Pins the contract that makes the optimizations safe to leave on by default:
the prefetched / fused paths are *semantically invisible* — bit-identical
batch order, the same rng chain, the same final state as the synchronous
k=1 loop — and the prefetcher's producer thread never outlives the loop,
whatever way the loop exits.
"""
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_clone_tpu import core
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.training import JaxTrial, Trainer, TrialContext
from determined_clone_tpu.training.metrics import MetricAccumulator
from determined_clone_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)
from determined_clone_tpu.utils.data import (
    BatchIterator,
    DevicePrefetcher,
    SyncDeviceFeeder,
    batch_iterator,
    make_device_feeder,
    synthetic_mnist,
)


def prefetch_threads_alive():
    return [t for t in threading.enumerate()
            if t.is_alive() and "prefetch" in t.name]


# ---------------------------------------------------------------------------
# DevicePrefetcher unit behaviour
# ---------------------------------------------------------------------------

class TestDevicePrefetcher:
    def test_preserves_order_and_applies_put(self):
        with DevicePrefetcher(iter(range(50)), put=lambda x: x * 2,
                              depth=3) as pf:
            assert list(pf) == [2 * i for i in range(50)]
        assert not pf.thread_alive

    def test_iterator_exception_forwarded(self):
        def gen():
            yield 1
            raise ValueError("source died")

        pf = DevicePrefetcher(gen(), depth=2)
        assert next(pf) == 1
        with pytest.raises(ValueError, match="source died"):
            next(pf)
        pf.close()
        assert not pf.thread_alive

    def test_put_exception_forwarded(self):
        def bad_put(x):
            raise RuntimeError("device_put failed")

        pf = DevicePrefetcher(iter([1]), put=bad_put)
        with pytest.raises(RuntimeError, match="device_put failed"):
            next(pf)
        pf.close()
        assert not pf.thread_alive

    def test_dead_consumer_mid_chunk_does_not_strand_producer(self):
        # infinite source, bounded queue: the producer is parked on a full
        # queue when the consumer stops pulling; close() must still join it
        def forever():
            i = 0
            while True:
                yield i
                i += 1

        pf = DevicePrefetcher(forever(), depth=2)
        assert next(pf) == 0  # producer is live and mid-chunk
        pf.close(timeout=5.0)
        assert not pf.thread_alive

    def test_close_is_idempotent_and_ends_iteration(self):
        pf = DevicePrefetcher(iter(range(10)), depth=2)
        assert next(pf) == 0
        pf.close()
        pf.close()
        with pytest.raises(StopIteration):
            next(pf)

    def test_wait_and_host_time_counters(self):
        pf = DevicePrefetcher(iter(range(5)), depth=2)
        assert list(pf) == list(range(5))
        assert pf.take_queue_wait() >= 0.0
        assert pf.take_queue_wait() == 0.0  # reset on take
        assert pf.take_host_time() >= 0.0
        pf.close()

    def test_sync_feeder_counts_both_ways(self):
        sf = SyncDeviceFeeder(iter(range(3)))
        assert list(sf) == [0, 1, 2]
        # both views report the same underlying counter, independently
        assert sf.take_queue_wait() >= 0.0
        assert sf.take_host_time() >= 0.0

    def test_factory_depth_zero_is_sync(self):
        assert isinstance(make_device_feeder(iter([]), depth=0),
                          SyncDeviceFeeder)
        pf = make_device_feeder(iter([]), depth=2)
        assert isinstance(pf, DevicePrefetcher)
        pf.close()


# ---------------------------------------------------------------------------
# BatchIterator index-skip fast path
# ---------------------------------------------------------------------------

class TestBatchIteratorSkip:
    def test_skip_equals_materialize(self):
        x, y = synthetic_mnist(640, seed=3)
        a = batch_iterator(x, y, 64, seed=5)
        b = batch_iterator(x, y, 64, seed=5)
        for _ in range(4):
            next(a)
        assert b.skip_batches(4) == 4
        for xa_ya, xb_yb in zip(a, b):
            np.testing.assert_array_equal(xa_ya[0], xb_yb[0])
            np.testing.assert_array_equal(xa_ya[1], xb_yb[1])

    def test_skip_past_end_reports_actual(self):
        x, y = synthetic_mnist(320, seed=0)
        it = batch_iterator(x, y, 64)  # 5 batches
        assert it.skip_batches(3) == 3
        assert len(it) == 2
        assert it.skip_batches(10) == 2
        with pytest.raises(StopIteration):
            next(it)

    def test_remainder_kept_when_not_dropped(self):
        x, y = synthetic_mnist(130, seed=0)
        it = batch_iterator(x, y, 64, drop_remainder=False, shuffle=False)
        assert len(it) == 3
        assert it.skip_batches(2) == 2
        xb, _ = next(it)
        assert len(xb) == 2  # the remainder batch survived the skip


# ---------------------------------------------------------------------------
# Fused dispatch: step-level equivalence
# ---------------------------------------------------------------------------

class TestFusedTrainStep:
    def test_k4_matches_sequential_k1(self):
        from determined_clone_tpu.models import mlp

        cfg = mlp.MLPConfig(in_dim=16, hidden_dims=(8,), n_classes=4)
        params = mlp.init(jax.random.PRNGKey(0), cfg)
        tx = optax.adam(1e-2)

        def loss(p, b, rng):
            xb, yb = b
            return mlp.loss_fn(p, cfg, xb, yb), {}

        rng = np.random.RandomState(0)
        batches = [
            (rng.randn(8, 16).astype(np.float32),
             rng.randint(0, 4, 8).astype(np.int32))
            for _ in range(8)
        ]

        s1 = create_train_state(params, tx, jax.random.PRNGKey(1))
        step1 = make_train_step(loss, tx, donate=False)
        acc1 = MetricAccumulator()
        for b in batches:
            s1, m = step1(s1, b)
            acc1.add(m)

        s4 = create_train_state(params, tx, jax.random.PRNGKey(1))
        step4 = make_train_step(loss, tx, donate=False, steps_per_dispatch=4)
        acc4 = MetricAccumulator()
        for i in range(0, len(batches), 4):
            s4, m = step4(s4, *batches[i:i + 4])
            acc4.add(m, count=4)

        # identical params AND identical rng chain: the scan is the same
        # sequence of steps, not an approximation of it
        for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s4.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(s1.rng), np.asarray(s4.rng))
        r1, r4 = acc1.result(), acc4.result()
        assert r1["loss"] == pytest.approx(r4["loss"], rel=1e-5)

    def test_rejects_bad_k(self):
        with pytest.raises(ValueError, match="steps_per_dispatch"):
            make_train_step(lambda p, b, r: jnp.zeros(()), optax.sgd(0.1),
                            steps_per_dispatch=0)


# ---------------------------------------------------------------------------
# Trainer-level seeded equivalence + shutdown
# ---------------------------------------------------------------------------

class OrderSensitiveTrial(JaxTrial):
    """loss = (w - mean(batch))^2 with per-batch distinct means: the final w
    encodes the exact batch sequence, so any reordering or drop by the
    prefetch/fused paths changes the result."""

    N_BATCHES = 24

    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.05)

    def loss(self, params, batch, rng):
        del rng
        loss = (params["w"] - jnp.mean(batch)) ** 2
        return loss, {}

    def training_data(self):
        rng = np.random.RandomState(42)
        for i in range(self.N_BATCHES):
            yield (rng.randn(4, 1) * 0.1 + i).astype(np.float32)

    def validation_data(self):
        return [np.ones((4, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 4


def run_trial(tmp_path, trial_cls, optimizations, max_batches=24,
              sched_unit=8, subdir=""):
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": max_batches}},
        "scheduling_unit": sched_unit,
        "optimizations": optimizations,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path / (subdir or "ck"))},
    })
    with core.init(config=cfg, trial_id=1) as cctx:
        mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
        ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
        t = Trainer(trial_cls(ctx))
        result = t.fit()
        backend = cctx.train._backend
        losses = [r["metrics"]["loss"] for r in backend.records
                  if r["group"] == "training"]
        return float(np.asarray(t._final_state.params["w"])), losses, result


class TestTrainerEquivalence:
    def test_prefetch_and_fusion_match_sync_loop(self, tmp_path):
        w_sync, loss_sync, _ = run_trial(
            tmp_path, OrderSensitiveTrial,
            {"prefetch_depth": 0, "steps_per_dispatch": 1}, subdir="sync")
        w_pf, loss_pf, _ = run_trial(
            tmp_path, OrderSensitiveTrial,
            {"prefetch_depth": 2, "steps_per_dispatch": 1}, subdir="pf")
        w_fused, loss_fused, _ = run_trial(
            tmp_path, OrderSensitiveTrial,
            {"prefetch_depth": 2, "steps_per_dispatch": 4}, subdir="fused")

        # prefetch changes WHERE device_put happens, not what runs: exact
        assert w_pf == w_sync
        assert loss_pf == pytest.approx(loss_sync, rel=1e-6)
        # fusion reorders only the metric summation: same weights, loss
        # equal within re-association tolerance
        assert w_fused == pytest.approx(w_sync, rel=1e-5, abs=1e-6)
        assert loss_fused == pytest.approx(loss_sync, rel=1e-4)
        assert not prefetch_threads_alive()

    def test_fusion_handles_non_divisible_boundaries(self, tmp_path):
        # 22 batches, scheduling_unit 8, k=4: chunks of 8, 8, 6 — the last
        # chunk mixes one fused dispatch with two single-step fallbacks
        w_sync, _, res_s = run_trial(
            tmp_path, OrderSensitiveTrial,
            {"prefetch_depth": 0, "steps_per_dispatch": 1},
            max_batches=22, subdir="sync22")
        w_fused, _, res_f = run_trial(
            tmp_path, OrderSensitiveTrial,
            {"prefetch_depth": 2, "steps_per_dispatch": 4},
            max_batches=22, subdir="fused22")
        assert res_s["batches_trained"] == res_f["batches_trained"] == 22
        assert w_fused == pytest.approx(w_sync, rel=1e-5, abs=1e-6)


class ExplodingTrial(OrderSensitiveTrial):
    def training_data(self):
        for i in range(7):
            yield np.full((4, 1), float(i), np.float32)
        raise RuntimeError("data source died mid-chunk")


class TestPrefetcherShutdown:
    def test_mid_chunk_exception_joins_producer(self, tmp_path):
        cfg = ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 1000}},
            "scheduling_unit": 10,
            "optimizations": {"prefetch_depth": 2, "steps_per_dispatch": 1},
            "checkpoint_storage": {"type": "shared_fs",
                                   "host_path": str(tmp_path)},
        })
        with core.init(config=cfg, trial_id=1) as cctx:
            mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
            ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
            with pytest.raises(RuntimeError, match="data source died"):
                Trainer(ExplodingTrial(ctx)).fit()
        assert not prefetch_threads_alive()

    def test_preemption_joins_producer(self, tmp_path):
        import time as _time

        flag = tmp_path / "flag"
        flag.write_text("")
        cfg = ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 100000}},
            "scheduling_unit": 4,
            "optimizations": {"prefetch_depth": 2, "steps_per_dispatch": 2},
            "checkpoint_storage": {"type": "shared_fs",
                                   "host_path": str(tmp_path)},
        })

        class EndlessTrial(OrderSensitiveTrial):
            def training_data(self):
                i = 0
                while True:
                    yield np.full((4, 1), float(i % 97), np.float32)
                    i += 1

        with core.init(
            config=cfg, trial_id=1,
            preemption_source=core.FilePreemptionSource(str(flag)),
        ) as cctx:
            mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
            ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
            _time.sleep(0.3)  # let the watcher observe the flag
            result = Trainer(EndlessTrial(ctx)).fit()
            assert result["preempted"]
        assert not prefetch_threads_alive()


# ---------------------------------------------------------------------------
# Restore: index-skip replay + validation remainder handling
# ---------------------------------------------------------------------------

class CountingBatchIterator(BatchIterator):
    materialized = 0

    def __next__(self):
        CountingBatchIterator.materialized += 1
        return super().__next__()


class SkippableTrial(JaxTrial):
    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.1)

    def loss(self, params, batch, rng):
        del batch, rng
        loss = (params["w"] - 3.0) ** 2
        return loss, {}

    def training_data(self):
        x, y = synthetic_mnist(2048, seed=0)
        return CountingBatchIterator(x, y, 64, seed=0)

    @property
    def global_batch_size(self):
        return 64


class TestRestoreSkipFastPath:
    def test_replay_skips_by_arithmetic(self, tmp_path):
        cfg_dict = {
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 10}},
            "scheduling_unit": 10,
            # sync feeder: with prefetch on, the producer runs ahead of
            # consumption and the materialization count isn't deterministic
            "optimizations": {"prefetch_depth": 0},
            "checkpoint_storage": {"type": "shared_fs",
                                   "host_path": str(tmp_path)},
        }
        cfg = ExperimentConfig.from_dict(cfg_dict)
        with core.init(config=cfg, trial_id=1) as cctx:
            mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
            ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
            Trainer(SkippableTrial(ctx)).fit()
        ckpt_id = core.LocalCheckpointRegistry(
            str(tmp_path / "checkpoints.jsonl")).list()[-1]["storage_id"]

        cfg_dict["searcher"]["max_length"] = {"batches": 20}
        cfg2 = ExperimentConfig.from_dict(cfg_dict)
        CountingBatchIterator.materialized = 0
        with core.init(config=cfg2, trial_id=1) as cctx:
            mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
            ctx = TrialContext(config=cfg2, hparams={}, core=cctx, mesh=mesh)
            result = Trainer(SkippableTrial(ctx)).fit(
                latest_checkpoint=ckpt_id)
        assert result["batches_trained"] == 20
        # fast path: 1 probe batch (batch_spec discovery) + 10 trained;
        # without skip_batches the replay would also materialize the 9
        # remaining replayed batches (20 total)
        assert CountingBatchIterator.materialized == 11


class RemainderValTrial(JaxTrial):
    """Validation data with a shape-mismatched remainder batch; ``bsum``
    detects whether the remainder reached eval_step (it must not — eval
    stays one compiled program)."""

    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.1)

    def loss(self, params, batch, rng):
        del batch, rng
        return (params["w"] - 3.0) ** 2, {}

    def eval_metrics(self, params, batch):
        return {"loss": (params["w"] - 3.0) ** 2,
                "bsum": jnp.sum(batch)}

    def training_data(self):
        for _ in range(8):
            yield np.ones((4, 1), np.float32)

    def validation_data(self):
        return [np.ones((4, 1), np.float32),
                np.ones((4, 1), np.float32),
                np.ones((2, 1), np.float32)]  # the remainder

    @property
    def global_batch_size(self):
        return 4


class TestValidationRemainder:
    def test_remainder_batch_dropped_not_retraced(self, tmp_path):
        cfg = ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 8}},
            "scheduling_unit": 8,
            "checkpoint_storage": {"type": "shared_fs",
                                   "host_path": str(tmp_path)},
        })
        with core.init(config=cfg, trial_id=1) as cctx:
            mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
            ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
            Trainer(RemainderValTrial(ctx)).fit()
            vals = [r["metrics"] for r in cctx.train._backend.records
                    if r["group"] == "validation"]
        assert vals
        # full batches sum to 4.0 each; had the (2,1) remainder been
        # included the mean would be (4+4+2)/3 ≈ 3.33
        assert vals[-1]["bsum"] == pytest.approx(4.0)
