"""Master request tracing, allgather barrier, and YAML config files.

≈ the reference's otel spans + prometheus middleware (core.go:1014,1189),
the allgather service (master/internal/task/allgather), and viper config
files (root.go:69-117, options.go:47).
"""
import json
import threading

import pytest

from tests.test_platform import build_binaries, start_master

from determined_clone_tpu.api.client import MasterSession

MASTER_BIN = None


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("obs")
    proc, session, port = start_master(tmp)
    yield {"session": session, "port": port, "proc": proc}
    proc.kill()
    proc.wait(timeout=10)


def test_request_tracing(master):
    session = master["session"]
    for _ in range(3):
        session.master_info()
    # ids aggregate into one route key
    for i in (1, 2, 3):
        try:
            session.get(f"/api/v1/experiments/{i}")
        except Exception:
            pass

    spans = session.get("/debug/requests")["requests"]
    assert spans, "spans recorded"
    assert all({"at", "duration_ms", "status", "method", "route"}
               <= set(s) for s in spans)

    stats = {r["route"]: r for r in session.get("/debug/stats")["routes"]}
    assert "GET/api/v1/master" in stats
    info = stats["GET/api/v1/master"]
    assert info["count"] >= 3 and info["p95_ms"] >= 0
    # the three different experiment ids collapse into one :id route
    assert "GET/api/v1/experiments/:id" in stats
    assert stats["GET/api/v1/experiments/:id"]["count"] >= 3
    # 404s are not server errors
    assert stats["GET/api/v1/experiments/:id"]["errors"] == 0


def test_allgather_requires_live_allocation(master):
    """A queued (not yet scheduled) gang cannot populate the barrier — a
    lingering member of a requeued leg must not resurrect stale state."""
    from determined_clone_tpu.api.client import MasterError

    session = master["session"]
    task = session.create_task("command", cmd=["sleep", "1"], slots=1)
    with pytest.raises(MasterError) as err:
        session.post(f"/api/v1/allocations/{task['id']}/allgather",
                     {"rank": 0, "round": 0, "data": {}})
    assert err.value.status == 409
    session.kill_task(task["id"])


def test_allgather_multi_rank(tmp_path):
    """Real multi-rank barrier through the kubernetes RM (world_size > 1)."""
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    proc, session, port = start_master(
        tmp_path, "--rm", "kubernetes", "--kube-slots-per-pod", "8")
    try:
        exp = session.create_experiment({
            "name": "ag", "entrypoint": "m:T",
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 1}},
            "resources": {"slots_per_trial": 16},
        })
        trial = session.get_experiment(exp["id"])["trials"][0]
        alloc_id = f"trial-{trial['id']}.0"
        import time

        deadline = time.time() + 15
        while time.time() < deadline:
            q = [j for j in session.job_queue() if j["id"] == alloc_id]
            if q and q[0]["world_size"] == 2:
                break
            time.sleep(0.2)
        else:
            pytest.fail("allocation never became a 2-member gang")

        results = {}

        def member(rank):
            results[rank] = session.allgather(
                alloc_id, rank, f"host-{rank}", timeout=15)

        threads = [threading.Thread(target=member, args=(r,))
                   for r in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        assert results[0] == results[1] == ["host-0", "host-1"]
        # out-of-range rank rejected
        from determined_clone_tpu.api.client import MasterError

        with pytest.raises(MasterError):
            session.post(f"/api/v1/allocations/{alloc_id}/allgather",
                         {"rank": 7, "round": 0, "data": {}})
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_master_config_file(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    cfg = tmp_path / "master.yaml"
    cfg.write_text(
        "# master config\n"
        "scheduler: fair_share\n"
        "auth_required: true\n"
        "rbac: true\n"
        "kube:\n"
        "  namespace: from-file\n"
        "unmanaged_timeout: 123\n"
    )
    proc, session, port = start_master(tmp_path, "--config", str(cfg))
    try:
        # auth_required from the file is live
        import urllib.request
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/experiments", timeout=5)
        assert err.value.code == 401
        session.login("admin")
        # rbac from the file is live (enforced flag visible via rbac/me)
        assert session.my_permissions()["enforced"] is True
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_master_config_file_rejects_unknown_keys(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    import subprocess

    from tests.test_platform import MASTER_BIN as BIN

    cfg = tmp_path / "bad.yaml"
    cfg.write_text("schedulr: typo\n")
    r = subprocess.run([str(BIN), "--config", str(cfg)],
                       capture_output=True, text=True, timeout=10)
    assert r.returncode == 2
    assert "schedulr" in r.stderr


def test_agent_config_file(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    import subprocess
    from tests.test_platform import MASTER_BIN

    agent_bin = MASTER_BIN.parent / "dct-agent"
    cfg = tmp_path / "agent.yaml"
    cfg.write_text("bogus_key: 1\n")
    r = subprocess.run([str(agent_bin), "--config", str(cfg)],
                       capture_output=True, text=True, timeout=10)
    assert r.returncode == 2
    assert "bogus_key" in r.stderr


def test_per_pool_scheduler_flags(tmp_path):
    """--pool name=scheduler[:nopreempt] overrides per resource pool
    (≈ per-pool configs, rm/agentrm/resource_pool.go)."""
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    import subprocess
    from tests.test_platform import MASTER_BIN

    # bad scheduler name is rejected up front
    r = subprocess.run(
        [str(MASTER_BIN), "--pool", "batch=bogus"],
        capture_output=True, text=True, timeout=10)
    assert r.returncode == 2 and "bogus" in r.stderr

    # valid per-pool flags boot (incl. config-file form)
    cfg = tmp_path / "m.yaml"
    cfg.write_text("pool.batch: fifo\npool.research: priority:nopreempt\n")
    proc, session, port = start_master(
        tmp_path, "--config", str(cfg), "--pool", "interactive=round_robin")
    try:
        assert session.master_info()["cluster_name"] == "dct"
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_pool_suffix_typo_rejected(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    import subprocess
    from tests.test_platform import MASTER_BIN

    r = subprocess.run(
        [str(MASTER_BIN), "--pool", "batch=fifo:nopremept"],
        capture_output=True, text=True, timeout=10)
    assert r.returncode == 2 and "nopremept" in r.stderr


def test_master_config_endpoint(tmp_path):
    """GET /api/v1/master/config exposes the active config, secrets
    omitted, admin-gated under auth (≈ GetMasterConfig)."""
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    proc, session, port = start_master(
        tmp_path, "--auth-required", "--rbac",
        "--pool", "batch=fifo:nopreempt",
        "--sso-issuer", "idp.internal:443",
        "--sso-client-secret", "sup3rsecret")
    try:
        import urllib.error
        import urllib.request

        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master/config", timeout=5)
        assert err.value.code == 401  # no session: re-login, not denied
        # an authenticated non-admin is the 403 case
        from determined_clone_tpu.api.client import MasterError, MasterSession
        session.login("admin")
        session.create_user("cfg-nobody", "pw")
        s2 = MasterSession("127.0.0.1", port, timeout=5, retries=1)
        s2.login("cfg-nobody", "pw")
        with pytest.raises(MasterError) as err2:
            s2.get("/api/v1/master/config")
        assert err2.value.status == 403
        cfg = session.get("/api/v1/master/config")
        assert cfg["auth_required"] is True and cfg["rbac"] is True
        assert cfg["pools"]["batch"] == {"scheduler": "fifo",
                                         "preemption": False}
        assert cfg["sso_issuer"] == "idp.internal:443"
        assert "sup3rsecret" not in json.dumps(cfg)  # secrets never leave
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_telemetry_samples_round_trip_through_master(tmp_path):
    """Trial-shipped telemetry (registry snapshots + spans) lands under the
    trial's profiler endpoint and converts back into a valid Chrome trace
    — the `dct trace export` path, end to end against the real master."""
    from determined_clone_tpu.profiler import ProfilerAgent
    from determined_clone_tpu.telemetry import (
        Telemetry,
        spans_from_profiler_samples,
        to_chrome_trace,
        validate_chrome_trace,
    )

    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    # kubernetes RM materializes trials without a real agent, and the
    # profiler endpoint rejects unknown trial ids
    proc, session, port = start_master(
        tmp_path, "--rm", "kubernetes", "--kube-slots-per-pod", "8")
    try:
        exp = session.create_experiment({
            "name": "obs-roundtrip", "entrypoint": "m:T",
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 1}},
            "resources": {"slots_per_trial": 1},
            "observability": {"enabled": True, "ship_spans": True},
        })
        trial_id = session.get_experiment(exp["id"])["trials"][0]["id"]

        tel = Telemetry(enabled=True, ship_spans=True)
        prof = ProfilerAgent(session, trial_id, enabled=True,
                             sample_system=False, registry=tel.registry)
        prof.start()
        tel.registry.counter("steps_total", "steps").inc(5)
        tel.registry.histogram("train_dispatch_seconds", "x").observe(0.01)
        with tel.tracer.span("train_dispatch", chunk=0):
            pass
        tel.publish(prof, batches_trained=5)
        prof.stop()  # final flush
        assert prof.samples_dropped == 0

        samples = session.trial_profiler_samples(trial_id)
        by_group = {}
        for s in samples:
            by_group.setdefault(s.get("group"), []).append(s)

        (snap,) = by_group["telemetry"]
        assert snap["batches_trained"] == 5
        assert snap["metrics"]["steps_total"]["value"] == 5
        assert snap["metrics"]["train_dispatch_seconds"]["count"] == 1

        recs = spans_from_profiler_samples(samples)
        assert [r["name"] for r in recs] == ["train_dispatch"]
        assert validate_chrome_trace(to_chrome_trace(recs)) == []
    finally:
        proc.kill()
        proc.wait(timeout=10)
