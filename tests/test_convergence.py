"""Real-data convergence gates — the nightly accuracy bar.

≈ the reference's e2e_tests/tests/nightly/test_convergence.py:25 (mnist
best validation accuracy > 0.97). The build environment has no egress, so
the real data is sklearn's bundled handwritten-digits scans
(utils/data.py digits_dataset — genuine held-out split, same task family);
the gate value carries over unchanged.

Also pins the flagship GPT config's loss band: bench.py asserts measured
loss against tests/data/loss_bands.json, so the bench catches regression,
not just catastrophe (VERDICT r4 weak #5).
"""
import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

sys.path.insert(0, str(REPO / "examples" / "mnist"))
from model_def import MnistTrial  # noqa: E402

from determined_clone_tpu import core  # noqa: E402
from determined_clone_tpu.config.experiment import ExperimentConfig  # noqa: E402
from determined_clone_tpu.training import Trainer, TrialContext  # noqa: E402


def test_digits_cnn_beats_097(tmp_path):
    """The committed mnist example config's model, through the real
    Trainer, on real scans, to the reference's 0.97 bar."""
    cfg = ExperimentConfig.from_dict({
        "name": "convergence-digits",
        "entrypoint": "model_def:MnistTrial",
        "searcher": {"name": "single", "metric": "accuracy",
                     "smaller_is_better": False,
                     "max_length": {"batches": 220}},
        "scheduling_unit": 55,
        "min_validation_period": {"batches": 55},
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path)},
    })
    hparams = {"global_batch_size": 64, "lr": 1e-3,
               "n_filters_1": 16, "n_filters_2": 32, "dataset": "digits"}
    with core.init(config=cfg, trial_id=1) as cctx:
        ctx = TrialContext(config=cfg, hparams=hparams, core=cctx)
        backend = cctx.train._backend
        result = Trainer(MnistTrial(ctx)).fit()
        assert result["batches_trained"] == 220
        val = [r for r in backend.records if r["group"] == "validation"]
        assert val, "no validation reports"
        best = max(r["metrics"]["accuracy"] for r in val)
        print(f"\n[convergence] digits best val accuracy: {best:.4f}")
        assert best > 0.97, f"accuracy {best:.4f} below the 0.97 gate"


def test_loss_bands_file_well_formed():
    bands = json.loads(
        (REPO / "tests" / "data" / "loss_bands.json").read_text())
    assert "gpt-tiny-cpu" in bands
    for name, band in bands.items():
        assert 0 < band["min"] < band["max"], (name, band)
        assert band["max"] < 12, (name, band)  # sanity: ln(vocab) scale


def test_bench_asserts_against_band():
    """bench.py's loss gate must use the recorded band when one exists for
    the config (regression detection), falling back to the uniform-entropy
    catastrophe bound otherwise."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    band = json.loads(
        (REPO / "tests" / "data" / "loss_bands.json").read_text())[
            "gpt-tiny-cpu"]
    mid = (band["min"] + band["max"]) / 2
    assert bench.loss_ok_for("gpt-tiny-cpu", mid, vocab=512)
    # outside the band is a REGRESSION even though it beats ln(512)*1.05
    above = band["max"] + 0.05
    assert above < 1.05 * 6.24
    assert not bench.loss_ok_for("gpt-tiny-cpu", above, vocab=512)
    assert not bench.loss_ok_for("gpt-tiny-cpu", band["min"] - 0.3,
                                 vocab=512)
    # configs without a recorded band keep the catastrophe bound
    assert bench.loss_ok_for("gpt-unbanded", 6.0, vocab=512)
    assert not bench.loss_ok_for("gpt-unbanded", 7.0, vocab=512)
    assert not bench.loss_ok_for("gpt-unbanded", float("nan"), vocab=512)
