"""Content-addressed checkpoint store (docs/checkpoint_storage.md):
the shared transfer pool, the local chunk cache, chunk-level dedup,
ref-counted chunk GC, the config/schema/shim plumbing, and the
`dct checkpoint stats` surface."""
import contextlib
import json
import os
import threading
import time

import pytest

from determined_clone_tpu import core
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.config.experiment import (
    CheckpointStorageConfig,
    ConfigError,
)
from determined_clone_tpu.config.schema import STORAGE_SCHEMA, validate
from determined_clone_tpu.config.shims import shim
from determined_clone_tpu.core import CheckpointCorruptError
from determined_clone_tpu.core._checkpoint import verify_manifest_digests
from determined_clone_tpu.storage import (
    CASStorageManager,
    ChunkCache,
    SharedFSStorageManager,
    TransferPool,
    build,
)
from determined_clone_tpu.storage import cas as cas_mod

CHUNK = 1024  # small chunks so a few KiB of payload spans many


# ---------------------------------------------------------------------------
# transfer pool
# ---------------------------------------------------------------------------

def test_pool_returns_results_in_task_order():
    pool = TransferPool(workers=4)
    try:
        # reversed sleeps: without index tracking, completion order would
        # invert submission order
        tasks = [(lambda i=i: (time.sleep(0.02 * (4 - i)), i)[1])
                 for i in range(5)]
        assert pool.run(tasks) == [0, 1, 2, 3, 4]
    finally:
        pool.shutdown()


def test_pool_settles_every_task_then_raises_first_error():
    pool = TransferPool(workers=2)
    ran = []

    def ok(i):
        ran.append(i)

    def boom():
        raise OSError("copy died")

    try:
        with pytest.raises(OSError, match="copy died"):
            pool.run([lambda: ok(0), boom, lambda: ok(2), lambda: ok(3)])
        # per-file progress is kept even when one transfer dies
        assert sorted(ran) == [0, 2, 3]
    finally:
        pool.shutdown()


def test_pool_workers0_runs_inline_and_in_order():
    pool = TransferPool(workers=0)
    seen = []
    pool.run([(lambda i=i: seen.append(
        (i, threading.current_thread().name))) for i in range(4)])
    assert [i for i, _ in seen] == [0, 1, 2, 3]
    me = threading.current_thread().name
    assert all(name == me for _, name in seen)


def test_pool_single_task_never_queues():
    pool = TransferPool(workers=4)
    try:
        holder = []
        pool.run([lambda: holder.append(threading.current_thread().name)])
        # inline on the caller: no worker round-trip for a lone transfer
        assert holder == [threading.current_thread().name]
    finally:
        pool.shutdown()


def test_pool_nested_run_cannot_deadlock():
    # one worker: the outer batch occupies it, so the inner run() must be
    # served by caller participation or the pool would deadlock
    pool = TransferPool(workers=1)
    try:
        def outer(i):
            return sum(pool.run([(lambda j=j: i * 10 + j)
                                 for j in range(2)]))
        assert pool.run([lambda i=i: outer(i) for i in range(3)]) == \
            [1, 21, 41]
    finally:
        pool.shutdown()


def test_pool_workers_drain_whole_batch():
    # wake tokens are capped at the pool size, so workers must LOOP over
    # the batch. Every task parks on a 3-party barrier (caller + both
    # workers): 9 tasks need 3 full rounds with all three executors each
    # round — a worker that quit after one task per wake would leave the
    # barrier short from round 2 on and break it (timeout) instead
    pool = TransferPool(workers=2)
    barrier = threading.Barrier(3, timeout=10)
    names = []
    lock = threading.Lock()

    def gate():
        barrier.wait()
        with lock:
            names.append(threading.current_thread().name)

    try:
        pool.run([gate] * 9)
    finally:
        pool.shutdown()
    assert len(names) == 9
    # both workers took part in every round, not just the first
    worker_runs = [n for n in names if n.startswith("dct-xfer")]
    assert len(worker_runs) == 6


def test_pool_rejects_negative_workers_and_shutdown_is_final():
    with pytest.raises(ValueError):
        TransferPool(workers=-1)
    pool = TransferPool(workers=2)
    pool.run([lambda: 1, lambda: 2])
    pool.shutdown()
    with pytest.raises(RuntimeError):
        pool.run([lambda: 1, lambda: 2])


# ---------------------------------------------------------------------------
# chunk cache
# ---------------------------------------------------------------------------

def _digest(data: bytes) -> str:
    return cas_mod._sha256_bytes(data)


def test_cache_roundtrip_and_persisted_stats(tmp_path):
    cache = ChunkCache(str(tmp_path / "cache"), max_bytes=1 << 20)
    data = b"q" * 300
    d = _digest(data)
    assert cache.get(d) is None          # miss
    cache.put(d, data)
    hit = cache.get(d)
    assert hit is not None and open(hit, "rb").read() == data
    s = cache.stats()
    assert (s["hits"], s["misses"], s["entries"]) == (1, 1, 1)
    assert s["bytes"] == 300 and s["hit_rate"] == 0.5
    # counters survive a process restart (stats.json)
    again = ChunkCache(str(tmp_path / "cache"), max_bytes=1 << 20)
    assert again.stats()["hits"] == 1 and again.stats()["misses"] == 1


def test_cache_discards_corrupt_entry_as_miss(tmp_path):
    cache = ChunkCache(str(tmp_path / "cache"))
    data = b"r" * 128
    d = _digest(data)
    p = cache.put(d, data)
    with open(p, "wb") as f:
        f.write(b"x" * 128)  # bit rot under the same key
    assert cache.get(d) is None          # verified, evicted, counted a miss
    assert not os.path.exists(p)
    assert cache.stats()["misses"] == 1


def test_cache_evicts_lru_but_never_the_fresh_entry(tmp_path):
    cache = ChunkCache(str(tmp_path / "cache"), max_bytes=250)
    blobs = [bytes([i]) * 100 for i in range(3)]
    for i, blob in enumerate(blobs):
        cache.put(_digest(blob), blob)
        os.utime(cache._entry(_digest(blob)),
                 (1_000_000 + i, 1_000_000 + i))  # deterministic recency
    # 300 bytes > 250 cap: the oldest entry went, the other two stayed
    assert cache.get(_digest(blobs[0])) is None
    assert cache.get(_digest(blobs[1])) is not None
    assert cache.get(_digest(blobs[2])) is not None
    # a cache smaller than one chunk still holds the entry just written
    tiny = ChunkCache(str(tmp_path / "tiny"), max_bytes=10)
    big = b"z" * 100
    tiny.put(_digest(big), big)
    assert tiny.get(_digest(big)) is not None


def test_cache_stats_flush_is_amortized(tmp_path):
    cache = ChunkCache(str(tmp_path / "cache"))
    data = b"s" * 64
    d = _digest(data)
    cache.put(d, data)
    for _ in range(10):
        assert cache.get(d) is not None
    # the hot path does not pay a stats.json write per lookup
    assert not os.path.exists(cache._stats_path)
    assert cache.stats()["hits"] == 10   # stats() makes counters durable
    with open(cache._stats_path) as f:
        assert json.load(f)["hits"] == 10


def test_cache_evict_tolerates_vanished_entry(tmp_path, monkeypatch):
    # two processes share a cache_path: an entry listed by _evict may be
    # gone by the time it is stat'ed — that must not fail the put
    cache = ChunkCache(str(tmp_path / "cache"), max_bytes=250)
    blobs = [bytes([i]) * 100 for i in range(3)]
    d0 = _digest(blobs[0])
    cache.put(d0, blobs[0])
    cache.put(_digest(blobs[1]), blobs[1])
    real = os.path.getmtime

    def foreign_evict(p):
        if os.path.basename(p) == d0:
            with contextlib.suppress(FileNotFoundError):
                os.remove(p)
        return real(p)

    monkeypatch.setattr(os.path, "getmtime", foreign_evict)
    cache.put(_digest(blobs[2]), blobs[2])   # triggers _evict
    assert cache.get(_digest(blobs[2])) is not None


def test_cache_stats_tolerates_vanished_entry(tmp_path, monkeypatch):
    cache = ChunkCache(str(tmp_path / "cache"))
    blobs = [bytes([i]) * 100 for i in range(2)]
    d0 = _digest(blobs[0])
    for blob in blobs:
        cache.put(_digest(blob), blob)
    real = os.path.getsize

    def foreign_evict(p):
        if os.path.basename(p) == d0:
            with contextlib.suppress(FileNotFoundError):
                os.remove(p)
        return real(p)

    monkeypatch.setattr(os.path, "getsize", foreign_evict)
    s = cache.stats()
    assert s["entries"] == 1 and s["bytes"] == 100


# ---------------------------------------------------------------------------
# content-addressed store
# ---------------------------------------------------------------------------

class CountingFS(SharedFSStorageManager):
    """SharedFS that counts chunk-object downloads (cache-bypass probe)."""

    def __init__(self, base):
        super().__init__(base)
        self.chunk_fetches = 0

    def download(self, storage_id, dst_dir, paths=None):
        if storage_id == cas_mod.CHUNK_NAMESPACE:
            self.chunk_fetches += len(paths or [])
        return super().download(storage_id, dst_dir, paths=paths)


def make_cas(tmp_path, *, cache=False, counting=False):
    inner_cls = CountingFS if counting else SharedFSStorageManager
    inner = inner_cls(str(tmp_path / "store"))
    ck_cache = (ChunkCache(str(tmp_path / "cache"), max_bytes=1 << 20)
                if cache else None)
    mgr = CASStorageManager(inner, chunk_size=CHUNK, cache=ck_cache,
                            pool=TransferPool(workers=0))
    return mgr, inner


def write_payload(src, blob, extra=None):
    os.makedirs(os.path.join(src, "state"), exist_ok=True)
    with open(os.path.join(src, "state", "weights.bin"), "wb") as f:
        f.write(blob)
    if extra is not None:
        with open(os.path.join(src, "opt.bin"), "wb") as f:
            f.write(extra)


def test_cas_dedup_across_saves(tmp_path):
    mgr, _ = make_cas(tmp_path)
    blob = bytearray(8 * CHUNK)
    for i in range(8):
        blob[i * CHUNK:(i + 1) * CHUNK] = bytes([i + 1]) * CHUNK
    src = tmp_path / "src"
    write_payload(str(src), bytes(blob))
    mgr.upload(str(src), "ck-1")
    first = mgr.session_stats["bytes_uploaded"]
    assert first == 8 * CHUNK

    # one chunk changes between saves: saves 2 and 3 upload only it
    for n, sid in ((2, "ck-2"), (3, "ck-3")):
        blob[0:CHUNK] = bytes([0x40 + n]) * CHUNK
        write_payload(str(src), bytes(blob))
        before = mgr.session_stats["bytes_uploaded"]
        mgr.upload(str(src), sid)
        assert mgr.session_stats["bytes_uploaded"] - before == CHUNK
        mgr.commit(sid)
    mgr.commit("ck-1")

    stats = mgr.storage_stats()
    assert stats["cas_checkpoints"] == 3
    assert stats["chunk_bytes"] == 10 * CHUNK     # 8 + 1 + 1 unique chunks
    assert stats["logical_bytes"] == 24 * CHUNK   # 3 x 8 logical
    assert stats["dedup_ratio"] == 2.4
    assert mgr.session_stats["chunks_deduped"] == 14


def test_cas_restore_bit_identical_with_nested_paths(tmp_path):
    mgr, _ = make_cas(tmp_path)
    blob = os.urandom(3 * CHUNK + 17)   # non-aligned tail chunk
    extra = os.urandom(CHUNK // 2)
    src = tmp_path / "src"
    write_payload(str(src), blob, extra)
    mgr.upload(str(src), "ck-1")

    # the logical listing hides chunk manifests and reports true sizes
    assert mgr.list_files("ck-1") == {
        "state/weights.bin": len(blob), "opt.bin": len(extra)}

    dst = tmp_path / "dst"
    mgr.download("ck-1", str(dst))
    assert open(dst / "state" / "weights.bin", "rb").read() == blob
    assert open(dst / "opt.bin", "rb").read() == extra


def test_cas_empty_file_roundtrip(tmp_path):
    mgr, _ = make_cas(tmp_path)
    src = tmp_path / "src"
    src.mkdir()
    (src / "empty.bin").write_bytes(b"")
    mgr.upload(str(src), "ck-1")
    assert mgr.list_files("ck-1") == {"empty.bin": 0}
    dst = tmp_path / "dst"
    mgr.download("ck-1", str(dst))
    assert (dst / "empty.bin").read_bytes() == b""


def test_cas_warm_restore_never_touches_backend(tmp_path):
    mgr, inner = make_cas(tmp_path, cache=True, counting=True)
    blob = os.urandom(4 * CHUNK)
    src = tmp_path / "src"
    write_payload(str(src), blob)
    mgr.upload(str(src), "ck-1")

    # chunks were cached on the way up: even the first restore is warm
    dst = tmp_path / "dst"
    mgr.download("ck-1", str(dst))
    assert inner.chunk_fetches == 0
    assert open(dst / "state" / "weights.bin", "rb").read() == blob
    assert mgr.session_stats["cache_hits"] == 4

    # cold cache (fresh process, no --cache-path): every chunk is fetched
    cold = CASStorageManager(inner, chunk_size=CHUNK,
                             pool=TransferPool(workers=0))
    dst2 = tmp_path / "dst2"
    cold.download("ck-1", str(dst2))
    assert inner.chunk_fetches == 4
    assert open(dst2 / "state" / "weights.bin", "rb").read() == blob


def test_cas_gc_keeps_referenced_chunks(tmp_path):
    mgr, inner = make_cas(tmp_path)
    shared = os.urandom(2 * CHUNK)
    src = tmp_path / "src"
    write_payload(str(src), shared)
    mgr.upload(str(src), "ck-1")
    write_payload(str(src), shared, extra=os.urandom(CHUNK))
    mgr.upload(str(src), "ck-2")
    assert len(inner.list_files("cas")) == 3

    # ck-2's unique chunk is reclaimed; the chunks ck-1 still references
    # survive and ck-1 stays bit-identical
    mgr.delete("ck-2")
    assert len(inner.list_files("cas")) == 2
    dst = tmp_path / "dst"
    mgr.download("ck-1", str(dst))
    assert open(dst / "state" / "weights.bin", "rb").read() == shared

    mgr.delete("ck-1")  # last reference: the namespace empties out
    assert inner.list_files("cas") == {}


def test_cas_gc_protects_chunks_of_uncommitted_saves(tmp_path):
    # an in-flight (uncommitted) save's chunks must survive a concurrent
    # delete of an older checkpoint that shares them
    mgr, inner = make_cas(tmp_path)
    blob = os.urandom(2 * CHUNK)
    src = tmp_path / "src"
    write_payload(str(src), blob)
    mgr.upload(str(src), "ck-old")
    mgr.commit("ck-old")
    mgr.upload(str(src), "ck-inflight")  # same content, never committed
    mgr.delete("ck-old")
    dst = tmp_path / "dst"
    mgr.download("ck-inflight", str(dst))
    assert open(dst / "state" / "weights.bin", "rb").read() == blob


def _chunk_paths_of(mgr, storage_id):
    return [cas_mod.chunk_rel(d)
            for d in sorted(mgr._referenced_digests(storage_id))]


def test_cas_chunk_manifest_written_before_chunk_data(tmp_path, monkeypatch):
    # the manifest-first invariant concurrent GC safety rests on: if the
    # save dies mid-chunk-upload, the chunk manifest is already durable
    # (and the checkpoint, lacking COMMIT, is refused on restore)
    mgr, inner = make_cas(tmp_path)
    monkeypatch.setattr(
        mgr, "_upload_chunks",
        lambda to_send: (_ for _ in ()).throw(OSError("PUT died")))
    src = tmp_path / "src"
    write_payload(str(src), os.urandom(2 * CHUNK))
    with pytest.raises(OSError, match="PUT died"):
        mgr.upload(str(src), "ck-1")
    manifests = [r for r in inner.list_files("ck-1")
                 if cas_mod._is_chunk_manifest(r)]
    assert manifests, "chunk manifest must land before any chunk data"
    assert mgr._referenced_digests("ck-1")  # its references are visible


def test_cas_upload_repairs_dedup_against_concurrent_gc(tmp_path,
                                                        monkeypatch):
    # a foreign GC reclaims the chunks an in-flight save deduped against,
    # in the window between the dedup decision and the manifest landing:
    # the save must notice (fresh listing) and re-upload them
    mgr, inner = make_cas(tmp_path)
    blob = os.urandom(2 * CHUNK)
    src = tmp_path / "src"
    write_payload(str(src), blob)
    mgr.upload(str(src), "ck-old")
    mgr.commit("ck-old")
    victims = _chunk_paths_of(mgr, "ck-old")

    orig = mgr._write_chunk_manifest

    def hostile(storage_id, entries):
        orig(storage_id, entries)
        if storage_id == "ck-new":
            # simulate the other process's GC completing right here
            inner.delete("ck-old")
            inner.delete_files(cas_mod.CHUNK_NAMESPACE, victims)

    monkeypatch.setattr(mgr, "_write_chunk_manifest", hostile)
    before = mgr.session_stats["bytes_uploaded"]
    mgr.upload(str(src), "ck-new")   # full dedup, then the repair path
    assert mgr.session_stats["bytes_uploaded"] - before == 2 * CHUNK
    mgr.commit("ck-new")
    dst = tmp_path / "dst"
    mgr.download("ck-new", str(dst))
    assert open(dst / "state" / "weights.bin", "rb").read() == blob


def test_cas_known_chunks_rebuilt_after_foreign_gc(tmp_path):
    # a fresh save must not trust a dedup set that outlived the backend:
    # after a foreign GC empties the chunk namespace, the next save
    # re-uploads instead of deduping against bytes that are gone
    mgr, inner = make_cas(tmp_path)
    blob = os.urandom(2 * CHUNK)
    src = tmp_path / "src"
    write_payload(str(src), blob)
    mgr.upload(str(src), "ck-1")
    inner.delete("ck-1")
    inner.delete_files(cas_mod.CHUNK_NAMESPACE, _chunk_paths_of(mgr, "ck-1"))
    mgr._forget("ck-1")

    mgr.upload(str(src), "ck-2")
    dst = tmp_path / "dst"
    mgr.download("ck-2", str(dst))
    assert open(dst / "state" / "weights.bin", "rb").read() == blob


def test_cas_gc_second_walk_honors_late_manifest(tmp_path, monkeypatch):
    # a save on another manager writes its chunk manifest while this
    # manager's GC is mid-walk: the second ref-count walk must see it and
    # keep the shared chunks
    mgr, inner = make_cas(tmp_path)
    blob = os.urandom(2 * CHUNK)
    src = tmp_path / "src"
    write_payload(str(src), blob)
    mgr.upload(str(src), "ck-old")
    mgr.commit("ck-old")

    other = CASStorageManager(inner, chunk_size=CHUNK,
                              pool=TransferPool(workers=0))
    orig = mgr.list_storage_ids
    state = {"walks": 0}

    def walk():
        out = orig()
        state["walks"] += 1
        if state["walks"] == 1:
            # first walk's listing predates ck-new; its manifest (and full
            # dedup against ck-old's chunks) lands right after
            other.upload(str(src), "ck-new")
        return out

    monkeypatch.setattr(mgr, "list_storage_ids", walk)
    mgr.delete("ck-old")
    assert state["walks"] >= 2
    dst = tmp_path / "dst"
    mgr.download("ck-new", str(dst))
    assert open(dst / "state" / "weights.bin", "rb").read() == blob


def test_cas_constructor_rejects_nesting_and_bad_chunk_size(tmp_path):
    inner = SharedFSStorageManager(str(tmp_path))
    wrapped = CASStorageManager(inner, chunk_size=CHUNK)
    with pytest.raises(ValueError, match="nest"):
        CASStorageManager(wrapped, chunk_size=CHUNK)
    with pytest.raises(ValueError, match="chunk_size"):
        CASStorageManager(inner, chunk_size=0)


def test_cas_list_storage_ids_hides_chunk_namespace(tmp_path):
    mgr, _ = make_cas(tmp_path)
    src = tmp_path / "src"
    write_payload(str(src), os.urandom(CHUNK))
    mgr.upload(str(src), "ck-1")
    assert mgr.list_storage_ids() == ["ck-1"]


# ---------------------------------------------------------------------------
# config plumbing: from_dict/to_dict, schema, shim, build()
# ---------------------------------------------------------------------------

CAS_RAW = {
    "type": "cas",
    "chunk_size_kb": 64,
    "cache_path": "/var/cache/dct",
    "cache_size_mb": 16,
    "transfer_workers": 2,
    "inner": {"type": "shared_fs", "host_path": "/data/ckpts"},
}


def test_cas_config_round_trips_and_validates():
    cfg = CheckpointStorageConfig.from_dict(CAS_RAW)
    assert cfg.inner.type == "shared_fs"
    d = cfg.to_dict()
    assert d["inner"]["host_path"] == "/data/ckpts"
    assert CheckpointStorageConfig.from_dict(d).to_dict() == d
    assert validate(d, STORAGE_SCHEMA) == []
    # the schema union rejects a cas inner (no such variant nested)
    bad = dict(CAS_RAW, inner={"type": "cas",
                               "inner": CAS_RAW["inner"]})
    assert validate(bad, STORAGE_SCHEMA) != []


def test_non_cas_config_to_dict_has_no_cas_keys():
    d = CheckpointStorageConfig.from_dict(
        {"type": "shared_fs", "host_path": "/x"}).to_dict()
    assert not {"inner", "chunk_size_kb", "cache_path", "cache_size_mb",
                "transfer_workers"} & set(d)


def test_cas_config_rejections():
    with pytest.raises(ConfigError, match="inner"):
        CheckpointStorageConfig.from_dict({"type": "cas"})
    with pytest.raises(ConfigError, match="cannot itself"):
        CheckpointStorageConfig.from_dict(
            {"type": "cas", "inner": dict(CAS_RAW)})
    with pytest.raises(ConfigError, match="chunk_size_kb"):
        CheckpointStorageConfig.from_dict(
            dict(CAS_RAW, chunk_size_kb=0))
    with pytest.raises(ConfigError, match="transfer_workers"):
        CheckpointStorageConfig.from_dict(
            dict(CAS_RAW, transfer_workers=-1))


def test_flat_cas_form_synthesizes_inner_and_shims():
    # from_dict accepts the flat v0 convenience form directly
    cfg = CheckpointStorageConfig.from_dict(
        {"type": "cas", "host_path": "/data"})
    assert cfg.inner.type == "shared_fs"
    assert cfg.inner.host_path == "/data"
    # and the shim rewrites it to the explicit nested form, with a note
    raw, notes = shim({
        "checkpoint_storage": {"type": "cas", "host_path": "/data"}})
    storage = raw["checkpoint_storage"]
    assert storage["inner"] == {"type": "shared_fs", "host_path": "/data"}
    assert "host_path" not in storage
    assert any("flat cas" in n for n in notes)


def test_build_wires_cas_chain(tmp_path):
    cfg = CheckpointStorageConfig.from_dict({
        "type": "cas", "chunk_size_kb": 1, "transfer_workers": 0,
        "cache_path": str(tmp_path / "cache"), "cache_size_mb": 1,
        "inner": {"type": "shared_fs", "host_path": str(tmp_path / "s")}})
    mgr = build(cfg)
    assert isinstance(mgr, CASStorageManager)
    assert isinstance(mgr._inner, SharedFSStorageManager)
    assert mgr._chunk_size == 1024
    assert mgr._cache is not None and mgr._cache.max_bytes == 1 << 20

    # default off: a plain shared_fs config builds the plain backend
    plain = build(CheckpointStorageConfig.from_dict(
        {"type": "shared_fs", "host_path": str(tmp_path / "s")}))
    assert not isinstance(plain, CASStorageManager)


def test_experiment_config_accepts_cas_block(tmp_path):
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "checkpoint_storage": {
            "type": "cas", "chunk_size_kb": 1,
            "inner": {"type": "shared_fs", "host_path": str(tmp_path)}},
    })
    assert cfg.checkpoint_storage.type == "cas"
    assert cfg.checkpoint_storage.inner.host_path == str(tmp_path)


# ---------------------------------------------------------------------------
# dct checkpoint stats
# ---------------------------------------------------------------------------

def test_cli_checkpoint_stats_reports_dedup_and_cache(tmp_path, capsys):
    from determined_clone_tpu.cli.cli import main

    mgr, _ = make_cas(tmp_path)
    src = tmp_path / "src"
    blob = os.urandom(2 * CHUNK)
    write_payload(str(src), blob)
    mgr.upload(str(src), "ck-1")
    mgr.commit("ck-1")
    mgr.upload(str(src), "ck-2")    # full dedup against ck-1
    mgr.commit("ck-2")

    rc = main(["checkpoint", "stats",
               "--host-path", str(tmp_path / "store"),
               "--cache-path", str(tmp_path / "cli-cache")])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cas_checkpoints"] == 2
    assert doc["dedup_ratio"] == 2.0
    assert doc["cache"]["path"] == str(tmp_path / "cli-cache")


def test_cli_checkpoint_stats_refuses_non_cas_config(tmp_path, capsys):
    from determined_clone_tpu.cli.cli import main

    cfg = tmp_path / "exp.yaml"
    cfg.write_text("checkpoint_storage:\n  type: shared_fs\n"
                   f"  host_path: {tmp_path}\n")
    assert main(["checkpoint", "stats", "--config", str(cfg)]) == 2
    assert "not" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# non-CAS path: downloads digest-verify against manifest.json too
# ---------------------------------------------------------------------------

def make_core(tmp_path):
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path)},
    })
    return core.init(config=cfg, trial_id=1)


def test_download_digest_verifies_against_manifest(tmp_path):
    with make_core(tmp_path / "store") as cctx:
        ck = cctx.checkpoint
        with ck.store_path() as (path, holder):
            with open(os.path.join(path, "weights.bin"), "wb") as f:
                f.write(b"\x0a" * 64)
        sid = holder["storage_id"]
        # same-size content swap: the size check passes, only the sha256
        # in manifest.json can convict it
        with open(tmp_path / "store" / sid / "weights.bin", "wb") as f:
            f.write(b"\x0b" * 64)
        dst = tmp_path / "dl"
        with pytest.raises(CheckpointCorruptError) as ei:
            ck.download(sid, str(dst))
        assert "digest mismatch" in ei.value.reason
        # opt-out for forensic inspection of a known-bad checkpoint
        ck.download(sid, str(tmp_path / "dl2"), verify=False)


def test_verify_manifest_digests_semantics(tmp_path):
    d = tmp_path / "ck"
    d.mkdir()
    (d / "a.bin").write_bytes(b"abc")
    # legacy dir without a manifest: nothing to verify, not refused
    assert verify_manifest_digests(str(d)) is False
    manifest = {"files": {
        "a.bin": {"size": 3, "sha256": cas_mod._sha256_bytes(b"abc")},
        "b.bin": {"size": 9, "sha256": "0" * 64},
    }}
    (d / "manifest.json").write_text(json.dumps(manifest))
    # b.bin absent = partial download (paths subset), not corruption...
    assert verify_manifest_digests(str(d)) is True
    # ...but a FULL download missing a whole manifest-listed file is: a
    # backend that lost an object must not pass verification silently
    with pytest.raises(CheckpointCorruptError, match="missing"):
        verify_manifest_digests(str(d), require_all=True)
    (d / "a.bin").write_bytes(b"abX")
    with pytest.raises(CheckpointCorruptError):
        verify_manifest_digests(str(d))


def test_download_refuses_wholly_missing_file(tmp_path):
    # CheckpointContext.download is a full fetch: a data file the backend
    # dropped entirely (not just tore) must be convicted too
    with make_core(tmp_path / "store") as cctx:
        ck = cctx.checkpoint
        with ck.store_path() as (path, holder):
            with open(os.path.join(path, "weights.bin"), "wb") as f:
                f.write(b"\x0c" * 64)
        sid = holder["storage_id"]
        os.unlink(tmp_path / "store" / sid / "weights.bin")
        with pytest.raises(CheckpointCorruptError) as ei:
            ck.download(sid, str(tmp_path / "dl"))
        assert "missing" in ei.value.reason
