"""Cluster observability plane (docs/observability.md): the analytic FLOPs
engine, master-side aggregation (ingest gates, dedup, Prometheus rollups),
the in-process master's HTTP front-end, cross-component trace stitching
through a real experiment, and the bench regression gate."""
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_clone_tpu.api.inprocess import (
    InProcessMaster,
    InProcessSession,
    MasterHTTPServer,
)
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.experiment import LocalExperimentRunner
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.telemetry import flops as flops_mod
from determined_clone_tpu.telemetry import (
    parse_prometheus_text,
    validate_chrome_trace,
)
from determined_clone_tpu.telemetry.aggregate import (
    MAX_INGEST_BATCH,
    MAX_SAMPLE_BYTES,
    ClusterMetricsAggregator,
)
from determined_clone_tpu.training import JaxTrial
from determined_clone_tpu.utils.retry import RetryPolicy

from tools import bench_gate


# ---------------------------------------------------------------------------
# Analytic FLOPs / MFU engine
# ---------------------------------------------------------------------------

class TestFlops:
    def test_attention_formula(self):
        # L * (8 d^2 + 4 s d) per token
        assert flops_mod.attention_flops_per_token(
            d_model=64, seq_len=128, n_layers=2
        ) == 2 * (8 * 64**2 + 4 * 128 * 64)

    def test_mlp_dense_vs_moe(self):
        dense = flops_mod.mlp_flops_per_token(64, 256, n_layers=2)
        assert dense == 2 * 4 * 64 * 256
        # top-1 of 8 experts: one expert's compute + the router
        moe = flops_mod.mlp_flops_per_token(
            64, 256, n_layers=2, moe_experts=8, moe_k=1)
        assert moe == 2 * (4 * 64 * 256 + 2 * 64 * 8)

    def test_moe_layer_flops_hand_computed(self):
        # N=8 tokens, D=4, F=8, E=2 experts, cf=1.0 -> C = ceil(8/2) = 4
        # router  2*8*4*2        = 128
        # dispatch 2*8*2*4*4     = 512
        # up      2*2*4*4*8      = 512
        # down    2*2*4*8*4      = 512
        # combine 2*8*2*4*4      = 512
        out = flops_mod.moe_layer_flops(8, 4, 8, 2, capacity_factor=1.0)
        assert out["capacity"] == 4
        assert out["router"] == 128
        assert out["dispatch"] == 512
        assert out["up"] == 512
        assert out["down"] == 512
        assert out["combine"] == 512
        assert out["total"] == 2176

    def test_moe_capacity_shapes_the_count(self):
        # the einsum-dispatch count grows with E*C, not top-k: raising the
        # capacity factor raises expert + dispatch/combine terms alike
        lo = flops_mod.moe_layer_flops(8, 4, 8, 2, capacity_factor=1.0)
        hi = flops_mod.moe_layer_flops(8, 4, 8, 2, capacity_factor=1.25)
        assert hi["capacity"] == 5 and lo["capacity"] == 4
        assert hi["up"] / lo["up"] == pytest.approx(5 / 4)
        assert hi["dispatch"] / lo["dispatch"] == pytest.approx(5 / 4)
        assert hi["router"] == lo["router"]  # router sees N, not C
        # capacity floors at one slot per expert
        tiny = flops_mod.moe_layer_flops(2, 4, 8, 8, capacity_factor=1.0)
        assert tiny["capacity"] == 1

    def test_gpt_step_uses_exact_moe_count(self):
        class Cfg:
            n_layers, d_model, n_heads = 2, 64, 4
            d_ff, vocab_size, max_seq_len = 256, 512, 32
            moe_experts, moe_capacity_factor = 4, 1.0

        step = flops_mod.gpt_train_step_flops(Cfg(), batch_size=2)
        layer = flops_mod.moe_layer_flops(
            step.tokens, 64, 256, 4, capacity_factor=1.0)
        # the step-level mlp term is the exact capacity-based layer count
        # (x layers x train multiplier), not the top-k approximation
        assert step.breakdown["mlp"] == pytest.approx(
            Cfg.n_layers * layer["total"] * flops_mod.TRAIN_MULT)
        approx = (flops_mod.mlp_flops_per_token(
            64, 256, n_layers=2, moe_experts=4) * flops_mod.TRAIN_MULT
            * step.tokens)
        assert step.breakdown["mlp"] != pytest.approx(approx)

    def test_gpt_step_scales_with_batch(self):
        class Cfg:
            n_layers, d_model, n_heads = 2, 64, 4
            d_ff, vocab_size, max_seq_len = 256, 512, 32

        one = flops_mod.gpt_train_step_flops(Cfg(), batch_size=1)
        four = flops_mod.gpt_train_step_flops(Cfg(), batch_size=4)
        assert four.total == pytest.approx(4 * one.total)
        assert one.tokens == 32
        # training = 3x forward
        fwd = flops_mod.gpt_forward_flops_per_token(Cfg(), 32)
        assert one.per_token == pytest.approx(
            flops_mod.TRAIN_MULT * sum(fwd.values()))

    def test_dense_6n_fallback(self):
        assert flops_mod.dense_train_flops_per_token(1000) == 6000
        step = flops_mod.dense_train_step_flops(
            1000, batch_size=2, seq_len=8)
        assert step.total == 6000 * 16

    def test_mfu_and_cpu_peak_label(self):
        peak, label = flops_mod.peak_flops_estimate("cpu")
        assert label == "cpu:est"
        assert flops_mod.mfu(peak / 2, peak) == pytest.approx(0.5)
        assert flops_mod.mfu(peak, peak, n_devices=4) == pytest.approx(0.25)

    def test_tpu_generation_from_env_and_unknown_fallback(self, monkeypatch):
        monkeypatch.setenv("DCT_TPU_GENERATION", "v5p")
        peak, label = flops_mod.peak_flops_estimate("tpu")
        assert peak == flops_mod.TPU_PEAK_BF16_FLOPS["v5p"]
        assert label == "tpu:v5p"
        # unknown generation: fleet-default peak, labeled as assumed
        monkeypatch.delenv("DCT_TPU_GENERATION")
        peak, label = flops_mod.peak_flops_estimate("tpu")
        assert label == "tpu:v5e:assumed"


# ---------------------------------------------------------------------------
# Master-side aggregation: ingest gates, dedup, rollups
# ---------------------------------------------------------------------------

def _telemetry_sample(metrics):
    return {"time": 1.0, "group": "telemetry", "metrics": metrics}


def _gauge(v):
    return {"type": "gauge", "value": v}


class TestAggregator:
    def test_idempotent_ingest_counts_duplicates(self):
        agg = ClusterMetricsAggregator()
        batch = [_telemetry_sample({"samples_per_sec": _gauge(10.0)})]
        assert agg.ingest(1, batch, idempotency_key="k1") == 1
        assert agg.ingest(1, batch, idempotency_key="k1") == 0
        text = agg.dump()
        assert "dct_master_ingest_duplicates_total 1" in text
        assert "dct_master_ingest_batches_total 1" in text

    def test_rejection_reasons_counted(self):
        agg = ClusterMetricsAggregator()
        agg.ingest(1, "not a list")                     # not_a_list
        agg.ingest(1, [{}] * (MAX_INGEST_BATCH + 1))    # batch_too_large
        agg.ingest(1, [{"group": 7}])                   # malformed
        agg.ingest(1, [{"group": "span",
                        "blob": "x" * (MAX_SAMPLE_BYTES + 1)}])  # oversized
        parsed = parse_prometheus_text(agg.dump())
        rejected = {labels["reason"]: v for n, labels, v in parsed["samples"]
                    if n == "dct_master_ingest_rejected_total"}
        assert rejected["not_a_list"] >= 1
        assert rejected["batch_too_large"] >= 1
        assert rejected["malformed"] >= 1
        assert rejected["oversized"] >= 1

    def test_rollup_sums_across_trials(self):
        agg = ClusterMetricsAggregator()
        agg.ingest(1, [_telemetry_sample(
            {"samples_per_sec": _gauge(10.0)})], idempotency_key="a")
        agg.ingest(2, [_telemetry_sample(
            {"samples_per_sec": _gauge(30.0)})], idempotency_key="b")
        parsed = parse_prometheus_text(agg.dump())
        flat = {(n, labels.get("trial_id")): v
                for n, labels, v in parsed["samples"]}
        assert flat[("samples_per_sec", "1")] == 10.0
        assert flat[("samples_per_sec", "2")] == 30.0
        assert flat[("dct_cluster_samples_per_sec", None)] == 40.0
        assert flat[("dct_cluster_samples_per_sec_avg", None)] == 20.0

    def test_summary_ranks_by_throughput(self):
        agg = ClusterMetricsAggregator()
        for tid, rate in ((1, 5.0), (2, 50.0), (3, 20.0)):
            agg.ingest(tid, [_telemetry_sample(
                {"samples_per_sec": _gauge(rate)})],
                idempotency_key=f"t{tid}")
        s = agg.summary(top_n=2)
        assert [t[0] for t in s["top_trials_by_throughput"]] == ["2", "3"]
        assert s["throughput_total"] == pytest.approx(75.0)


# ---------------------------------------------------------------------------
# The in-process master over real HTTP
# ---------------------------------------------------------------------------

class TestMasterHTTP:
    def test_metrics_endpoint_round_trips(self):
        master = InProcessMaster()
        with MasterHTTPServer(master) as srv:
            url = f"http://{srv.host}:{srv.port}"
            body = json.dumps({
                "samples": [_telemetry_sample(
                    {"samples_per_sec": _gauge(12.5)})],
                "idempotency_key": "once",
            }).encode()
            req = urllib.request.Request(
                f"{url}/api/v1/trials/7/profiler", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert json.loads(resp.read())["accepted"] == 1
            with urllib.request.urlopen(f"{url}/metrics",
                                        timeout=10) as resp:
                assert resp.headers["Content-Type"].startswith("text/plain")
                text = resp.read().decode()
        parsed = parse_prometheus_text(text)
        flat = {(n, labels.get("trial_id")): v
                for n, labels, v in parsed["samples"]}
        assert flat[("samples_per_sec", "7")] == 12.5
        assert flat[("dct_master_ingest_batches_total", None)] == 1.0
        assert parsed["types"]["samples_per_sec"] == "gauge"

    def test_session_shim_and_404(self):
        master = InProcessMaster()
        session = InProcessSession(master)
        assert session.get("/api/v1/cluster/metrics")["trials"] == 0
        from determined_clone_tpu.api.client import MasterError
        with pytest.raises(MasterError):
            session.get("/api/v1/nope")


# ---------------------------------------------------------------------------
# E2E: an experiment drives the whole plane
# ---------------------------------------------------------------------------

class PlaneTrial(JaxTrial):
    """Tiny quadratic trial that fails its first leg so the plane sees a
    restart (retry counters > 0, restart leg as a sibling trace lane)."""

    _failed = {}

    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.3)

    def loss(self, params, batch, rng):
        return (params["w"] - 1.0) ** 2, {}

    def training_data(self):
        if not PlaneTrial._failed.get("done"):
            PlaneTrial._failed["done"] = True
            raise RuntimeError("injected failure")
        for _ in range(64):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2


@pytest.fixture(scope="module")
def plane(tmp_path_factory):
    """One observability-enabled experiment run against an in-process
    master, shared by the assertions below."""
    PlaneTrial._failed = {}
    tmp_path = tmp_path_factory.mktemp("plane")
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path)},
        "hyperparameters": {"lr": 0.5},
        "max_restarts": 1,
        "observability": {"enabled": True, "ship_spans": True,
                          "ship_metrics": True},
    })
    master = InProcessMaster()
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    runner = LocalExperimentRunner(
        cfg, PlaneTrial, storage_path=str(tmp_path), mesh=mesh,
        master=master, experiment_id=1,
        restart_backoff=RetryPolicy(name="test", base_delay_s=0.0,
                                    max_delay_s=0.0, jitter="none"))
    result = runner.run()
    return master, runner, result


class TestExperimentE2E:
    def test_run_completed_with_restart(self, plane):
        _, _, result = plane
        t = list(result.trials.values())[0]
        assert t.state == "completed"
        assert t.restarts == 1

    def test_metrics_page_has_rollups_and_counters(self, plane):
        master, _, _ = plane
        parsed = parse_prometheus_text(master.metrics_text())
        names = {n for n, _, _ in parsed["samples"]}
        # rolled-up trial throughput + per-step MFU accounting
        assert "dct_cluster_samples_per_sec" in names
        assert "dct_cluster_mfu" in names
        assert "dct_cluster_flops_per_sec" in names
        # the runner lane's restart counter made it in and rolled up
        flat = {(n, labels.get("component")): v
                for n, labels, v in parsed["samples"]}
        assert flat[("trial_restarts_total", "runner")] == 1.0
        assert flat[("dct_cluster_trial_restarts_total", None)] == 1.0
        assert flat[("dct_master_ingest_duplicates_total", None)] == 0.0

    def test_mfu_gauges_carry_provenance(self, plane):
        master, _, _ = plane
        parsed = parse_prometheus_text(master.metrics_text())
        infos = [labels for n, labels, _ in parsed["samples"]
                 if n == "mfu_peak_info"]
        assert infos, "trainer never shipped mfu_peak_info"
        assert all(i["assumed"] == "cpu:est" for i in infos)
        assert all(i["flops_source"] == "dense_6n" for i in infos)
        mfus = [v for n, _, v in parsed["samples"] if n == "mfu"]
        assert mfus and all(v > 0 for v in mfus)

    def test_summary_view(self, plane):
        master, _, _ = plane
        s = master.summary()
        assert s["trials"] == 1
        assert s["top_trials_by_throughput"][0][0] == "0"
        assert s["counters"].get("trial_restarts_total") == 1

    def test_cli_trace_export_stitches_experiment(self, plane, tmp_path):
        from determined_clone_tpu.cli.cli import main

        master, runner, _ = plane
        out = tmp_path / "trace.json"
        with MasterHTTPServer(master) as srv:
            rc = main(["-m", f"{srv.host}:{srv.port}", "trace", "export",
                       "--experiment", "1", "-o", str(out)])
        assert rc == 0
        with open(out) as f:
            trace = json.load(f)
        assert validate_chrome_trace(trace) == []
        # >= 2 process lanes (runner + the trial), one shared trace_id
        lanes = trace["otherData"]["processes"]
        assert "runner" in lanes and "trial-0" in lanes
        assert len(lanes) >= 2
        assert trace["otherData"]["trace_ids"] == [runner.trace_id]
        # the restart shows as sibling trial_leg spans in the runner lane
        legs = [e for e in trace["traceEvents"]
                if e.get("name") == "trial_leg"]
        assert len(legs) == 2
        assert len({e["pid"] for e in legs}) == 1

    def test_cli_metrics_summary_and_raw(self, plane, capsys):
        from determined_clone_tpu.cli.cli import main

        master, _, _ = plane
        with MasterHTTPServer(master) as srv:
            addr = f"{srv.host}:{srv.port}"
            assert main(["-m", addr, "metrics"]) == 0
            human = capsys.readouterr().out
            assert main(["-m", addr, "metrics", "--raw"]) == 0
            raw = capsys.readouterr().out
        assert "trial" in human

        def stable(text):
            # dct_master_source_age_seconds is wall-clock-valued: the two
            # dumps happen at different instants, so ages differ
            return [s for s in parse_prometheus_text(text)["samples"]
                    if s[0] != "dct_master_source_age_seconds"]

        assert stable(raw) == stable(master.metrics_text())


# ---------------------------------------------------------------------------
# Bench regression gate
# ---------------------------------------------------------------------------

def _bench_result(value, platform="cpu", mfu=0.3):
    return {"metric": "gpt_train_throughput", "value": value,
            "detail": {"platform": platform, "mfu": mfu,
                       "mfu_peak_assumed": "cpu:est" if mfu else None}}


class TestBenchGate:
    def test_wrapper_tail_parses(self, tmp_path):
        wrapped = tmp_path / "BENCH_r01.json"
        wrapped.write_text(json.dumps({
            "n": 1, "cmd": "bench", "rc": 0,
            "tail": "noise\n" + json.dumps(_bench_result(10.0)) + "\n",
        }))
        assert bench_gate.load_bench(str(wrapped))["value"] == 10.0

    def test_within_tolerance_passes(self):
        ok, _ = bench_gate.gate(_bench_result(100.0), _bench_result(96.0))
        assert ok

    def test_regression_fails(self):
        ok, report = bench_gate.gate(_bench_result(100.0),
                                     _bench_result(90.0))
        assert not ok
        assert any("FAIL" in line for line in report)

    def test_null_mfu_fails_even_when_faster(self):
        ok, _ = bench_gate.gate(_bench_result(100.0),
                                _bench_result(200.0, mfu=None))
        assert not ok
        ok, _ = bench_gate.gate(_bench_result(100.0),
                                _bench_result(200.0, mfu=None),
                                allow_null_mfu=True)
        assert ok

    def test_platform_change_skips_throughput(self):
        # TPU round vs CPU round: 10x slower but not a regression
        ok, report = bench_gate.gate(
            _bench_result(400.0, platform="tpu"),
            _bench_result(40.0, platform="cpu"))
        assert ok
        assert any("platform changed" in line for line in report)

    def test_cli_against_real_rounds(self, tmp_path):
        # the repo's own previous round vs a synthetic new one
        new = tmp_path / "new.json"
        new.write_text(json.dumps(_bench_result(41.0)))
        assert bench_gate.main(["BENCH_r05.json", str(new)]) == 0
