"""Auth boundary e2e: --auth-required master, allocation tokens, KDF.

≈ the reference's auth model: user sessions gate the API surface
(master/internal/api_auth.go), allocation-scoped session tokens carry the
data plane (master/internal/task/allocation_service.go), and the proxy is
part of the authenticated surface (master/internal/proxy/proxy.go).
Covers the round-1 ADVICE findings: anonymous /proxy dispatch, /exec
exposure, task-server interface-binding trust, FNV password hashing.
"""
import json
import os
import subprocess
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("sec")
    workdir = tmp / "agent-work"
    workdir.mkdir()

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data"), "--auth-required"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "sec-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            session.login("admin", "")
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


def raw_request(port, method, path, body=None, headers=None, host="127.0.0.1"):
    """Anonymous/direct HTTP without MasterSession's token handling.
    Returns (status, parsed-or-text body)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
            status = resp.status
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors="replace")
        status = e.code
    try:
        return status, json.loads(text)
    except ValueError:
        return status, text


def wait_for(predicate, timeout=60, interval=0.3, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def read_master_snapshot(data_dir):
    """The persisted master state, whichever store backend is active
    (sqlite kv table, or the legacy snapshot.json)."""
    db = data_dir / "master.db"
    if db.exists():
        import sqlite3

        with sqlite3.connect(db) as conn:
            row = conn.execute(
                "SELECT value FROM kv WHERE key='snapshot'").fetchone()
        if row:
            return json.loads(row[0])
        return None
    snap = data_dir / "snapshot.json"
    if snap.exists():
        return json.loads(snap.read_text())
    return None


def test_anonymous_api_rejected(cluster):
    port = cluster["port"]
    for method, path in [
        ("GET", "/api/v1/experiments"),
        ("GET", "/api/v1/tasks"),
        ("GET", "/api/v1/users"),
        ("POST", "/api/v1/tasks"),
        ("GET", "/api/v1/job-queue"),
    ]:
        status, body = raw_request(port, method, path, body={} if method == "POST" else None)
        assert status == 401, f"{method} {path} -> {status} {body}"


def test_login_and_me(cluster):
    port = cluster["port"]
    status, out = raw_request(port, "POST", "/api/v1/auth/login",
                              {"username": "admin", "password": ""})
    assert status == 200 and out["token"]
    status, me = raw_request(port, "GET", "/api/v1/auth/me",
                             headers={"Authorization": f"Bearer {out['token']}"})
    assert status == 200 and me["user"]["username"] == "admin"
    status, _ = raw_request(port, "POST", "/api/v1/auth/login",
                            {"username": "admin", "password": "wrong"})
    assert status == 401


def test_password_change_uses_kdf(cluster):
    session = cluster["session"]
    port = cluster["port"]
    user = session.request("POST", "/api/v1/users",
                           {"username": "kdfuser", "password": "first"})["user"]
    status, out = raw_request(port, "POST", "/api/v1/auth/login",
                              {"username": "kdfuser", "password": "first"})
    assert status == 200
    session.request("POST", f"/api/v1/users/{user['id']}/password",
                    {"password": "second"})
    status, _ = raw_request(port, "POST", "/api/v1/auth/login",
                            {"username": "kdfuser", "password": "first"})
    assert status == 401
    status, _ = raw_request(port, "POST", "/api/v1/auth/login",
                            {"username": "kdfuser", "password": "second"})
    assert status == 200
    # the persisted hash is the KDF format, not a bare FNV hex
    data_dir = cluster["tmp"] / "master-data"
    snap = wait_for(
        lambda: (lambda s: s if s and any(
            u["username"] == "kdfuser" for u in s.get("users", []))
            else None)(read_master_snapshot(data_dir)),
        desc="snapshot with kdfuser")
    stored = [u for u in snap["users"] if u["username"] == "kdfuser"][0]
    assert stored["password_hash"].startswith("pbkdf2_sha256$")


def test_api_responses_never_leak_alloc_token(cluster):
    session = cluster["session"]
    task = session.create_task("shell", name="leakcheck")
    assert "token" not in task
    listed = [t for t in session.list_tasks() if t["id"] == task["id"]][0]
    assert "token" not in listed
    session.kill_task(task["id"])


def test_proxy_requires_auth_and_task_requires_token(cluster):
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="sec-sh")
    tid = task["id"]
    wait_for(
        lambda: (lambda t: t if t["state"] == "RUNNING" and
                 t["proxy_address"] else None)(session.get_task(tid)),
        desc="shell task proxied",
    )

    # 1. anonymous /proxy POST (the round-1 RCE hole) is rejected
    status, body = raw_request(port, "POST", f"/proxy/{tid}/exec",
                               {"cmd": ["id"]})
    assert status == 401, f"anonymous proxy exec allowed: {body}"

    # 2. authenticated proxy exec works
    out = session.proxy(tid, "/exec", "POST", {"cmd": ["echo", "sec-ok"]})
    assert out["code"] == 0 and out["stdout"].strip() == "sec-ok"

    # 3. direct task-server access (bypassing the proxy) without the
    #    allocation token is rejected — binding is not the boundary
    host, tport = session.get_task(tid)["proxy_address"].rsplit(":", 1)
    status, body = raw_request(int(tport), "POST", "/exec",
                               {"cmd": ["id"]}, host=host)
    assert status == 401, f"tokenless direct exec allowed: {body}"
    status, _ = raw_request(int(tport), "POST", "/exec", {"cmd": ["id"]},
                            headers={"X-Alloc-Token": "f" * 32}, host=host)
    assert status == 401

    session.kill_task(tid)


def test_alloc_token_is_readonly_scoped(cluster):
    """Task containers run untrusted code: their DCT_ALLOC_TOKEN must open
    data-plane reads (experiments GET) but no mutating route."""
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="scope-sh")
    data_dir = cluster["tmp"] / "master-data"
    alloc_token = wait_for(
        lambda: next((a.get("token") for a in
                      (read_master_snapshot(data_dir) or {}).get(
                          "allocations", [])
                      if a["id"] == task["id"] and a.get("token")), None),
        desc="allocation token persisted")
    headers = {"Authorization": f"Bearer {alloc_token}"}
    status, _ = raw_request(port, "GET", "/api/v1/experiments",
                            headers=headers)
    assert status == 200
    status, _ = raw_request(port, "POST", "/api/v1/tasks",
                            {"type": "shell", "name": "evil"}, headers=headers)
    assert status == 401
    status, _ = raw_request(port, "GET", "/api/v1/job-queue", headers=headers)
    assert status == 401
    session.kill_task(task["id"])


def test_exec_is_shell_mode_only(cluster):
    session = cluster["session"]
    task = session.create_task("notebook", name="sec-nb")
    tid = task["id"]
    wait_for(
        lambda: (lambda t: t if t["state"] == "RUNNING" and
                 t["proxy_address"] else None)(session.get_task(tid)),
        desc="notebook task proxied",
    )
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError) as err:
        session.proxy(tid, "/exec", "POST", {"cmd": ["id"]})
    assert err.value.status == 403
    session.kill_task(tid)


def test_trial_kill_requires_session(cluster):
    """Round-3 ADVICE (high): with --auth-required but RBAC off, anonymous
    POST /trials/:id/kill previously fell through rbac_allows() (which
    passes unconditionally when RBAC is disabled). It must 401 without a
    session and succeed with one."""
    session = cluster["session"]
    port = cluster["port"]
    exp = session.create_experiment({
        "name": "killsec", "entrypoint": "x:Y",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
        "hyperparameters": {},
    })
    trial_id = wait_for(
        lambda: next((t["id"] for t in
                      session.get_experiment(exp["id"]).get("trials", [])),
                     None),
        desc="trial created")
    status, _ = raw_request(port, "POST", f"/api/v1/trials/{trial_id}/kill")
    assert status == 401
    status, _ = raw_request(
        port, "POST", f"/api/v1/trials/{trial_id}/kill",
        headers={"Authorization": f"Bearer {session.token}"})
    assert status == 200
    session.kill_experiment(exp["id"])


def test_allgather_requires_alloc_token(cluster):
    """Round-3 ADVICE (medium): the allgather barrier must demand the
    allocation's data-plane token — an anonymous peer could otherwise
    inject its own address into a live gang's rendezvous payload."""
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="ag-sec")
    tid = task["id"]
    data_dir = cluster["tmp"] / "master-data"
    alloc_token = wait_for(
        lambda: next((a.get("token") for a in
                      (read_master_snapshot(data_dir) or {}).get(
                          "allocations", [])
                      if a["id"] == tid and a.get("token")), None),
        desc="allocation token persisted")
    wait_for(lambda: session.get_task(tid)["state"] in
             ("RUNNING", "PULLING"), desc="allocation live")
    body = {"rank": 0, "round": 0, "data": {"addr": "evil:1"}}
    status, _ = raw_request(
        port, "POST", f"/api/v1/allocations/{tid}/allgather", body)
    assert status == 401
    status, resp = raw_request(
        port, "POST", f"/api/v1/allocations/{tid}/allgather", body,
        headers={"Authorization": f"Bearer {alloc_token}"})
    assert status == 200
    session.kill_task(tid)


def test_allocation_data_plane_requires_token(cluster):
    """All /allocations/:id/* routes are data-plane: rendezvous and proxy
    posts steer gang/user traffic, log posts feed log-pattern policies (a
    kill primitive). Anonymous access must 401; the allocation's token (or
    a session) opens them."""
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="dp-sec")
    tid = task["id"]
    data_dir = cluster["tmp"] / "master-data"
    alloc_token = wait_for(
        lambda: next((a.get("token") for a in
                      (read_master_snapshot(data_dir) or {}).get(
                          "allocations", [])
                      if a["id"] == tid and a.get("token")), None),
        desc="allocation token persisted")
    headers = {"Authorization": f"Bearer {alloc_token}"}

    for method, path, body in [
        ("POST", f"/api/v1/allocations/{tid}/rendezvous",
         {"rank": 0, "address": "evil:1"}),
        ("POST", f"/api/v1/allocations/{tid}/proxy",
         {"address": "evil:80"}),
        ("POST", f"/api/v1/allocations/{tid}/logs",
         {"logs": ["injected"]}),
        ("GET", f"/api/v1/allocations/{tid}/logs", None),
        ("GET", f"/api/v1/allocations/{tid}/preempt", None),
    ]:
        status, _ = raw_request(port, method, path, body)
        assert status == 401, f"anonymous {method} {path} -> {status}"
        status, _ = raw_request(port, method, path, body, headers=headers)
        assert status == 200, f"token {method} {path} -> {status}"

    # out-of-range rendezvous ranks are rejected even with the token
    status, _ = raw_request(
        port, "POST", f"/api/v1/allocations/{tid}/rendezvous",
        {"rank": 5, "address": "x:1"}, headers=headers)
    assert status == 400
    session.kill_task(tid)


def test_trial_mutations_require_session_or_own_token(cluster):
    """Trial data-plane mutations (metrics/searcher ops) can steer or stop
    an HP search, so anonymous posts must 401; the trial's own allocation
    token or a session opens them."""
    session = cluster["session"]
    port = cluster["port"]
    exp = session.create_experiment({
        "name": "trialgate", "entrypoint": "x:Y",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
        "hyperparameters": {},
    })
    trial_id = wait_for(
        lambda: next((t["id"] for t in
                      session.get_experiment(exp["id"]).get("trials", [])),
                     None),
        desc="trial created")
    body = {"group": "training", "steps_completed": 999999,
            "metrics": {"loss": 0.0}}
    status, _ = raw_request(
        port, "POST", f"/api/v1/trials/{trial_id}/metrics", body)
    assert status == 401
    status, _ = raw_request(
        port, "GET", f"/api/v1/trials/{trial_id}")
    assert status == 401
    status, _ = raw_request(
        port, "POST", f"/api/v1/trials/{trial_id}/metrics", body,
        headers={"Authorization": f"Bearer {session.token}"})
    assert status == 200
    session.kill_experiment(exp["id"])


def test_log_follow_route_requires_auth(cluster):
    """The follow long-poll is dispatched outside route()'s gate and
    carries its own copy — anonymous followers must 401, token 200."""
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="follow-sec")
    tid = task["id"]
    data_dir = cluster["tmp"] / "master-data"
    alloc_token = wait_for(
        lambda: next((a.get("token") for a in
                      (read_master_snapshot(data_dir) or {}).get(
                          "allocations", [])
                      if a["id"] == tid and a.get("token")), None),
        desc="allocation token persisted")
    status, _ = raw_request(
        port, "GET", f"/api/v1/allocations/{tid}/logs?follow=0")
    assert status == 401
    status, out = raw_request(
        port, "GET", f"/api/v1/allocations/{tid}/logs?follow=0",
        headers={"Authorization": f"Bearer {alloc_token}"})
    assert status == 200 and "next_offset" in out
    session.kill_task(tid)
