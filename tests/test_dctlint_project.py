"""dctlint v2 whole-program analysis: ProjectIndex units, the new
project-scope checkers against seeded fixture trees, the incremental
cache, `--changed` scoping, and the cold-run perf budget.

The fixture trees mirror the acceptance criteria of the whole-program
pass: a two-lock ordering cycle, a blocking call under a lock, a
fault-point/doc-catalog mismatch (both directions), a conflicting
metric family, a schema key that never round-trips, and a jitted
closure over ``self`` — each must produce exactly the expected
diagnostic, and the clean variants must stay clean.
"""
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.dctlint import core as lint_core  # noqa: E402
from tools.dctlint.core import _analyze_source  # noqa: E402
from tools.dctlint.project import (  # noqa: E402
    ProjectIndex, module_name_for)

TIER1_LINT_PATHS = ["determined_clone_tpu", "tools", "bench.py"]
PERF_BUDGET_S = 10.0


def _write_tree(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))


def _run_tree(tmp_path, files, select=None, **kw):
    _write_tree(tmp_path, files)
    return lint_core.run([str(tmp_path)], select=select,
                         relative_to=tmp_path, **kw)


def _index(files):
    """ProjectIndex straight from sources (repo-relative paths)."""
    facts = {}
    for rel, src in files.items():
        mod, ispkg = module_name_for(rel)
        res = _analyze_source(rel, textwrap.dedent(src), mod, ispkg)
        facts[rel] = res["facts"]
    return ProjectIndex(facts)


# ---------------------------------------------------------------------------
# ProjectIndex units: alias + relative-import resolution, propagation
# ---------------------------------------------------------------------------

def test_relative_import_resolves_to_defining_module():
    idx = _index({
        "pkg/__init__.py": "",
        "pkg/a.py": """
            import threading

            _glock = threading.Lock()

            def helper():
                with _glock:
                    pass
            """,
        "pkg/b.py": """
            from .a import helper

            def caller():
                helper()
            """,
    })
    ni = idx.files["pkg/b.py"]["name_imports"]
    assert ni["helper"] == "pkg.a.helper"
    targets = idx.resolve_call("pkg.b.caller",
                               idx.functions["pkg.b.caller"]
                               ["facts"]["calls"][0][0])
    assert ("pkg.a.helper", True) in targets
    acq = idx.eventual_acquires("pkg.b.caller")
    assert "pkg.a._glock" in acq
    assert acq["pkg.a._glock"]["certain"]


def test_condition_alias_collapses_onto_wrapped_lock():
    idx = _index({
        "mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)
            """,
    })
    resolved = idx.resolve_lockref("mod", ["c", "C", "_cond"])
    assert resolved == ("mod.C._lock", "lock")


def test_typed_self_attribute_call_is_certain():
    idx = _index({
        "mod.py": """
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()

                def take(self):
                    with self._lock:
                        pass

            class Owner:
                def __init__(self):
                    self.pool = Pool()

                def use(self):
                    self.pool.take()
            """,
    })
    desc = idx.functions["mod.Owner.use"]["facts"]["calls"][0][0]
    assert idx.resolve_call("mod.Owner.use", desc) == \
        [("mod.Pool.take", True)]


def test_mutable_attrs_excludes_init_only_state():
    idx = _index({
        "mod.py": """
            class C:
                def __init__(self):
                    self.frozen = 1

                def poke(self):
                    self.counter = 2
            """,
    })
    assert idx.mutable_attrs("mod.C") == {"counter"}


# ---------------------------------------------------------------------------
# CONC003 — lock-order cycles and the documented hierarchy
# ---------------------------------------------------------------------------

def test_conc003_two_lock_cycle_fixture(tmp_path):
    diags = _run_tree(tmp_path, {
        "locks.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ba(self):
                    with self._b:
                        with self._a:
                            pass
            """,
    }, select=["CONC003"])
    assert [d.rule for d in diags] == ["CONC003"]
    assert "lock-order cycle" in diags[0].message
    assert "locks.Pair._a" in diags[0].message
    assert "locks.Pair._b" in diags[0].message
    assert "hierarchy" in diags[0].hint


def test_conc003_consistent_order_is_clean(tmp_path):
    diags = _run_tree(tmp_path, {
        "locks.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ab_again(self):
                    with self._a:
                        with self._b:
                            pass
            """,
    }, select=["CONC003"])
    assert diags == []


def test_conc003_cycle_through_call_graph(tmp_path):
    diags = _run_tree(tmp_path, {
        "graph.py": """
            import threading

            class A:
                def __init__(self, b):
                    self._lock = threading.Lock()
                    self.b = b

                def work(self):
                    with self._lock:
                        self.b.poke()

            class B:
                def __init__(self, a):
                    self._lock = threading.Lock()
                    self.a = a

                def poke(self):
                    with self._lock:
                        pass

                def back(self):
                    with self._lock:
                        self.a.ping()

            class AHelper:
                pass
            """,
        "graph2.py": """
            import threading
            from graph import A

            class Other:
                def __init__(self):
                    self.a = A(None)

                def go(self):
                    self.a.work()
            """,
    }, select=["CONC003"])
    # A._lock -> B._lock via work(); no back edge resolves certainly
    # (A.ping doesn't exist), so the graph stays acyclic
    assert diags == []


def test_conc003_report_names_documented_hierarchy(tmp_path):
    stats = {}
    _run_tree(tmp_path, {
        "mod.py": """
            import threading

            _l = threading.Lock()

            def f():
                with _l:
                    pass
            """,
    }, select=["CONC003"], stats=stats)
    summary = stats["summaries"]["CONC003"]
    assert "hierarchy verified: " \
        "control < serving < resource < recorder < sink < leaf" in summary


def test_conc003_plain_lock_self_reacquire(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
            """,
    }, select=["CONC003"])
    assert [d.rule for d in diags] == ["CONC003"]
    assert "re-acquired" in diags[0].message
    assert "RLock" in diags[0].hint


def test_conc003_rlock_reentrancy_is_fine(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
            """,
    }, select=["CONC003"])
    assert diags == []


# ---------------------------------------------------------------------------
# CONC004 — blocking call while a lock is held
# ---------------------------------------------------------------------------

def test_conc004_sleep_under_lock_fixture(tmp_path):
    diags = _run_tree(tmp_path, {
        "box.py": """
            import threading
            import time

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def slow(self):
                    with self._lock:
                        time.sleep(0.5)
            """,
    }, select=["CONC004"])
    assert [d.rule for d in diags] == ["CONC004"]
    assert "time.sleep" in diags[0].message
    assert "box.Box._lock" in diags[0].message
    assert "outside the critical section" in diags[0].hint


def test_conc004_propagates_through_certain_calls(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import threading
            import time

            def nap():
                time.sleep(1)

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def f(self):
                    with self._lock:
                        nap()
            """,
    }, select=["CONC004"])
    assert [d.rule for d in diags] == ["CONC004"]
    assert "may block" in diags[0].message
    assert "mod.nap" in diags[0].message


def test_conc004_sleep_outside_lock_is_clean(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import threading
            import time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def f(self):
                    with self._lock:
                        n = 1
                    time.sleep(n)
            """,
    }, select=["CONC004"])
    assert diags == []


def test_conc004_condition_wait_own_lock_exempt(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)

                def wait(self):
                    with self._cond:
                        self._cond.wait()
            """,
    }, select=["CONC004"])
    assert diags == []


# ---------------------------------------------------------------------------
# CONTRACT001 — fault-point catalog sync (both directions)
# ---------------------------------------------------------------------------

_FAULTS_STUB = """
    def point(name):
        pass
    """

_FAULT_DOC = """
    # Fault tolerance

    ### Fault points

    | point | where |
    |---|---|
    | `db.write` | the documented one |
    | `db.ghost` | this point no longer exists |
    """


def test_contract001_missing_and_stale_rows(tmp_path):
    diags = _run_tree(tmp_path, {
        "faults.py": _FAULTS_STUB,
        "docs/fault_tolerance.md": _FAULT_DOC,
        "app.py": """
            import faults

            def save():
                faults.point("db.write")
                faults.point("db.commit")
            """,
    }, select=["CONTRACT001"])
    assert len(diags) == 2
    missing = [d for d in diags if d.path == "app.py"]
    stale = [d for d in diags if d.path == "docs/fault_tolerance.md"]
    assert len(missing) == 1 and len(stale) == 1
    assert 'fault point "db.commit" has no row' in missing[0].message
    assert "add the missing row" in missing[0].hint
    assert 'row "db.ghost"' in stale[0].message
    assert "no longer exists" in stale[0].message


def test_contract001_synced_catalog_is_clean(tmp_path):
    diags = _run_tree(tmp_path, {
        "faults.py": _FAULTS_STUB,
        "docs/fault_tolerance.md": """
            ### Fault points

            | point | where |
            |---|---|
            | `db.write` / `db.commit` | both live here |
            """,
        "app.py": """
            import faults

            def save():
                faults.point("db.write")
                faults.point("db.commit")
            """,
    }, select=["CONTRACT001"])
    assert diags == []


def test_contract001_stale_rows_skipped_on_partial_view(tmp_path):
    # linting a subtree that doesn't include the faults runtime must
    # not declare every documented point stale
    diags = _run_tree(tmp_path, {
        "docs/fault_tolerance.md": _FAULT_DOC,
        "app.py": "x = 1\n",
    }, select=["CONTRACT001"])
    assert diags == []


# ---------------------------------------------------------------------------
# CONTRACT002 — metric family registry
# ---------------------------------------------------------------------------

def test_contract002_conflicting_types_fixture(tmp_path):
    diags = _run_tree(tmp_path, {
        "m1.py": """
            def setup(registry):
                registry.counter("jobs_total")
            """,
        "m2.py": """
            def setup(registry):
                registry.gauge("jobs_total")
            """,
    }, select=["CONTRACT002"])
    assert [d.rule for d in diags] == ["CONTRACT002"]
    assert 'family "jobs_total"' in diags[0].message
    assert "one name, one type" in diags[0].message
    assert "gauge" in diags[0].message and "counter" in diags[0].message


def test_contract002_undocumented_family(tmp_path):
    diags = _run_tree(tmp_path, {
        "docs/observability.md": "Catalog: `jobs_total` is here.\n",
        "m.py": """
            def setup(registry):
                registry.counter("jobs_total")
                registry.counter("ghosts_total")
            """,
    }, select=["CONTRACT002"])
    assert [d.rule for d in diags] == ["CONTRACT002"]
    assert 'family "ghosts_total" is not documented' in diags[0].message


def test_contract002_documented_consistent_registry_is_clean(tmp_path):
    diags = _run_tree(tmp_path, {
        "docs/observability.md": "`jobs_total` and `depth` exist.\n",
        "m.py": """
            def setup(registry):
                registry.counter("jobs_total")
                registry.gauge("depth")
            """,
        "m2.py": """
            def again(registry):
                registry.counter("jobs_total")
            """,
    }, select=["CONTRACT002"])
    assert diags == []


# ---------------------------------------------------------------------------
# CONTRACT003 — schema keys round-trip to ExperimentConfig
# ---------------------------------------------------------------------------

def test_contract003_unconsumed_key_and_fieldless_schema(tmp_path):
    diags = _run_tree(tmp_path, {
        "config/__init__.py": "",
        "config/schema.py": """
            EXPERIMENT_SCHEMA = {
                "type": "object",
                "properties": {
                    "name": {"type": "string"},
                    "mystery": {"type": "integer"},
                },
            }
            """,
        "config/experiment.py": """
            import dataclasses

            @dataclasses.dataclass
            class ExperimentConfig:
                name: str = ""
                extra_field: int = 0
            """,
    }, select=["CONTRACT003"])
    assert len(diags) == 2
    by_path = {d.path: d for d in diags}
    schema_diag = by_path["config/schema.py"]
    cfg_diag = by_path["config/experiment.py"]
    assert 'schema key "mystery"' in schema_diag.message
    assert "never consumed" in schema_diag.message
    assert "PASSTHROUGH_KEYS" in schema_diag.hint
    assert 'field "extra_field" has no EXPERIMENT_SCHEMA key' \
        in cfg_diag.message


def test_contract003_raw_get_counts_as_consumption(tmp_path):
    diags = _run_tree(tmp_path, {
        "config/__init__.py": "",
        "config/schema.py": """
            EXPERIMENT_SCHEMA = {
                "type": "object",
                "properties": {
                    "name": {"type": "string"},
                    "profiling": {"type": "object"},
                },
            }
            """,
        "config/experiment.py": """
            import dataclasses

            @dataclasses.dataclass
            class ExperimentConfig:
                name: str = ""
                profiling_on: bool = False

                @classmethod
                def from_dict(cls, raw):
                    prof = raw.get("profiling", {})
                    return cls(name=raw.get("name", ""),
                               profiling_on=bool(prof))
            """,
    }, select=["CONTRACT003"])
    # "profiling" has no field but IS consumed; "profiling_on" has no
    # schema key -> exactly one reverse-direction diag
    assert len(diags) == 1
    assert 'field "profiling_on"' in diags[0].message


def test_contract003_skips_partial_view_without_config_class(tmp_path):
    diags = _run_tree(tmp_path, {
        "config/__init__.py": "",
        "config/schema.py": """
            EXPERIMENT_SCHEMA = {
                "type": "object",
                "properties": {"orphan": {"type": "string"}},
            }
            """,
    }, select=["CONTRACT003"])
    assert diags == []


# ---------------------------------------------------------------------------
# JAX004 — jit-boundary purity
# ---------------------------------------------------------------------------

def test_jax004_bound_method_closure_over_self(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import jax

            class Runner:
                def __init__(self):
                    self.scale = 1.0

                def _step(self, x):
                    return x * self.scale

                def compile(self):
                    return jax.jit(self._step)
            """,
    }, select=["JAX004"])
    assert [d.rule for d in diags] == ["JAX004"]
    assert "bound method self._step" in diags[0].message
    assert "captures self" in diags[0].message
    assert "free function" in diags[0].hint


def test_jax004_side_effect_through_call_graph(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import time
            import jax

            def helper(x):
                time.sleep(1)
                return x

            def step(x):
                return helper(x)

            step_fn = jax.jit(step)
            """,
    }, select=["JAX004"])
    assert [d.rule for d in diags] == ["JAX004"]
    assert "time.sleep" in diags[0].message
    assert "mod.helper" in diags[0].message
    assert "jax.jit at mod.py" in diags[0].message


def test_jax004_global_store_in_traced_region(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import jax

            _steps = 0

            def step(x):
                global _steps
                _steps = _steps + 1
                return x

            step_fn = jax.jit(step)
            """,
    }, select=["JAX004"])
    assert [d.rule for d in diags] == ["JAX004"]
    assert "writes module global _steps" in diags[0].message


def test_jax004_pure_pipeline_is_clean(tmp_path):
    diags = _run_tree(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def helper(x):
                return jnp.tanh(x)

            def step(x):
                return helper(x) * 2

            step_fn = jax.jit(step)
            """,
    }, select=["JAX004"])
    assert diags == []


# ---------------------------------------------------------------------------
# incremental cache + --changed scoping + perf budget
# ---------------------------------------------------------------------------

_CACHE_TREE = {
    "a.py": "def f():\n    return 1\n",
    "b.py": "def g():\n    return 2\n",
    "c.py": "def h():\n    return 3\n",
}


def test_cache_hits_and_invalidation(tmp_path):
    _write_tree(tmp_path, _CACHE_TREE)
    cache = tmp_path / "cache.json"
    s1, s2, s3 = {}, {}, {}
    lint_core.run([str(tmp_path)], relative_to=tmp_path,
                  cache_path=cache, stats=s1)
    assert s1["analyzed"] == 3 and s1["cache_hits"] == 0
    lint_core.run([str(tmp_path)], relative_to=tmp_path,
                  cache_path=cache, stats=s2)
    assert s2["analyzed"] == 0 and s2["cache_hits"] == 3
    (tmp_path / "b.py").write_text("def g():\n    return 20\n")
    diags = lint_core.run([str(tmp_path)], relative_to=tmp_path,
                          cache_path=cache, stats=s3)
    assert s3["analyzed"] == 1 and s3["cache_hits"] == 2
    assert diags == []


def test_cached_run_still_reports_cross_file_violations(tmp_path):
    """Cache reuse must not lose project-scope findings: the facts are
    cached, the project pass always re-runs over the full index."""
    files = {
        "m1.py": "def a(registry):\n    registry.counter('dup')\n",
        "m2.py": "def b(registry):\n    registry.gauge('dup')\n",
    }
    _write_tree(tmp_path, files)
    cache = tmp_path / "cache.json"
    first = lint_core.run([str(tmp_path)], select=["CONTRACT002"],
                          relative_to=tmp_path, cache_path=cache)
    stats = {}
    second = lint_core.run([str(tmp_path)], select=["CONTRACT002"],
                           relative_to=tmp_path, cache_path=cache,
                           stats=stats)
    assert stats["cache_hits"] == 2
    assert [d.message for d in second] == [d.message for d in first]
    assert len(second) == 1


def test_changed_only_filters_reporting_not_analysis(tmp_path):
    """--changed scopes the report to touched files while the project
    pass still sees everything — a cross-file conflict whose *other*
    half moved is still attributed to its defining site."""
    files = {
        "m1.py": "def a(registry):\n    registry.counter('dup')\n",
        "m2.py": "def b(registry):\n    registry.gauge('dup')\n",
    }
    _write_tree(tmp_path, files)
    only_m2 = lint_core.run([str(tmp_path)], select=["CONTRACT002"],
                            relative_to=tmp_path,
                            changed_only={"m2.py"})
    assert [d.path for d in only_m2] == ["m2.py"]
    only_m1 = lint_core.run([str(tmp_path)], select=["CONTRACT002"],
                            relative_to=tmp_path,
                            changed_only={"m1.py"})
    assert only_m1 == []  # the diag anchors on m2.py, out of scope


def test_perf_budget_cold_full_tree():
    """A cold serial run over the whole tree (per-file pass + facts +
    every project checker) stays under the documented budget."""
    stats = {}
    lint_core.run([str(REPO / p) for p in TIER1_LINT_PATHS],
                  relative_to=REPO, jobs=1, stats=stats)
    assert stats["files"] >= 100
    assert stats["wall_s"] < PERF_BUDGET_S, (
        f"cold dctlint run took {stats['wall_s']:.2f}s over "
        f"{stats['files']} files (budget {PERF_BUDGET_S}s) — profile "
        f"the per-file pass before raising the budget")


def test_stats_summaries_cover_all_project_checkers():
    stats = {}
    lint_core.run([str(REPO / p) for p in TIER1_LINT_PATHS],
                  relative_to=REPO, stats=stats)
    assert set(stats["project_checkers"]) == {
        "CONC003", "CONC004", "CONTRACT001", "CONTRACT002",
        "CONTRACT003", "JAX004"}
    for rule in stats["project_checkers"]:
        assert rule in stats["summaries"], rule
