"""Master-scheduled multislice e2e: two fake 4-chip agents (= two v5e-4
slices) are reserved AS ONE GANG by the scheduler's slice-group path
(scheduler.cc find_fit n_slices branch), the rendezvous payload carries
slice assignments, and exec/trial.py builds the hybrid ICI×DCN mesh
(parallel/mesh.py make_multislice_mesh) — ZeRO-style fsdp inside each
slice's ICI, data parallelism across slices over DCN.

The reference has no multislice equivalent (SURVEY §7.7 — this is the
beat-the-reference axis); its closest analogue is the flat multi-node
gang, which tests/test_multi_agent_gang.py mirrors.
"""
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"

TRIAL_MODULE = '''
import jax
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial


class Trial(JaxTrial):
    def initial_params(self, rng):
        # a 2-process world, 4 chips per process = 8 global devices
        assert jax.process_count() == 2, jax.process_count()
        assert jax.device_count() == 8, jax.device_count()
        mesh = self.context.mesh
        shape = dict(mesh.shape)
        # dcn {dp: 2} x ici {fsdp: 4} from the experiment's mesh hparam
        assert shape["dp"] == 2 and shape["fsdp"] == 4, shape
        # dcn-major: dp index == slice == owning process, so dp collectives
        # cross DCN exactly once and fsdp collectives stay on-slice
        devs = mesh.devices.reshape(2, -1)
        for slice_id in range(2):
            procs = {d.process_index for d in devs[slice_id]}
            assert procs == {slice_id}, (slice_id, procs)
        return {"w": jnp.zeros((4, 4))}

    def optimizer(self):
        return optax.sgd(0.1)

    def loss(self, params, batch, rng):
        pred = batch @ params["w"]
        return jnp.mean((pred - 1.0) ** 2), {}

    def training_data(self):
        rng = np.random.RandomState(0)
        for _ in range(64):
            yield rng.randn(8, 4).astype(np.float32)

    def validation_data(self):
        return [np.ones((8, 4), np.float32)]

    @property
    def global_batch_size(self):
        return 8
'''


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("multislice")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    base_env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        # each agent process models ONE 4-chip slice
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "DCT_AGENT_SLOTS": "4",
        "DCT_AGENT_TOPOLOGY": "v5e-4",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=base_env,
    )
    agents = []
    for i in range(2):
        workdir = tmp / f"slice-{i}"
        workdir.mkdir()
        (workdir / "model_def.py").write_text(TRIAL_MODULE)
        agents.append(subprocess.Popen(
            [str(AGENT_BIN), "--master-port", str(port),
             "--id", f"slice-agent-{i}", "--work-dir", str(workdir)],
            cwd=str(workdir),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=base_env,
        ))

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if len(session.list_agents()) == 2:
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        for a in agents:
            a.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port}

    for a in agents:
        a.kill()
    master.kill()
    for a in agents:
        a.wait(timeout=10)
    master.wait(timeout=10)


def wait_for(predicate, timeout=300, interval=1.0, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def test_two_slice_gang_builds_ici_dcn_mesh(cluster):
    session = cluster["session"]
    exp = session.create_experiment({
        "name": "multislice2x4",
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "resources": {
            "slots_per_trial": 8,
            "topology": {"slices": 2, "slice_shape": "v5e-4"},
        },
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": {
            "mesh": {"ici": {"fsdp": 4}, "dcn": {"dp": 2}},
        },
        "max_restarts": 0,
    })

    def done():
        d = session.get_experiment(exp["id"])
        state = d["experiment"]["state"]
        if state == "ERRORED":
            trial = d["trials"][0]
            logs = session.task_logs(f"trial-{trial['id']}.0", limit=200)
            raise AssertionError(
                "multislice experiment ERRORED:\n" +
                "\n".join(l.get("log", "") for l in logs[-40:]))
        return d if state == "COMPLETED" else None

    detail = wait_for(done, desc="multislice completion")
    trial = detail["trials"][0]
    assert trial["state"] == "COMPLETED"

    # the rendezvous payload carried the slice-group assignment
    rdv = session.get(
        f"/api/v1/allocations/trial-{trial['id']}.0/rendezvous")
    assert rdv["world_size"] == 2
    assert rdv["n_slices"] == 2
    assert rdv["slice_ids"] == [0, 1]

    # validation metrics flowed (chief reported through the sharded step)
    metrics = session.trial_metrics(trial["id"])
    val = [m for m in metrics if m.get("group") == "validation"]
    assert val


def test_slice_group_waits_for_matching_topology(cluster):
    """A 4-slice request can never fit on two v5e-4 agents: it must stay
    QUEUED (all-or-nothing slice-group reservation), not half-schedule."""
    session = cluster["session"]
    exp = session.create_experiment({
        "name": "multislice-unfittable",
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 2}},
        "resources": {
            "slots_per_trial": 16,
            "topology": {"slices": 4, "slice_shape": "v5e-4"},
        },
        "hyperparameters": {},
        "max_restarts": 0,
    })
    time.sleep(3)  # several scheduler ticks
    d = session.get_experiment(exp["id"])
    trials = d["trials"]
    assert d["experiment"]["state"] in ("ACTIVE", "QUEUED", "RUNNING")
    assert all(t["state"] in ("QUEUED", "PENDING") for t in trials), trials
    session.kill_experiment(exp["id"])
