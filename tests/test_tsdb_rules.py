"""Time-series store + declarative alert rules (docs/observability.md
"Time series, queries & alert rules"): ring/staircase storage and
windowed reductions on simulated clocks, memory-budget eviction under a
long scrape soak, JSONL segment persistence across a "restart", the
scrape's stale-source skip, every rule kind's state machine, the stock
SLO burn rules re-deriving PR 13's verdict from stored series alone,
the master's /api/v1/timeseries and /api/v1/alerts routes, the
dct query / dct alerts / dct top CLI, and the TSDB-backed autoscaler
signal adapter."""
import json
import os
import time

import pytest

from determined_clone_tpu.api.inprocess import (
    InProcessMaster,
    MasterHTTPServer,
)
from determined_clone_tpu.cli.cli import main
from determined_clone_tpu.serving.autoscale import (
    AutoscalePolicy,
    Autoscaler,
    TimeSeriesSignals,
)
from determined_clone_tpu.telemetry.aggregate import (
    ClusterMetricsAggregator,
)
from determined_clone_tpu.telemetry.rules import (
    AlertRule,
    RuleEngine,
    format_alerts,
    stock_slo_rules,
)
from determined_clone_tpu.telemetry.slo import SLOEngine
from determined_clone_tpu.telemetry.tsdb import TimeSeriesDB

T0 = 1_000_000.0  # simulated wall-clock origin; nothing reads time.time


def sim_clock(start=T0):
    state = {"t": start}

    def clock():
        return state["t"]

    return state, clock


def make_tsdb(**kw):
    state, clock = sim_clock()
    kw.setdefault("clock", clock)
    return state, TimeSeriesDB(**kw)


REPLICA_TEXT = """# TYPE serving_queue_depth gauge
serving_queue_depth {queue}
# TYPE serving_tokens_per_sec gauge
serving_tokens_per_sec 120
# TYPE serving_requests_completed_total counter
serving_requests_completed_total {completed}
"""


# -- storage + query ---------------------------------------------------------


def test_record_and_windowed_query():
    state, db = make_tsdb()
    for i in range(10):
        db.record("q_depth", float(i), t=T0 + 5.0 * i)
    state["t"] = T0 + 45.0
    res = db.query("q_depth", window_s=20.0, reduce="raw")
    assert res["series"][0]["samples"] == [
        [T0 + 30.0, 6.0], [T0 + 35.0, 7.0],
        [T0 + 40.0, 8.0], [T0 + 45.0, 9.0]]
    assert db.query("q_depth", window_s=20.0,
                    reduce="avg")["series"][0]["value"] == 7.5
    assert db.query("q_depth", window_s=20.0,
                    reduce="max")["series"][0]["value"] == 9.0
    assert db.query("q_depth", window_s=20.0,
                    reduce="last")["series"][0]["value"] == 9.0
    with pytest.raises(ValueError):
        db.query("q_depth", reduce="median")


def test_label_subset_matching():
    _, db = make_tsdb()
    db.record("lat", 1.0, labels={"component": "r0", "quantile": "0.99"},
              t=T0)
    db.record("lat", 2.0, labels={"component": "r1", "quantile": "0.99"},
              t=T0)
    db.record("lat", 9.0, labels={"component": "r0", "quantile": "0.5"},
              t=T0)
    res = db.query("lat", {"quantile": "0.99"}, window_s=60.0,
                   reduce="last", now=T0)
    assert sorted(s["labels"]["component"] for s in res["series"]) == \
        ["r0", "r1"]
    only = db.query("lat", {"component": "r0", "quantile": "0.99"},
                    window_s=60.0, reduce="last", now=T0)["series"]
    assert [s["value"] for s in only] == [1.0]


def test_rate_tolerates_counter_reset():
    _, db = make_tsdb()
    # 100 → 150 → restart → 30: increase = 50 + 30, over 20s
    for i, v in enumerate([100.0, 150.0, 30.0]):
        db.record("steps_total", v, kind="counter", t=T0 + 10.0 * i)
    res = db.query("steps_total", window_s=60.0, reduce="increase",
                   now=T0 + 20.0)
    assert res["series"][0]["value"] == pytest.approx(80.0)
    rate = db.query("steps_total", window_s=60.0, reduce="rate",
                    now=T0 + 20.0)["series"][0]["value"]
    assert rate == pytest.approx(80.0 / 20.0)
    # a single point cannot produce a rate — None, never an error
    db.record("lone_total", 5.0, kind="counter", t=T0)
    assert db.query("lone_total", window_s=60.0, reduce="rate",
                    now=T0)["series"][0]["value"] is None


def test_staircase_keeps_long_windows_answerable():
    # fine ring of 10 samples, coarse steps of 60s: after 100 samples
    # every 10s, the fine ring covers only the newest 90s but coarse
    # points keep the older history queryable — and counter increase
    # across the tier boundary stays exact (coarse stores step-end
    # cumulative value, not an average).
    _, db = make_tsdb(capacity_per_series=10, coarse_step_s=60.0)
    for i in range(100):
        db.record("work_total", 7.0 * i, kind="counter", t=T0 + 10.0 * i)
    now = T0 + 990.0
    long_win = db.query("work_total", window_s=900.0, reduce="increase",
                        now=now)["series"][0]
    assert long_win["n"] > 10  # coarse points joined the fine ring
    samples = db.query("work_total", window_s=900.0, reduce="raw",
                       now=now)["series"][0]["samples"]
    assert samples == sorted(samples)  # coarse strictly before fine
    # increase over the full span is exact despite downsampling
    first_v, last_v = samples[0][1], samples[-1][1]
    assert long_win is not None
    assert db.query("work_total", window_s=900.0, reduce="increase",
                    now=now)["series"][0]["value"] == last_v - first_v
    # gauges read the step average from the coarse tier
    _, db2 = make_tsdb(capacity_per_series=10, coarse_step_s=60.0)
    for i in range(100):
        db2.record("g", 10.0, t=T0 + 10.0 * i)
    avg = db2.query("g", window_s=900.0, reduce="avg",
                    now=now)["series"][0]["value"]
    assert avg == pytest.approx(10.0)


def test_memory_budget_evicts_stalest_series_under_soak():
    state, db = make_tsdb(capacity_per_series=50,
                          memory_budget_bytes=40_000)
    # a long soak: 40 series, the first 20 stop reporting early on.
    # Eviction is lazy (budget-pressure-driven), so the dead pool drains
    # over time rather than instantly — by the end of the soak, sustained
    # pressure from the live series must have flushed every dead one.
    for tick in range(400):
        state["t"] = T0 + 5.0 * tick
        for s in range(40):
            if tick > 100 and s < 20:
                continue
            db.record(f"metric_{s}", float(tick))
    stats = db.stats()
    assert stats["within_budget"], stats
    assert stats["bytes_estimate"] <= stats["memory_budget_bytes"]
    assert stats["series_evicted_total"] > 0
    # the survivors are the fresh series, not the dead ones
    names = db.series_names()
    assert all(int(n.split("_")[1]) >= 20 for n in names), names
    assert stats["top_series_bytes"]  # accounting is per-series


def test_max_series_cap_evicts():
    _, db = make_tsdb(max_series=5)
    for s in range(8):
        db.record(f"m{s}", 1.0, t=T0 + s)
    assert len(db.series_names()) == 5
    assert "m7" in db.series_names()  # newest kept, stalest dropped


def test_from_dict_reads_config_units():
    db = TimeSeriesDB.from_dict({"memory_budget_mb": 2,
                                 "capacity_per_series": 16})
    assert db.memory_budget_bytes == 2 * 1024 * 1024
    assert db.capacity_per_series == 16
    with pytest.raises(ValueError):
        TimeSeriesDB(capacity_per_series=1)


# -- persistence -------------------------------------------------------------


def test_segments_replay_after_restart(tmp_path):
    d = str(tmp_path / "tsdb")
    state, clock = sim_clock()
    db = TimeSeriesDB(persist_dir=d, segment_scrapes=3, clock=clock)
    for i in range(7):
        state["t"] = T0 + 5.0 * i
        n = db.scrape_text("# TYPE steps_total counter\n"
                           f"steps_total {10 * i}\n"
                           "# TYPE q gauge\n"
                           f"q {i}\n")
        assert n == 2
    db.close()
    segs = [p for p in os.listdir(d) if p.endswith(".jsonl")]
    assert len(segs) >= 2  # rotated at segment_scrapes
    # torn tail from a kill -9 mid-write must not poison the replay
    with open(os.path.join(d, sorted(segs)[-1]), "a") as f:
        f.write('{"t": 123, "samples": [["x", {}')
    db2 = TimeSeriesDB(persist_dir=d, clock=clock)
    state["t"] = T0 + 30.0
    res = db2.query("steps_total", window_s=3600.0, reduce="increase")
    assert res["series"][0]["value"] == pytest.approx(60.0)
    assert db2.query("q", window_s=3600.0,
                     reduce="last")["series"][0]["value"] == 6.0
    db2.close()
    # replay=False starts empty but appends new segments after the old
    db3 = TimeSeriesDB(persist_dir=d, replay=False, clock=clock)
    assert db3.series_names() == []
    db3.close()


def test_segment_ring_bounds_disk(tmp_path):
    d = str(tmp_path / "ring")
    state, clock = sim_clock()
    db = TimeSeriesDB(persist_dir=d, segment_scrapes=2, max_segments=3,
                      clock=clock)
    for i in range(20):
        state["t"] = T0 + 5.0 * i
        db.scrape_text(f"g {i}\n")
    db.close()
    segs = [p for p in os.listdir(d) if p.endswith(".jsonl")]
    assert len(segs) <= 3


# -- scrape freshness --------------------------------------------------------


def test_scrape_skips_sources_that_did_not_reingest():
    state, clock = sim_clock()
    agg = ClusterMetricsAggregator(clock=clock)
    db = TimeSeriesDB(clock=clock)
    agg.ingest_prometheus_text("serving_replica_r0",
                               REPLICA_TEXT.format(queue=3, completed=10))
    db.scrape(agg)
    n0 = len(db.query("serving_queue_depth", window_s=3600.0,
                      reduce="raw")["series"][0]["samples"])
    assert n0 == 1
    # replica never re-ingests: its latest-wins snapshot must NOT be
    # re-stored as fresh observations on later ticks
    for tick in range(1, 5):
        state["t"] = T0 + 5.0 * tick
        db.scrape(agg)
    samples = db.query("serving_queue_depth", window_s=3600.0,
                       reduce="raw")["series"][0]["samples"]
    assert len(samples) == 1
    # master-computed rollups stay fresh every tick
    fleet = db.query("dct_fleet_queue_depth", window_s=3600.0,
                     reduce="raw")["series"][0]["samples"]
    assert len(fleet) == 5
    # the replica reports again → its series advance again
    state["t"] = T0 + 25.0
    agg.ingest_prometheus_text("serving_replica_r0",
                               REPLICA_TEXT.format(queue=4, completed=20))
    db.scrape(agg)
    samples = db.query("serving_queue_depth", window_s=3600.0,
                       reduce="raw")["series"][0]["samples"]
    assert len(samples) == 2 and samples[-1][1] == 4.0


# -- rules -------------------------------------------------------------------


def test_threshold_rule_state_machine_with_hold_down():
    state, db = make_tsdb()
    rule = AlertRule("deep", "threshold", series="q", window_s=30.0,
                     reduce="avg", op="gt", value=4.0, for_s=10.0)
    engine = RuleEngine([rule], clock=db._clock)

    def tick(value):
        db.record("q", value)
        snap = engine.evaluate(db)[0]
        state["t"] += 5.0
        return snap["state"]

    assert tick(1.0) == "inactive"
    assert tick(9.0) == "pending"       # breach starts the hold-down
    assert tick(9.0) == "pending"
    assert tick(9.0) == "firing"        # held >= for_s
    assert "q avg=" in rule.detail
    assert tick(0.0) == "firing"        # 30s avg still over 4
    assert tick(0.0) == "firing"
    state["t"] += 30.0                   # breach ages out of the window
    assert tick(0.0) == "resolved"
    assert tick(0.0) == "inactive"
    # for_s=0 fires on the same tick it breaches
    instant = AlertRule("now", "threshold", series="q", window_s=10.0,
                        reduce="last", op="gt", value=5.0)
    db.record("q", 9.0)
    assert instant.evaluate(db, state["t"])["state"] == "firing"


def test_rate_of_change_rule():
    state, db = make_tsdb()
    rule = AlertRule("hot", "rate_of_change", series="err_total",
                     window_s=60.0, op="gt", value=1.0)
    for i in range(4):  # 0.4/s: under threshold
        db.record("err_total", 2.0 * i, kind="counter", t=T0 + 5.0 * i)
    state["t"] = T0 + 15.0
    assert rule.evaluate(db, state["t"])["state"] == "inactive"
    for i in range(4, 8):  # 10/s burst
        db.record("err_total", 8.0 + 50.0 * (i - 3), kind="counter",
                  t=T0 + 5.0 * i)
    state["t"] = T0 + 35.0
    assert rule.evaluate(db, state["t"])["state"] == "firing"


def test_absence_rule_fires_on_missing_and_stale():
    state, db = make_tsdb()
    rule = AlertRule("gone", "absence", series="hb",
                     labels={"component": "r0"}, stale_s=20.0,
                     severity="page")
    # never stored at all → active immediately
    snap = rule.evaluate(db, T0)
    assert snap["state"] == "firing" and "absent" in snap["detail"]
    db.record("hb", 1.0, labels={"component": "r0"})
    assert rule.evaluate(db, state["t"])["state"] == "resolved"
    assert rule.evaluate(db, state["t"])["state"] == "inactive"
    state["t"] = T0 + 50.0               # sample now 50s old > 20s
    snap = rule.evaluate(db, state["t"])
    assert snap["state"] == "firing"
    assert 'hb{component="r0"}' in snap["detail"]


def test_burn_rate_counter_pair_needs_every_window():
    state, db = make_tsdb()
    rule = AlertRule("err-burn", "burn_rate",
                     bad_series="bad_total", total_series="all_total",
                     windows=[60.0, 600.0], threshold=2.0,
                     objective=0.9)
    # long history at 50% errors: bad_fraction/budget = 0.5/0.1 = 5x
    for i in range(121):
        t = T0 + 5.0 * i
        db.record("bad_total", 5.0 * i, kind="counter", t=t)
        db.record("all_total", 10.0 * i, kind="counter", t=t)
    state["t"] = T0 + 600.0
    snap = rule.evaluate(db, state["t"])
    assert snap["state"] == "firing"
    assert "burning" in snap["detail"]
    # errors stop: the short window cools first and un-fires the rule
    for i in range(121, 145):
        t = T0 + 5.0 * i
        db.record("bad_total", 600.0, kind="counter", t=t)
        db.record("all_total", 10.0 * i, kind="counter", t=t)
    state["t"] = T0 + 720.0
    snap = rule.evaluate(db, state["t"])
    assert snap["state"] == "resolved"
    assert "60" in snap["detail"]  # the cooled window is named


def test_stock_slo_rules_reproduce_fast_burn_from_stored_series():
    # PR 13's fast-burn scenario (tests/test_slo.py), but the verdict is
    # re-derived by the rule engine from the scraped dct_slo_burn_rate
    # series alone — no SLOEngine in the loop at evaluation time.
    state, clock = sim_clock()
    master = InProcessMaster(clock=clock)
    master.enable_timeseries({"stock_slo_rules": True})
    slo = SLOEngine(availability_objective=0.999, clock=clock)
    fast, slow = stock_slo_rules(objective="availability")
    master.rules.add(fast)
    master.rules.add(slow)
    # transient spike: 5m burns, 1h dilutes → no fast burn
    slo.record_request(ok=False, n=20, t=T0)
    slo.record_request(ok=True, n=980, t=T0)
    slo.record_request(ok=True, n=100_000, t=T0 - 1800.0)
    slo.publish(master.aggregator.registry)
    master.scrape_tick()
    assert fast.state == "inactive"
    # sustained errors across the hour → both fast windows burn
    state["t"] = T0 + 5.0
    for tick in range(12):
        slo.record_request(ok=False, n=5000, t=T0 - tick * 300.0)
    slo.publish(master.aggregator.registry)
    master.scrape_tick()
    assert slo.evaluate(now=state["t"])["verdict"] == "fast_burn"
    assert fast.state == "firing"
    assert "slo-availability-fast-burn" in master.rules.firing()
    payload = master.rules.alerts()
    assert "slo-availability-fast-burn" in payload["firing"]
    assert "burning" in format_alerts(payload)
    # firing state is itself exported as a scrapeable gauge
    assert ('dct_alert_firing{rule="slo-availability-fast-burn"'
            in master.aggregator.registry.dump())
    master.stop_scraper()


def test_rule_validation_rejects_bad_configs():
    with pytest.raises(ValueError):
        AlertRule("x", "nope")
    with pytest.raises(ValueError):
        AlertRule("x", "threshold", series="s")  # no value
    with pytest.raises(ValueError):
        AlertRule("x", "absence", series="s", stale_s=0.0)
    with pytest.raises(ValueError):
        AlertRule("x", "burn_rate", windows=["5m"])  # no threshold
    with pytest.raises(ValueError):
        AlertRule("x", "burn_rate", bad_series="b", windows=[60.0],
                  threshold=1.0)  # no total/objective
    with pytest.raises(ValueError):
        AlertRule.from_dict({"name": "x", "kind": "threshold",
                             "series": "s", "value": 1.0, "bogus": 2})
    with pytest.raises(ValueError):
        RuleEngine.from_config([
            {"name": "d", "kind": "absence", "series": "s",
             "stale_s": 5.0},
            {"name": "d", "kind": "absence", "series": "s",
             "stale_s": 5.0}])


# -- master routes + scraper lifecycle ---------------------------------------


def feed_fleet(master, state, ticks=6):
    """Drive a synthetic two-replica fleet through the aggregator: the
    rollup dct_fleet_* families the scrape stores are computed exactly
    as they would be for a live ServingFleet's shipped telemetry."""
    for tick in range(ticks):
        for r in range(2):
            master.aggregator.ingest_prometheus_text(
                f"serving_replica_r{r}",
                REPLICA_TEXT.format(queue=4 + tick, completed=50 * tick))
        master.scrape_tick()
        state["t"] += 5.0


def test_master_timeseries_and_alert_routes():
    state, clock = sim_clock()
    master = InProcessMaster(clock=clock)
    master.enable_timeseries({
        "timeseries": {"capacity_per_series": 64},
        "rules": [{"name": "deep", "kind": "threshold",
                   "series": "dct_fleet_queue_depth", "window_s": 60.0,
                   "reduce": "avg", "op": "gt", "value": 5.0}],
    })
    feed_fleet(master, state)
    # list view
    st, payload, _ = master.handle("GET", "/api/v1/timeseries", None)
    assert st == 200
    assert "dct_fleet_requests_completed" in payload["series"]
    assert payload["stats"]["within_budget"]
    # windowed rate over a fleet counter is non-empty and exact:
    # completed climbs 100/tick across 2 replicas, one tick per 5s
    st, payload, _ = master.handle(
        "GET", "/api/v1/timeseries?name=dct_fleet_requests_completed"
               "&reduce=rate&window=60", None)
    assert st == 200
    assert payload["series"][0]["value"] == pytest.approx(20.0)
    # label filtering + quantile reduce
    st, payload, _ = master.handle(
        "GET", "/api/v1/timeseries?name=serving_queue_depth"
               "&labels=component%3Dserving_replica_r0&reduce=quantile"
               "&q=0.5&window=600", None)
    assert st == 200
    assert len(payload["series"]) == 1
    assert payload["series"][0]["value"] == pytest.approx(6.5)
    # alerts route sees the threshold rule firing (queue avg climbs > 5)
    st, payload, _ = master.handle("GET", "/api/v1/alerts", None)
    assert st == 200
    assert payload["firing"] == ["deep"]
    # malformed requests are 400s, not crashes
    st, _, _ = master.handle(
        "GET", "/api/v1/timeseries?name=x&reduce=median", None)
    assert st == 400
    st, _, _ = master.handle(
        "GET", "/api/v1/timeseries?name=x&labels=oops", None)
    assert st == 400
    master.stop_scraper()


def test_routes_404_when_not_enabled():
    master = InProcessMaster()
    st, payload, _ = master.handle("GET", "/api/v1/timeseries", None)
    assert st == 404 and "not enabled" in payload["error"]
    st, payload, _ = master.handle("GET", "/api/v1/alerts", None)
    assert st == 404 and "not enabled" in payload["error"]


def test_scraper_thread_runs_and_stops():
    master = InProcessMaster()
    master.enable_timeseries({})
    master.aggregator.ingest_prometheus_text("serving_replica_r0",
                                             REPLICA_TEXT.format(
                                                 queue=1, completed=1))
    master.start_scraper(period_s=0.02)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if master.tsdb.stats()["scrapes_total"] >= 2:
            break
        time.sleep(0.01)
    assert master.tsdb.stats()["scrapes_total"] >= 2
    master.stop_scraper()  # conftest fails the test if the thread leaks


# -- CLI ---------------------------------------------------------------------


@pytest.fixture()
def live_master():
    state, clock = sim_clock()
    master = InProcessMaster(clock=clock)
    master.enable_timeseries({
        "rules": [{"name": "deep", "kind": "threshold",
                   "series": "dct_fleet_queue_depth", "window_s": 60.0,
                   "reduce": "avg", "op": "gt", "value": 5.0}],
    })
    feed_fleet(master, state)
    with MasterHTTPServer(master, 0) as srv:
        yield f"127.0.0.1:{srv.port}"
    master.stop_scraper()


def test_cli_query(live_master, capsys):
    assert main(["-m", live_master, "query"]) == 0
    out = capsys.readouterr().out
    assert "series" in out and "dct_fleet_queue_depth" in out
    assert main(["-m", live_master, "query",
                 "dct_fleet_requests_completed", "--reduce", "rate",
                 "--window", "60"]) == 0
    out = capsys.readouterr().out
    assert "rate over 60s: 20" in out
    assert main(["-m", live_master, "query", "dct_fleet_queue_depth",
                 "--reduce", "last", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["series"][0]["value"] == 18.0  # 2 replicas x queue 9
    assert main(["-m", live_master, "query", "no_such_series"]) == 1


def test_cli_alerts(live_master, capsys):
    assert main(["-m", live_master, "alerts"]) == 0
    out = capsys.readouterr().out
    assert "1 firing" in out and "deep" in out
    assert main(["-m", live_master, "alerts", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["firing"] == ["deep"]


def test_cli_top_once(live_master, capsys):
    assert main(["-m", live_master, "top", "--once"]) == 0
    out = capsys.readouterr().out
    assert "dct top" in out
    assert "tokens/s" in out
    assert "serving_replica_r0" in out   # per-replica lane
    assert "ALERTS FIRING: deep" in out


def test_cli_against_plain_master_says_not_enabled(capsys):
    master = InProcessMaster()
    with MasterHTTPServer(master, 0) as srv:
        addr = f"127.0.0.1:{srv.port}"
        assert main(["-m", addr, "query"]) == 1
        assert main(["-m", addr, "alerts"]) == 1
        assert main(["-m", addr, "top", "--once"]) == 1
    err = capsys.readouterr().err
    assert "not enabled" in err


# -- autoscaler adapter ------------------------------------------------------


class _FakeFleet:
    def __init__(self):
        self.grown = 0

    def scale_up(self, n):
        self.grown += n

    def scale_down(self, n):
        raise AssertionError("should not shrink in this scenario")


def test_timeseries_signals_drive_autoscaler():
    state, clock = sim_clock()
    master = InProcessMaster(clock=clock)
    master.enable_timeseries({})
    feed_fleet(master, state, ticks=8)  # queue climbs to 22 fleet-wide
    signals = TimeSeriesSignals(master.tsdb, window_s=30.0)
    fleet = _FakeFleet()
    scaler = Autoscaler(
        fleet, AutoscalePolicy(queue_high=8.0, breach_ticks=2,
                               max_replicas=4),
        signals_fn=signals)
    s = signals()
    assert s.healthy == 2 and s.queue_depth > 16
    assert scaler.tick() == "hold"       # first breach tick
    assert scaler.tick() == "grow"       # sustained → grow
    assert fleet.grown == 1
    master.stop_scraper()


def test_rule_override_forces_congestion():
    state, clock = sim_clock()
    master = InProcessMaster(clock=clock)
    master.enable_timeseries({
        "rules": [{"name": "congested", "kind": "threshold",
                   "series": "dct_fleet_queue_depth", "window_s": 60.0,
                   "reduce": "avg", "op": "gt", "value": 5.0}],
    })
    feed_fleet(master, state)
    signals = TimeSeriesSignals(master.tsdb, rules=master.rules,
                                congestion_rules=["congested"])
    assert signals().p99_s == float("inf")
    master.stop_scraper()
