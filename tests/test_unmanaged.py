"""Unmanaged trials: off-cluster runs reporting in to a real C++ master.

≈ the reference's unmanaged experiments (core_v2/_unmanaged.py,
core/_heartbeat.py:15, core/_log_shipper.py:18): no agent is involved —
the "trial" runs inside this test process and the master records it.
"""
import logging
import time

import pytest

from tests.test_platform import build_binaries, start_master

from determined_clone_tpu import core


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("unmanaged")
    proc, session, port = start_master(tmp)
    yield {"session": session, "port": port, "proc": proc}
    proc.kill()
    proc.wait(timeout=10)


def test_unmanaged_trial_reports_in(master, tmp_path):
    session = master["session"]
    with core.init_unmanaged(
        master_port=master["port"],
        name="laptop-run",
        config={"searcher": {"name": "single", "metric": "loss",
                             "max_length": {"batches": 10}},
                "checkpoint_storage": {"type": "shared_fs",
                                       "host_path": str(tmp_path)}},
        heartbeat_interval=0.2,
    ) as ctx:
        exp_id = ctx.experiment_id
        # visible as a live experiment, held by no scheduler
        exp = session.get_experiment(exp_id)
        assert exp["experiment"]["state"] == "RUNNING"
        assert exp["trials"][0]["state"] == "RUNNING"
        assert all(j["id"] != ctx.allocation_id for j in session.job_queue())

        for step in range(1, 4):
            ctx.train.report_training_metrics(
                steps_completed=step, metrics={"loss": 1.0 / step})
        logging.getLogger("unmanaged-test").warning("hello from off-cluster")
        assert ctx.preempt.should_preempt() is False

    # clean exit completes trial + experiment
    exp = session.get_experiment(exp_id)
    assert exp["experiment"]["state"] == "COMPLETED"
    assert exp["trials"][0]["state"] == "COMPLETED"

    metrics = session.trial_metrics(exp["trials"][0]["id"])
    assert any(m["metrics"]["loss"] == 1.0 for m in metrics)

    logs = session.task_logs(f"unmanaged-{exp['trials'][0]['id']}.0")
    assert any("hello from off-cluster" in str(line["log"]) for line in logs)


def test_unmanaged_failure_marks_errored(master):
    session = master["session"]
    with pytest.raises(RuntimeError, match="boom"):
        with core.init_unmanaged(master_port=master["port"],
                                 name="failing-run",
                                 heartbeat_interval=0.2) as ctx:
            exp_id = ctx.experiment_id
            raise RuntimeError("boom")
    exp = session.get_experiment(exp_id)
    assert exp["experiment"]["state"] == "ERRORED"
    assert "boom" in exp["trials"][0]["error"]


def test_dead_client_reaped_by_watchdog(tmp_path):
    """A SIGKILLed client must not leave a RUNNING experiment forever."""
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    proc, session, port = start_master(tmp_path, "--unmanaged-timeout", "1")
    try:
        # register an unmanaged trial and then never heartbeat (the raw API
        # stands in for a client that got SIGKILLed immediately)
        resp = session.post("/api/v1/experiments", {"config": {
            "name": "dead-client", "entrypoint": "unmanaged",
            "unmanaged": True,
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 1}}}})
        exp_id = resp["experiment"]["id"]
        deadline = time.time() + 15
        while time.time() < deadline:
            exp = session.get_experiment(exp_id)
            if exp["experiment"]["state"] == "ERRORED":
                break
            time.sleep(0.3)
        else:
            pytest.fail("watchdog never errored the silent unmanaged trial")
        assert "heartbeat lost" in exp["trials"][0]["error"]
        # the watchdog must not restart-loop: state and restart count are
        # stable after further watchdog periods
        restarts = exp["trials"][0]["restarts"]
        assert restarts <= 1
        time.sleep(2.5)
        exp = session.get_experiment(exp_id)
        assert exp["experiment"]["state"] == "ERRORED"
        assert exp["trials"][0]["restarts"] == restarts
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_unmanaged_heartbeat_requires_token_under_auth(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    proc, session, port = start_master(tmp_path, "--auth-required")
    try:
        user = session.login("admin")
        with core.init_unmanaged(master_port=port, name="authed-run",
                                 heartbeat_interval=0.2,
                                 token=session.token) as ctx:
            trial_id = ctx.trial_id
            # anonymous mutation is rejected; the shipped data-plane token
            # (used internally by the heartbeat thread) is what authorizes
            from determined_clone_tpu.api.client import (
                MasterError, MasterSession)

            anon = MasterSession("127.0.0.1", port)
            anon.token = None
            with pytest.raises(MasterError) as err:
                anon.post(f"/api/v1/trials/{trial_id}/heartbeat",
                          {"state": "ERRORED"})
            assert err.value.status == 401
        assert user["username"] == "admin"
        assert session.get_experiment(ctx.experiment_id)["experiment"][
            "state"] == "COMPLETED"
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_unmanaged_preemption_flag(master):
    session = master["session"]
    with core.init_unmanaged(master_port=master["port"], name="preempt-run",
                             heartbeat_interval=0.1) as ctx:
        session.kill_experiment(ctx.experiment_id)
        # the next heartbeat observes the preempt flag; the data-plane
        # preempt long-poll sees it too
        deadline = time.time() + 10
        while time.time() < deadline:
            if session.get(
                    f"/api/v1/allocations/{ctx.allocation_id}/preempt"
            )["preempt"]:
                break
            time.sleep(0.1)
        else:
            pytest.fail("preempt flag never raised for unmanaged trial")
