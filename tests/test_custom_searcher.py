"""Custom searcher: user SearchMethods driving experiments via the master's
event queue (RemoteSearchRunner) and the local orchestrator
(LocalSearchRunner).

≈ the reference's custom-search stack: master/pkg/searcher/custom_search.go
(event queue), harness/determined/searcher/_search_runner.py (runners),
e2e_tests custom-searcher flows.
"""
import os
import subprocess
import threading
import time
from pathlib import Path
from typing import List

import pytest

from determined_clone_tpu.searcher import (
    Close,
    Create,
    LocalSearchRunner,
    RemoteSearchRunner,
    SearchMethod,
    Shutdown,
    ValidateAfter,
    build_method,
)

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"

TRIAL_MODULE = '''
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial


class Trial(JaxTrial):
    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(self.context.get_hparam("lr", 0.2))

    def loss(self, params, batch, rng):
        return (params["w"] - 2.0) ** 2, {}

    def training_data(self):
        for _ in range(64):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2
'''


class TwoTrialMethod(SearchMethod):
    """Create two trials with fixed lrs, one validation round each, then
    close both and shut down. Small but exercises every event type's path."""

    def __init__(self):  # noqa: D107 - no config needed
        self.validated: List[int] = []
        self.created: List[int] = []

    def initial_operations(self):
        return [
            Create(-1, {"lr": 0.1}),
            Create(-1, {"lr": 0.3}),
        ]

    def on_trial_created(self, request_id):
        self.created.append(request_id)
        return [ValidateAfter(request_id, 4)]

    def on_validation_completed(self, request_id, metric, units):
        self.validated.append(request_id)
        ops = [Close(request_id)]
        if len(self.validated) == 2:
            ops.append(Shutdown())
        return ops

    def progress(self):
        return len(self.validated) / 2.0


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("customsearch")
    workdir = tmp / "agent-work"
    workdir.mkdir()
    (workdir / "model_def.py").write_text(TRIAL_MODULE)

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "2",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "cs-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


def test_remote_search_runner_end_to_end(cluster):
    session = cluster["session"]
    method = TwoTrialMethod()
    runner = RemoteSearchRunner(method, session, poll_interval=0.2)
    config = {
        "name": "custom-e2e",
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "custom", "metric": "loss",
                     "max_length": {"batches": 4}},
        "resources": {"slots_per_trial": 1},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": {"lr": 0.2},
        "max_restarts": 1,
    }
    done = {}

    def drive():
        done["exp_id"] = runner.run(config)

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    t.join(timeout=300)
    assert not t.is_alive(), "runner did not converge"

    detail = session.get_experiment(done["exp_id"])
    assert detail["experiment"]["state"] == "COMPLETED"
    trials = detail["trials"]
    assert len(trials) == 2
    assert {t["hparams"]["lr"] for t in trials} == {0.1, 0.3}
    assert all(t["state"] == "COMPLETED" for t in trials)
    assert all(t["units_done"] >= 4 for t in trials)
    assert sorted(method.validated) == sorted(method.created)
    # the method's progress reached the master (GET experiment detail)
    assert detail.get("progress") == 1.0


def test_events_endpoint_rejects_builtin_searcher(cluster):
    from determined_clone_tpu.api.client import MasterError

    session = cluster["session"]
    exp = session.create_experiment({
        "name": "builtin",
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 100000}},
        "resources": {"slots_per_trial": 1},
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": {"lr": 0.2},
    })
    with pytest.raises(MasterError) as err:
        session.request(
            "GET", f"/api/v1/experiments/{exp['id']}/searcher/events")
    assert err.value.status == 400
    session.kill_experiment(exp["id"])


class PickBestLocal(SearchMethod):
    """Three fixed-lr trials, single validation, close all, shutdown."""

    def __init__(self):
        self.lrs = [0.5, 0.2, 0.8]
        self.n_done = 0

    def initial_operations(self):
        return [Create(-1, {"lr": lr}) for lr in self.lrs]

    def on_trial_created(self, request_id):
        return [ValidateAfter(request_id, 2)]

    def on_validation_completed(self, request_id, metric, units):
        self.n_done += 1
        ops = [Close(request_id)]
        if self.n_done == len(self.lrs):
            ops.append(Shutdown())
        return ops

    def progress(self):
        return self.n_done / len(self.lrs)


def test_local_search_runner(tmp_path):
    import jax

    from determined_clone_tpu.config import ExperimentConfig
    from determined_clone_tpu.parallel import MeshSpec, make_mesh
    from tests.test_experiment_runner import QuadraticTrial

    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "custom", "metric": "loss",
                     "max_length": {"batches": 2}},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs", "host_path": str(tmp_path)},
        "hyperparameters": {"lr": 0.5},
        "max_restarts": 1,
    })
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    result = LocalSearchRunner(PickBestLocal()).run(
        cfg, QuadraticTrial, storage_path=str(tmp_path), mesh=mesh)
    assert result.shutdown
    assert result.n_trials == 3
    assert all(t.state == "completed" for t in result.trials.values())
    # loss floor = lr → best is the smallest lr
    assert result.best_trial.hparams["lr"] == 0.2


def test_build_method_custom_points_to_runners():
    from determined_clone_tpu.config.experiment import SearcherConfig
    from determined_clone_tpu.config.hyperparameters import (
        HyperparameterSpace,
    )

    cfg = SearcherConfig.from_dict({"name": "custom", "metric": "loss"})
    with pytest.raises(ValueError) as err:
        build_method(cfg, HyperparameterSpace({}))
    assert "RemoteSearchRunner" in str(err.value)
