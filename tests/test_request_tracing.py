"""Per-request distributed tracing (docs/observability.md "Request
tracing & SLOs"): the request archive's tail-sampling + kill -9
durability, the router's exclusion/dispatch observability, and the
acceptance path — one request through the fleet HTTP front door with an
injected replica failure stitches into a single valid Chrome trace
(front door → router dispatch + redispatch → both replica legs) under
one trace_id, retrievable via ``dct trace request <id>``. The slow
chaos test hard-kills a replica process mid-request and proves the
archive recovers the partial leg."""
import json
import os
import subprocess
import sys
import time
import urllib.request

import jax
import pytest

from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving import (
    BucketSpec,
    KVCacheConfig,
    LeastLoadedRouter,
    ServerOverloaded,
    ServingFleet,
)
from determined_clone_tpu.serving.http import FleetHTTPServer
from determined_clone_tpu.telemetry import (
    MetricsRegistry,
    RequestArchive,
    Tracer,
    request_archive_summary,
    request_chrome_trace,
    request_records,
    validate_chrome_trace,
)
from determined_clone_tpu.telemetry.aggregate import ClusterMetricsAggregator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32, n_heads=4,
                    d_ff=64, max_seq_len=48, remat=False,
                    attention_impl="mha")
BUCKETS = BucketSpec.build(2, 8)
CACHE = KVCacheConfig(num_blocks=16, block_size=8)
PROMPT = [1, 2, 3]


@pytest.fixture(scope="module")
def params():
    return gpt.init(jax.random.PRNGKey(0), CFG)


def make_fleet(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("cache", CACHE)
    kw.setdefault("warmup", False)
    return ServingFleet(params, CFG, **kw)


# ---------------------------------------------------------------------------
# Request archive: tail sampling + durability (no engines)
# ---------------------------------------------------------------------------


def archive_span(archive, tracer, request_id, name="request_admitted",
                 **extra):
    tracer.record_span(name, time.perf_counter(), 0.001,
                       request_id=request_id, **extra)
    return archive


def test_archive_keeps_errors_slowest_and_samples(tmp_path):
    archive = RequestArchive(str(tmp_path), slowest_n=2, sample_rate=0.0)
    tracer = Tracer(enabled=True, process_name="frontdoor")
    tracer.add_sink(archive.sink_for(tracer))
    for rid, lat in (("r-big", 0.5), ("r-mid", 0.2), ("r-small", 0.1)):
        archive_span(archive, tracer, rid, trace_id=f"t-{rid}")
    # spans without a request_id never reach the archive
    tracer.record_span("warmup", time.perf_counter(), 0.001)

    assert archive.note_result("r-big", ok=True, latency_s=0.5) == "slowest"
    assert archive.note_result("r-mid", ok=True, latency_s=0.2) == "slowest"
    # under the slowest-N floor and not sampled → let go
    assert archive.note_result("r-small", ok=True, latency_s=0.1) is None
    # errors are always retained, latency or not
    archive_span(archive, tracer, "r-err")
    assert archive.note_result("r-err", ok=False,
                               error="ServerOverloaded") == "error"
    assert archive.retained_count == 3

    summary = request_archive_summary(str(tmp_path))
    assert summary["live_spans"] == 4  # every request-tagged span, kept or not
    assert "r-small" in summary["live_request_ids"]
    reasons = {r["request_id"]: r["reason"] for r in summary["retained"]}
    assert reasons == {"r-big": "slowest", "r-mid": "slowest",
                       "r-err": "error"}
    archive.close()


def test_archive_sample_rate_keeps_the_rest(tmp_path):
    archive = RequestArchive(str(tmp_path), slowest_n=0, sample_rate=1.0)
    tracer = Tracer(enabled=True, process_name="frontdoor")
    tracer.add_sink(archive.sink_for(tracer))
    archive_span(archive, tracer, "r-fast")
    assert archive.note_result("r-fast", ok=True,
                               latency_s=0.001) == "sampled"
    archive.close()


def test_archive_live_ring_is_durable_before_close(tmp_path):
    """Write-through property: the span is on disk the moment the tracer
    finishes it — no close(), no flush — so a kill -9 mid-request leaves
    the partial leg readable (the chaos contract, proven cross-process
    by the slow test below)."""
    archive = RequestArchive(str(tmp_path))
    tracer = Tracer(enabled=True, process_name="serving_replica_r1")
    tracer.add_sink(archive.sink_for(tracer))
    archive_span(archive, tracer, "r-crash", trace_id="t-crash")
    recs = request_records(str(tmp_path), "r-crash")
    assert len(recs) == 1
    assert recs[0]["process"] == "serving_replica_r1"
    assert recs[0]["trace_id"] == "t-crash"
    archive.close()


def test_request_records_dedup_and_chrome_trace(tmp_path):
    archive = RequestArchive(str(tmp_path), slowest_n=4)
    fd = Tracer(enabled=True, process_name="frontdoor")
    fd.add_sink(archive.sink_for(fd))
    rep = Tracer(enabled=True, process_name="serving_replica_r1")
    rep.add_sink(archive.sink_for(rep))
    archive_span(archive, rep, "r-1", name="request_admitted",
                 trace_id="t-1")
    archive_span(archive, fd, "r-1", name="frontdoor_request",
                 trace_id="t-1")
    archive.note_result("r-1", ok=True, latency_s=0.2)  # retained bundle
    archive.close()
    # each span now exists in the live ring AND the retained bundle;
    # request_records must not double-count
    recs = request_records(str(tmp_path), "r-1")
    assert len(recs) == 2
    trace = request_chrome_trace(str(tmp_path), "r-1")
    assert validate_chrome_trace(trace) == []
    assert trace["otherData"]["trace_ids"] == ["t-1"]
    assert set(trace["otherData"]["processes"]) == {
        "frontdoor", "serving_replica_r1"}
    with pytest.raises(KeyError):
        request_chrome_trace(str(tmp_path), "r-unknown")


# ---------------------------------------------------------------------------
# Router observability (fake ports)
# ---------------------------------------------------------------------------


class FakePort:
    def __init__(self, rid, fail=None):
        self.replica_id = rid
        self.fail = fail
        self.kwargs = None

    def admitting(self):
        return True

    def load(self):
        return (0, -16)

    def submit(self, prompt, max_new_tokens, **kwargs):
        if self.fail is not None:
            raise self.fail
        self.kwargs = kwargs

        class Handle:
            def result(self, timeout=None):
                return None

        return Handle()


def _gauge(reg, name):
    return reg.gauge(name, "").value


def test_router_exclusion_gauge_and_per_replica_dispatch():
    clock = [0.0]
    reg = MetricsRegistry()
    router = LeastLoadedRouter(reg, exclude_cooldown_s=1.0,
                               clock=lambda: clock[0])
    bad = FakePort("a", fail=ServerOverloaded("queue full"))
    good = FakePort("b")
    router.add(bad)
    router.add(good)
    handle = router.submit(PROMPT, 4, request_id="r-1", trace_id="t-1")
    assert handle.replica_id == "b"
    # the failing replica sits in cooldown, visible as a gauge
    assert router.excluded() == ["a"]
    assert _gauge(reg, "router_excluded_replicas") == 1.0
    # per-replica dispatch counter: only the replica that served it
    text = reg.dump()
    assert 'router_dispatch_total{replica="b"} 1' in text
    assert 'router_dispatch_total{replica="a"}' not in text
    # the minted trace identity rode the failover hop into the replica
    assert good.kwargs["trace_id"] == "t-1"
    assert good.kwargs["request_id"] == "r-1"
    # cooldown expiry clears the gauge
    clock[0] += 2.0
    assert router.excluded() == []
    assert _gauge(reg, "router_excluded_replicas") == 0.0


def test_router_records_dispatch_and_redispatch_spans():
    tracer = Tracer(enabled=True, process_name="router")
    router = LeastLoadedRouter(MetricsRegistry(), tracer=tracer)
    router.add(FakePort("a", fail=ConnectionError("replica died")))
    router.add(FakePort("b"))
    router.submit(PROMPT, 4, request_id="r-1", trace_id="t-1")
    names = [e["name"] for e in tracer.events()]
    assert "router_redispatch" in names
    assert "router_dispatch" in names
    dispatch = next(e for e in tracer.events()
                    if e["name"] == "router_dispatch")
    assert dispatch["args"]["replica"] == "b"
    assert dispatch["args"]["attempts"] == 2
    assert dispatch["args"]["trace_id"] == "t-1"


def test_router_without_trace_id_spares_minimal_ports():
    """Fakes that predate tracing (no trace_id kwarg) keep working: the
    kwarg is only forwarded when the front door minted one."""

    class LegacyPort:
        replica_id = "legacy"

        def admitting(self):
            return True

        def load(self):
            return (0, 0)

        def submit(self, prompt, max_new_tokens, *, eos_token_id=None,
                   request_id=None):
            class Handle:
                def result(self, timeout=None):
                    return None

            return Handle()

    router = LeastLoadedRouter()
    router.add(LegacyPort())
    assert router.submit(PROMPT, 4) is not None


# ---------------------------------------------------------------------------
# The acceptance path: HTTP front door, injected failure, one trace
# ---------------------------------------------------------------------------


def test_traced_request_with_failover_stitches_one_trace(params, tmp_path):
    archive_dir = str(tmp_path / "archive")
    agg = ClusterMetricsAggregator()
    fleet = make_fleet(params, aggregator=agg, tracing=True,
                       archive_dir=archive_dir)
    try:
        fleet.scale_up(2)
        rep_a, rep_b = fleet.replicas()

        # inject: replica A accepts the work, then the connection "drops"
        # — the router must fail over while A's partial leg keeps tracing
        orig_submit = rep_a.submit

        def flaky_submit(prompt, max_new_tokens=16, **kw):
            orig_submit(prompt, max_new_tokens, **kw)
            raise ConnectionError("link dropped after enqueue")

        rep_a.submit = flaky_submit
        with FleetHTTPServer(fleet) as server:
            body = json.dumps({"prompt": PROMPT,
                               "max_new_tokens": 6}).encode()
            req = urllib.request.Request(
                f"{server.url}/v1/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = json.loads(resp.read().decode())
            rep_a.submit = orig_submit
            rid, tid = out["request_id"], out["trace_id"]
            assert rid.startswith("req-") and tid.startswith("trace-")
            assert out["replica_id"] == rep_b.replica_id
            assert len(out["tokens"]) == 6

            # both engines saw the request (A kept the enqueued copy);
            # let them go idle so every span of both legs is recorded
            for rep in fleet.replicas():
                rep.engine.wait_idle(60.0)
            fleet.sample_telemetry()

            # the SLO surface saw the request
            with urllib.request.urlopen(f"{server.url}/v1/slo",
                                        timeout=10) as resp:
                slo = json.loads(resp.read().decode())["slo"]
            assert slo["verdict"] in ("ok", "slow_burn", "fast_burn")
            with urllib.request.urlopen(f"{server.url}/v1/fleet",
                                        timeout=10) as resp:
                assert json.loads(
                    resp.read().decode())["slo_verdict"] is not None

        # ONE stitched trace: front door + router decision (incl. the
        # redispatch) + both replica legs, all under a single trace_id
        trace = request_chrome_trace(archive_dir, rid)
        assert validate_chrome_trace(trace) == []
        assert trace["otherData"]["trace_ids"] == [tid]
        processes = set(trace["otherData"]["processes"])
        assert {"frontdoor", "router",
                f"serving_replica_{rep_a.replica_id}",
                f"serving_replica_{rep_b.replica_id}"} <= processes
        names = {e["name"] for e in trace["traceEvents"]
                 if e["ph"] in ("X", "i")}
        assert {"frontdoor_request", "router_dispatch",
                "router_redispatch", "request_admitted",
                "request_retired"} <= names

        # the aggregator got the same lanes via sample_telemetry
        agg_procs = {s.get("process") for s in agg.spans()}
        assert {"frontdoor", "router"} <= agg_procs

        # a completed request is the fleet's slowest so far → exemplar
        roll = agg.serving_fleet_rollup()
        assert roll["slowest_request"]["request_id"] == rid

        # the operator path: dct trace request <id>
        from determined_clone_tpu.cli.cli import main as cli_main
        out_path = tmp_path / "request-trace.json"
        rc = cli_main(["trace", "request", rid,
                       "--archive-dir", archive_dir, "-o", str(out_path)])
        assert rc == 0
        written = json.loads(out_path.read_text())
        assert validate_chrome_trace(written) == []
        assert written["otherData"]["trace_ids"] == [tid]
        # and an unknown id fails with the archive's inventory, not a stack
        assert cli_main(["trace", "request", "req-nope",
                         "--archive-dir", archive_dir,
                         "-o", str(out_path)]) == 1
    finally:
        fleet.close()


def test_disabled_telemetry_means_zero_tracing_work(params, tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("DCT_TELEMETRY_DISABLED", "1")
    fleet = make_fleet(params, archive_dir=str(tmp_path / "archive"))
    try:
        assert fleet.tracing is False
        assert fleet.frontdoor_tracer is None
        assert fleet.archive is None
        assert fleet.slo is None
        fleet.scale_up(1)
        rep = fleet.replicas()[0]
        assert rep.tracer is None
        assert rep.engine._tracer is None
        # ids pass through unminted: no uuid cost on the disabled path
        assert fleet.mint_ids(None, None) == (None, None)
        result, _ = fleet.handle_request(PROMPT, 4)
        assert len(result.tokens) == 4
        assert result.trace_id is None
        # nothing was archived and no request events were recorded
        assert not os.path.isdir(str(tmp_path / "archive"))
    finally:
        fleet.close()


def test_tracing_on_by_default_and_attach_tracer_swap(params, monkeypatch):
    monkeypatch.delenv("DCT_TELEMETRY_DISABLED", raising=False)
    fleet = make_fleet(params)
    try:
        assert fleet.tracing is True
        assert fleet.frontdoor_tracer is not None
        assert fleet.slo is not None
        fleet.scale_up(1)
        engine = fleet.replicas()[0].engine
        assert engine._tracer is not None
        # the bench's traced/untraced A/B rides this atomic swap
        engine.attach_tracer(None)
        assert engine._tracer is None
        t = Tracer(enabled=True, process_name="bench_serving")
        engine.attach_tracer(t)
        assert engine._tracer is t
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# Chaos: replica hard-killed mid-request (the flight-recorder property)
# ---------------------------------------------------------------------------

CHAOS_LEG1 = '''
import os, signal, sys, time
sys.path.insert(0, {repo!r})
import jax
from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving import (
    BucketSpec, KVCacheConfig, ServingFleet)
from determined_clone_tpu.telemetry.flight import request_records

archive_dir = sys.argv[1]
cfg = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32, n_heads=4,
                    d_ff=64, max_seq_len=48, remat=False,
                    attention_impl="mha")
params = gpt.init(jax.random.PRNGKey(0), cfg)
fleet = ServingFleet(params, cfg, name="leg1",
                     buckets=BucketSpec.build(2, 8),
                     cache=KVCacheConfig(num_blocks=16, block_size=8),
                     warmup=False, tracing=True, archive_dir=archive_dir,
                     iteration_floor_s=0.05)
fleet.scale_up(1)
fleet.submit([1, 2, 3], 40, request_id="req-chaos", trace_id="trace-chaos")
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    if any(r.get("name") == "request_admitted"
           for r in request_records(archive_dir, "req-chaos")):
        # the partial leg is on disk; die like a machine failure —
        # no drain, no close, no atexit
        os.kill(os.getpid(), signal.SIGKILL)
    time.sleep(0.02)
print("ADMISSION NEVER ARCHIVED", file=sys.stderr)
sys.exit(3)
'''


@pytest.mark.slow
def test_kill9_replica_leaves_partial_leg_and_failover_completes(
        params, tmp_path):
    """Satellite chaos property: leg 1 (a subprocess fleet) is SIGKILLed
    mid-request after admission; the archive's live ring keeps its
    partial spans. Leg 2 (this process) re-runs the same request_id /
    trace_id to completion — the failed-over retry — and the stitched
    trace shows BOTH legs under the one trace_id."""
    archive_dir = str(tmp_path / "archive")
    script = tmp_path / "chaos_leg1.py"
    script.write_text(CHAOS_LEG1.format(repo=REPO))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("DCT_TELEMETRY_DISABLED", None)
    proc = subprocess.run([sys.executable, str(script), archive_dir],
                          env=env, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == -9, proc.stdout + proc.stderr

    # the partial leg survived the kill
    leg1 = request_records(archive_dir, "req-chaos")
    assert any(r.get("name") == "request_admitted" for r in leg1)
    assert all(r.get("trace_id") == "trace-chaos" for r in leg1)

    # leg 2: a fresh fleet over the same archive completes the request
    fleet = make_fleet(params, name="leg2", tracing=True,
                       archive_dir=archive_dir)
    try:
        fleet.scale_up(1)
        result, _ = fleet.handle_request(
            PROMPT, 6, request_id="req-chaos", trace_id="trace-chaos")
        assert len(result.tokens) == 6
        for rep in fleet.replicas():
            rep.engine.wait_idle(60.0)
    finally:
        fleet.close()

    trace = request_chrome_trace(archive_dir, "req-chaos")
    assert validate_chrome_trace(trace) == []
    assert trace["otherData"]["trace_ids"] == ["trace-chaos"]
    processes = set(trace["otherData"]["processes"])
    assert "serving_replica_leg1-1" in processes   # the killed leg
    assert "serving_replica_leg2-1" in processes   # the completed leg
    assert "frontdoor" in processes
    names = {e["name"] for e in trace["traceEvents"]
             if e["ph"] in ("X", "i")}
    assert "request_admitted" in names
    assert "request_retired" in names              # only leg 2 got here
