"""Searcher behavior tests via simulation — the reference's asha_test.go /
simulate.go strategy."""
import json

import pytest

from determined_clone_tpu.config import ExperimentConfig, SearcherConfig
from determined_clone_tpu.config.hyperparameters import HyperparameterSpace
from determined_clone_tpu.searcher import (
    ASHASearch,
    AdaptiveASHASearch,
    GridSearch,
    RandomSearch,
    Searcher,
    SingleSearch,
    build_method,
    simulate,
)

SPACE = HyperparameterSpace({
    "lr": {"type": "log", "minval": -4, "maxval": -1, "count": 4},
    "width": {"type": "int", "minval": 8, "maxval": 64, "count": 3},
})


def cfg(**kw):
    base = {"name": "single", "metric": "loss", "max_length": {"batches": 64}}
    base.update(kw)
    return SearcherConfig.from_dict(base)


def good_lr_metric(hparams, units):
    """Lower loss for lr near 1e-2 and more training."""
    import math

    lr = hparams["lr"]
    dist = abs(math.log10(lr) + 2.0)
    return dist + 1.0 / (1 + units / 8)


class TestSingle:
    def test_one_trial_to_max_length(self):
        r = simulate(SingleSearch(cfg(), SPACE), good_lr_metric)
        assert r.shutdown
        assert r.n_trials == 1
        assert list(r.units_by_trial().values()) == [64]


class TestRandom:
    def test_max_trials_created_all_full_length(self):
        c = cfg(name="random", max_trials=7, max_concurrent_trials=3)
        r = simulate(RandomSearch(c, SPACE), good_lr_metric)
        assert r.shutdown
        assert r.n_trials == 7
        assert all(u == 64 for u in r.units_by_trial().values())
        assert r.max_concurrent_seen <= 3

    def test_errored_trial_replaced_and_search_completes(self):
        from determined_clone_tpu.searcher import Searcher

        c = cfg(name="random", max_trials=3, max_concurrent_trials=1)
        engine = Searcher(RandomSearch(c, SPACE))
        from determined_clone_tpu.searcher.base import Create, Shutdown, ValidateAfter

        queue = list(engine.initial_operations())
        shutdown = False
        errored_once = False
        events = 0
        while queue and events < 100:
            events += 1
            op = queue.pop(0)
            if isinstance(op, Create):
                queue.extend(engine.trial_created(op.request_id))
            elif isinstance(op, ValidateAfter):
                if not errored_once:
                    errored_once = True
                    queue.extend(engine.trial_exited_early(op.request_id, "err"))
                else:
                    queue.extend(engine.validation_completed(op.request_id, 1.0, op.length))
            elif isinstance(op, Shutdown):
                shutdown = True
        assert shutdown  # failure did not wedge the search

    def test_distinct_hparams(self):
        c = cfg(name="random", max_trials=5)
        r = simulate(RandomSearch(c, SPACE), good_lr_metric)
        lrs = {t.hparams["lr"] for t in r.trials.values()}
        assert len(lrs) == 5


class TestGrid:
    def test_enumerates_grid(self):
        space = HyperparameterSpace({
            "a": {"type": "categorical", "vals": [1, 2]},
            "b": {"type": "categorical", "vals": ["x", "y", "z"]},
        })
        c = cfg(name="grid", max_trials=100)
        r = simulate(GridSearch(c, space), lambda hp, u: float(hp["a"]))
        assert r.shutdown
        assert r.n_trials == 6
        combos = {(t.hparams["a"], t.hparams["b"]) for t in r.trials.values()}
        assert len(combos) == 6


class TestASHA:
    def test_rung_structure_and_early_stopping(self):
        c = cfg(name="asha", max_trials=16, divisor=4, num_rungs=3,
                max_length={"batches": 64}, max_concurrent_trials=4)
        method = ASHASearch(c, SPACE, seed=1)
        assert method.rung_targets == [4, 16, 64]
        r = simulate(method, good_lr_metric)
        assert r.shutdown
        assert r.n_trials == 16
        units = sorted(r.units_by_trial().values())
        # most trials stop early; only ~1/divisor^2 reach the top rung
        assert units[0] == 4
        n_top = sum(1 for u in units if u == 64)
        assert 1 <= n_top <= 6
        # total budget far below max_trials * max_length
        assert sum(units) < 16 * 64 * 0.5

    def test_promotes_good_trials(self):
        # async ASHA can't guarantee the global best is promoted (quota is
        # taken by whoever is best among *arrived* trials), but every
        # top-rung trial must be better than the median of its cohort.
        c = cfg(name="asha", max_trials=12, divisor=3, num_rungs=3,
                max_length={"batches": 27}, max_concurrent_trials=12)
        r = simulate(ASHASearch(c, SPACE, seed=3), good_lr_metric)
        scores = sorted(good_lr_metric(t.hparams, 27) for t in r.trials.values())
        median = scores[len(scores) // 2]
        top_units = max(r.units_by_trial().values())
        top_trials = [t for t in r.trials.values()
                      if t.trained_units == top_units]
        assert top_trials
        assert all(good_lr_metric(t.hparams, 27) < median for t in top_trials)

    def test_stopping_variant(self):
        c = cfg(name="asha", max_trials=12, divisor=3, num_rungs=3,
                max_length={"batches": 27}, stop_once=True,
                max_concurrent_trials=4)
        r = simulate(ASHASearch(c, SPACE, seed=5), good_lr_metric)
        assert r.shutdown
        assert r.n_trials == 12

    def test_smaller_is_better_false(self):
        c = cfg(name="asha", max_trials=9, divisor=3, num_rungs=2,
                smaller_is_better=False, max_length={"batches": 9},
                max_concurrent_trials=9)
        # maximize: higher is better; trial with highest metric promotes
        r = simulate(ASHASearch(c, SPACE, seed=7),
                     lambda hp, u: hp["lr"])
        best = max(r.trials.values(), key=lambda t: t.hparams["lr"])
        assert best.trained_units == max(r.units_by_trial().values())

    def test_snapshot_restore_midway(self):
        c = cfg(name="asha", max_trials=8, divisor=2, num_rungs=3,
                max_length={"batches": 16}, max_concurrent_trials=2)
        m1 = ASHASearch(c, SPACE, seed=11)
        e1 = Searcher(m1)
        ops = list(e1.initial_operations())
        # process a few events
        created = [o for o in ops if type(o).__name__ == "Create"]
        for o in created:
            ops.extend(e1.trial_created(o.request_id))
        snap = json.loads(json.dumps(e1.snapshot()))  # survives JSON

        m2 = ASHASearch(c, SPACE, seed=11)
        e2 = Searcher(m2)
        e2.restore(snap)
        assert e2.next_id == e1.next_id
        assert m2.created == m1.created
        assert m2.rung_targets == m1.rung_targets


class TestAdaptiveASHA:
    @pytest.mark.parametrize("mode,expected_brackets", [
        ("aggressive", 1), ("standard", 3), ("conservative", 4),
    ])
    def test_bracket_counts(self, mode, expected_brackets):
        c = cfg(name="adaptive_asha", max_trials=16, num_rungs=4,
                max_length={"batches": 64}, mode=mode)
        m = AdaptiveASHASearch(c, SPACE)
        assert len(m.brackets) == expected_brackets

    def test_budget_split_and_completion(self):
        c = cfg(name="adaptive_asha", max_trials=16, num_rungs=3,
                divisor=4, max_length={"batches": 64}, mode="standard",
                max_concurrent_trials=6)
        r = simulate(AdaptiveASHASearch(c, SPACE, seed=13), good_lr_metric)
        assert r.shutdown
        assert r.n_trials == 16

    def test_aggressive_equals_asha(self):
        c = cfg(name="adaptive_asha", max_trials=8, num_rungs=3, divisor=4,
                max_length={"batches": 64}, mode="aggressive",
                max_concurrent_trials=4)
        r = simulate(AdaptiveASHASearch(c, SPACE, seed=17), good_lr_metric)
        assert r.shutdown and r.n_trials == 8


class TestFactory:
    def test_build_all(self):
        for name in ("single", "random", "grid", "asha", "adaptive_asha"):
            c = cfg(name=name, max_trials=4, max_length={"batches": 8})
            m = build_method(c, SPACE)
            assert m is not None

    def test_custom_unbuildable(self):
        with pytest.raises(ValueError, match="custom"):
            build_method(cfg(name="custom"), SPACE)
