"""Bench result-schema regression test: a REAL bench child run must emit
the fields the regression gate (tools/bench_gate.py) and the BENCH history
depend on — non-null analytic ``mfu``, its labeled denominator, and the
XLA section (compile time, HLO fingerprint, measured MFU, peak memory)
added by the observability issue. A schema drift here silently turns the
gate advisory, so it is pinned by running the actual child, not a mock.

The child is killed right after it banks the first rung's result line (the
mha/mnist/pipeline extras are budget-dependent and not schema-load-bearing),
keeping the test inside the tier-1 lane.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


@pytest.fixture(scope="module")
def bench_result():
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--child"], env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    result = None
    deadline = time.monotonic() + 300
    try:
        for line in proc.stdout:
            if time.monotonic() > deadline:
                break
            line = line.strip()
            if not line.startswith("{"):
                continue
            obj = json.loads(line)
            if "metric" in obj:
                result = obj
                break
    finally:
        try:
            proc.send_signal(signal.SIGKILL)
        except OSError:
            pass
        proc.wait(timeout=30)
    assert result is not None, "bench child banked no result line"
    return result


def test_headline_fields(bench_result):
    assert bench_result["metric"] == "gpt_train_throughput"
    assert bench_result["value"] > 0
    assert bench_result["unit"] == "samples/sec/chip"


def test_analytic_mfu_never_null(bench_result):
    """The bench gate hard-fails on mfu=null; the analytic engine must
    produce one on every platform, with the denominator labeled."""
    detail = bench_result["detail"]
    assert detail["mfu"] is not None and detail["mfu"] > 0
    assert isinstance(detail["mfu_peak_assumed"], str)
    assert ":" in detail["mfu_peak_assumed"]  # "<label>:<peak flops>"
    assert detail["flops_per_step"] > 0


def test_xla_section_schema(bench_result):
    """The XLA section: every field the gate's _xla_lines reads, non-null
    on the CPU lane (the lane that always runs)."""
    xla = bench_result["detail"]["xla"]
    assert xla["compile_time_s"] > 0
    assert isinstance(xla["fingerprint"], str)
    assert len(xla["fingerprint"]) == 16
    assert xla["program_flops"] > 0
    assert xla["program_bytes_accessed"] > 0
    assert xla["measured_flops_per_sec"] > 0
    assert 0 < xla["measured_mfu"] < 1
    assert xla["peak_memory_bytes"] > 0
    assert xla["memory_device_count"] >= 1
    # median-of-repeats ran (the r03->r04 noise fix): spread recorded
    assert xla["timing_spread"] is None or xla["timing_spread"] >= 1.0


def test_goodput_section_schema(bench_result):
    """The goodput section (telemetry/goodput.py, measured on a real
    trainer mini-run inside the bench child): the acceptance criterion is
    a non-null fraction with the conservation invariant holding — a null
    here means the ledger fell out of the bench wiring."""
    gp = bench_result["detail"]["goodput"]
    assert gp.get("error") is None
    assert gp["goodput_fraction"] is not None
    assert 0 <= gp["goodput_fraction"] <= 1
    assert gp["wall_s"] > 0
    assert gp["conservation_ok"] is True
    assert gp["conservation_error_fraction"] <= 0.01
    cats = gp["categories"]
    assert cats["productive"] > 0
    assert cats["checkpoint_save"] > 0  # the mini-run commits at batch 8


def test_serving_section_schema(bench_result):
    """The serving section (serving/engine.py measured by bench's
    latency-vs-load sweep): non-null tokens/sec and p50/p99 at >= 3
    offered loads, continuous batching beating the static
    run-to-completion baseline at the highest load in the same run, and
    the compile count inside the bucket budget — the serving-lane
    acceptance criteria, pinned against the real child."""
    sv = bench_result["detail"]["serving"]
    assert sv.get("error") is None, sv
    points = sv["load_points"]
    assert len(points) >= 3
    rates = [p["offered_rps"] for p in points]
    assert rates == sorted(rates) and len(set(rates)) == len(rates)
    for p in points:
        assert p["tokens_per_sec"] > 0
        assert p["p50_total_s"] > 0
        assert p["p99_total_s"] >= p["p50_total_s"]
        assert p["completed"] == sv["requests"]
    assert sv["static"]["tokens_per_sec"] > 0
    # the point of continuous batching — same programs, same pool, same
    # request set; only the scheduling policy differs
    assert sv["continuous_over_static"] > 1.0, sv
    assert 0 < sv["programs_compiled"] <= sv["program_budget"]
    assert sv["serving_mfu"] > 0
    assert ":" in sv["mfu_peak_assumed"]
    # tracing A/B at top load: the overhead estimate must be measured
    # (non-null) and sane; the <2% budget itself is the gate's advisory
    assert isinstance(sv["tracing_overhead"], float)
    assert sv["tracing_overhead"] < 0.5
    assert sv["traced_tokens_per_sec"] > 0
    # the simulated-clock SLO replay of the measured latency distribution
    slo = sv["slo"]
    assert slo["verdict"] in ("ok", "slow_burn", "fast_burn", "no_data")
    assert slo["latency_threshold_s"] > 0
    assert isinstance(slo["burning_fast"], bool)


def test_tsdb_section_schema(bench_result):
    """The tsdb section (telemetry/tsdb.py measured by bench's synthetic
    scrape soak): the acceptance criterion is a scrape+store+rule-eval
    duty cycle under 2% of the scrape period with the store inside its
    memory budget — a null here means the soak fell out of the wiring."""
    ts = bench_result["detail"]["tsdb"]
    assert ts.get("error") is None, ts
    assert ts["series"] > 0
    assert ts["samples_per_scrape"] > 0
    assert ts["dump_ms"] > 0
    assert ts["scrape_ms"] > 0
    assert ts["scrape_period_s"] > 0
    assert 0 < ts["duty_fraction"] < 0.02
    assert ts["bytes_estimate"] > 0
    assert ts["within_budget"] is True


def test_gate_accepts_fresh_round(bench_result):
    """The regression gate passes a round against itself and prints the
    advisory xla + goodput lines — wiring proof that gate and schema
    agree."""
    from tools.bench_gate import gate

    ok, report = gate(bench_result, bench_result)
    assert ok, report
    assert any(line.startswith("ok: xla compile=") for line in report)
    assert any(line.startswith("ok: goodput fraction=") for line in report)
    assert any(line.startswith("ok: serving ") for line in report)
    assert any(line.startswith("ok: tsdb ") for line in report)
    warns = [line for line in report if line.startswith("WARN:")]
    assert not warns, warns


def test_gate_report_lines_convert_to_json(bench_result):
    """--json is a faithful re-encoding: every text report line maps to
    one {level, section, message} record, with the section recovered
    from the line itself (the contract CI dashboards consume)."""
    from tools.bench_gate import gate, report_line_to_json

    _, report = gate(bench_result, bench_result)
    for line in report:
        rec = report_line_to_json(line)
        assert rec["level"] in ("ok", "warn", "fail", "note", "info")
        assert rec["message"] and rec["message"] in line
    by_section = {report_line_to_json(line)["section"]
                  for line in report}
    assert {"throughput", "xla", "goodput", "serving", "tsdb"} <= \
        by_section
    # spot-check the three prefix levels and the section-note form
    assert report_line_to_json("FAIL: mfu missing")["level"] == "fail"
    assert report_line_to_json(
        "WARN: tsdb errored: boom") == {
            "level": "warn", "section": "tsdb",
            "message": "tsdb errored: boom"}
    note = report_line_to_json(
        "note: section 'exec_cache' present in the previous round is "
        "missing in the new one; compare skipped")
    assert note == {"level": "note", "section": "exec_cache",
                    "message": note["message"]}


def test_gate_enforces_bench_history():
    """The throughput compare is ENFORCED, not advisory: the two newest
    committed BENCH rounds must gate clean at the -5% tolerance. A PR
    that regresses throughput past the tolerance fails tier-1 here, per
    ROADMAP item 5's 'every perf PR must move MFU or tokens/sec'.

    Skips (never fails) when the history can't support a compare: fewer
    than two rounds, or a round whose driver wrapper banked no result
    line (early rounds predate the result-line contract). mfu=null is
    allowed: pre-analytic-engine rounds carry it."""
    from tools.bench_gate import gate, load_bench, newest_rounds

    try:
        old_path, new_path = newest_rounds(REPO)
        old, new = load_bench(old_path), load_bench(new_path)
    except ValueError as e:
        pytest.skip(f"BENCH history not comparable: {e}")
    ok, report = gate(old, new, allow_null_mfu=True)
    assert ok, (f"{old_path} -> {new_path} failed the enforced "
                f"throughput gate:\n" + "\n".join(report))
