"""Telemetry subsystem tests: spans, metrics registry, Chrome-trace export,
and the trainer/prefetcher/profiler wiring (docs/observability.md)."""
import json
import threading
import time

import numpy as np
import pytest

from determined_clone_tpu.telemetry import (
    NULL_SPAN,
    Histogram,
    MetricsRegistry,
    Telemetry,
    Tracer,
    chrome_trace_events,
    null_span,
    parse_prometheus_text,
    spans_from_profiler_samples,
    telemetry_from_config,
    to_chrome_trace,
    validate_chrome_trace,
)


# ---------------------------------------------------------------------------
# Spans: nesting, ordering, determinism
# ---------------------------------------------------------------------------

class TestSpans:
    def test_nesting_depth_and_order(self):
        tr = Tracer()
        with tr.span("outer"):
            with tr.span("inner_a"):
                pass
            with tr.span("inner_b", tag=1):
                pass
        # spans record on exit: children before parent, siblings in order
        names = [e["name"] for e in tr.events()]
        assert names == ["inner_a", "inner_b", "outer"]
        by_name = {e["name"]: e for e in tr.events()}
        assert by_name["outer"]["depth"] == 0
        assert by_name["inner_a"]["depth"] == 1
        assert by_name["inner_b"]["depth"] == 1
        assert by_name["inner_b"]["args"] == {"tag": 1}

    def test_child_interval_inside_parent(self):
        tr = Tracer()
        with tr.span("parent"):
            with tr.span("child"):
                time.sleep(0.002)
        by_name = {e["name"]: e for e in tr.events()}
        p, c = by_name["parent"], by_name["child"]
        assert p["ts_us"] <= c["ts_us"]
        assert c["ts_us"] + c["dur_us"] <= p["ts_us"] + p["dur_us"] + 1

    def test_set_merges_args(self):
        tr = Tracer()
        with tr.span("s", a=1) as sp:
            sp.set(b=2)
        (e,) = tr.events()
        assert e["args"] == {"a": 1, "b": 2}

    def test_instant_event(self):
        tr = Tracer()
        tr.instant("marker", k="v")
        (e,) = tr.events()
        assert e["ph"] == "i" and e["name"] == "marker"

    def test_max_events_keeps_head_counts_drops(self):
        tr = Tracer(max_events=3)
        for i in range(5):
            with tr.span(f"s{i}"):
                pass
        assert [e["name"] for e in tr.events()] == ["s0", "s1", "s2"]
        assert tr.dropped == 2

    def test_disabled_tracer_is_null(self):
        tr = Tracer(enabled=False)
        assert tr.span("x") is NULL_SPAN
        with tr.span("x") as sp:
            sp.set(ignored=True)
        assert tr.events() == []

    def test_null_span_is_reusable_noop(self):
        with null_span("a", k=1) as sp:
            sp.set(other=2)
        with null_span("b") as sp2:
            assert sp2 is sp

    def test_span_summary_aggregates(self):
        tr = Tracer()
        for _ in range(3):
            with tr.span("step"):
                pass
        tr.instant("marker")  # instants excluded from the summary
        summary = tr.span_summary()
        assert set(summary) == {"step"}
        assert summary["step"]["count"] == 3
        assert summary["step"]["total_s"] >= 0.0

    def test_drain_since_cursor(self):
        tr = Tracer()
        with tr.span("a"):
            pass
        new, cur = tr.drain_since(0)
        assert [e["name"] for e in new] == ["a"]
        with tr.span("b"):
            pass
        new, cur = tr.drain_since(cur)
        assert [e["name"] for e in new] == ["b"]
        new, cur = tr.drain_since(cur)
        assert new == []


# ---------------------------------------------------------------------------
# Thread safety: spans recorded from a producer thread interleave cleanly
# ---------------------------------------------------------------------------

class TestThreadSafety:
    def test_spans_from_many_threads(self):
        tr = Tracer()
        n_threads, n_spans = 4, 200

        def work(tid):
            for i in range(n_spans):
                with tr.span("w", i=i):
                    if i % 50 == 0:
                        time.sleep(0.0001)

        threads = [threading.Thread(target=work, args=(t,), name=f"wk-{t}")
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = tr.events()
        assert len(events) == n_threads * n_spans
        # per-thread nesting stacks are thread-local: every span depth 0
        assert all(e["depth"] == 0 for e in events)
        assert len({e["tid"] for e in events}) == n_threads

    def test_prefetch_producer_lane(self):
        from determined_clone_tpu.utils.data import DevicePrefetcher

        tr = Tracer()
        reg = MetricsRegistry()
        pf = DevicePrefetcher(iter(range(20)), put=lambda x: x * 2,
                              depth=2, tracer=tr, registry=reg)
        try:
            got = list(pf)
        finally:
            pf.close()
        assert got == [x * 2 for x in range(20)]
        events = tr.events()
        names = {e["name"] for e in events}
        assert {"produce_batch", "dataload_next", "device_put"} <= names
        # all producer spans live on the producer thread's lane
        lanes = {e["tname"] for e in events}
        assert lanes == {"device-prefetch"}
        # nesting: device_put sits inside produce_batch
        by = {}
        for e in events:
            by.setdefault(e["name"], []).append(e)
        assert all(e["depth"] == 1 for e in by["device_put"])
        assert all(e["depth"] == 0 for e in by["produce_batch"])
        hist = reg.histogram("device_put_seconds")
        assert hist.count == 20

    def test_registry_concurrent_updates(self):
        reg = MetricsRegistry()
        c = reg.counter("hits", "test")
        h = reg.histogram("lat", "test")

        def work():
            for i in range(500):
                c.inc()
                h.observe(i * 0.001)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 2000
        assert h.count == 2000


# ---------------------------------------------------------------------------
# Histogram percentiles vs numpy
# ---------------------------------------------------------------------------

class TestHistogram:
    @pytest.mark.parametrize("q", [50, 95, 99])
    def test_percentiles_match_numpy_when_unsampled(self, q):
        rng = np.random.default_rng(7)
        xs = rng.lognormal(mean=-3, sigma=1.0, size=1000)
        h = Histogram("lat", "test", reservoir_size=4096)
        for x in xs:
            h.observe(float(x))
        assert h.percentile(q) == pytest.approx(
            np.percentile(xs, q), rel=1e-9)

    def test_percentiles_close_under_reservoir_sampling(self):
        rng = np.random.default_rng(11)
        xs = rng.normal(loc=10.0, scale=2.0, size=20_000)
        h = Histogram("lat", "test", reservoir_size=2048, seed=3)
        for x in xs:
            h.observe(float(x))
        # reservoir is a uniform sample: quantiles agree statistically
        assert h.percentile(50) == pytest.approx(
            np.percentile(xs, 50), abs=0.3)
        assert h.count == 20_000

    def test_empty_histogram(self):
        import math

        h = Histogram("lat", "test")
        assert math.isnan(h.percentile(50))
        assert h.count == 0
        with pytest.raises(ValueError):
            h.percentile(101)

    def test_prometheus_exposition_format(self):
        reg = MetricsRegistry()
        reg.counter("reqs_total", "requests").inc(3)
        reg.gauge("depth", "queue depth").set(2.5)
        h = reg.histogram("lat_seconds", "latency")
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        text = reg.dump()
        assert "# TYPE reqs_total counter" in text
        assert "reqs_total 3" in text
        assert "# TYPE depth gauge" in text
        assert "depth 2.5" in text
        assert '# TYPE lat_seconds summary' in text
        assert 'lat_seconds{quantile="0.5"} 0.2' in text
        assert "lat_seconds_count 3" in text

    def test_registry_idempotent_and_type_checked(self):
        reg = MetricsRegistry()
        c1 = reg.counter("n", "x")
        assert reg.counter("n", "x") is c1
        with pytest.raises(TypeError):
            reg.gauge("n", "x")

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("n", "x").inc(-1)


class TestPromExposition:
    """dump() edge cases + round-trip through parse_prometheus_text —
    the parser `dct metrics` falls back to against a bare /metrics page."""

    def test_empty_registry_dumps_empty(self):
        reg = MetricsRegistry()
        assert reg.dump() == ""
        parsed = parse_prometheus_text(reg.dump())
        assert parsed["samples"] == []

    def test_single_sample_histogram(self):
        reg = MetricsRegistry()
        reg.histogram("lat_seconds", "latency").observe(0.25)
        text = reg.dump()
        # one observation: every quantile collapses onto it
        for q in ("0.5", "0.95", "0.99"):
            assert f'lat_seconds{{quantile="{q}"}} 0.25' in text
        assert "lat_seconds_sum 0.25" in text
        assert "lat_seconds_count 1" in text
        parsed = parse_prometheus_text(text)
        assert parsed["types"]["lat_seconds"] == "summary"
        count = [v for n, labels, v in parsed["samples"]
                 if n == "lat_seconds_count"]
        assert count == [1.0]

    def test_label_escaping_round_trips(self):
        reg = MetricsRegistry()
        ugly = 'quo"te\\slash\nnewline'
        reg.counter("errs_total", "errors",
                    labels={"msg": ugly, "code": "7"}).inc(2)
        text = reg.dump()
        assert "\n\n" not in text  # escaped newline never splits the line
        parsed = parse_prometheus_text(text)
        (sample,) = [s for s in parsed["samples"] if s[0] == "errs_total"]
        assert sample[1] == {"msg": ugly, "code": "7"}
        assert sample[2] == 2.0

    def test_help_escaping(self):
        reg = MetricsRegistry()
        reg.gauge("g", "multi\nline \\help").set(1)
        text = reg.dump()
        assert "# HELP g multi\\nline \\\\help" in text
        assert parse_prometheus_text(text)["help"]["g"] == \
            "multi\nline \\help"

    def test_labeled_children_share_one_family(self):
        reg = MetricsRegistry()
        reg.counter("hits_total", "hits", labels={"trial": "1"}).inc(1)
        reg.counter("hits_total", "hits", labels={"trial": "2"}).inc(4)
        text = reg.dump()
        assert text.count("# TYPE hits_total counter") == 1
        parsed = parse_prometheus_text(text)
        got = {s[1]["trial"]: s[2] for s in parsed["samples"]
               if s[0] == "hits_total"}
        assert got == {"1": 1.0, "2": 4.0}

    def test_full_round_trip_all_types(self):
        reg = MetricsRegistry()
        reg.counter("c_total", "c").inc(5)
        reg.gauge("g", "g").set(-2.5)
        h = reg.histogram("h_seconds", "h")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        parsed = parse_prometheus_text(reg.dump())
        flat = {(n, tuple(sorted(labels.items()))): v
                for n, labels, v in parsed["samples"]}
        assert flat[("c_total", ())] == 5.0
        assert flat[("g", ())] == -2.5
        assert flat[("h_seconds_sum", ())] == 10.0
        assert flat[("h_seconds_count", ())] == 4.0
        assert flat[("h_seconds", (("quantile", "0.5"),))] == \
            pytest.approx(h.percentile(50))

    @pytest.mark.parametrize("seed", [7, 23, 1031])
    def test_randomized_exposition_round_trips(self, seed):
        """Property test: whatever a registry holds — random names,
        hostile label values, exemplar rings — parsing its own dump()
        must reconstruct every family, label set, quantile child, and
        exemplar line. This is the contract the TSDB scrape and the
        `dct metrics` fallback both stand on."""
        import random

        rng = random.Random(seed)
        label_values = ["a", "b-7", 'quo"te', "back\\slash", "new\nline",
                        "sp ace", "ünïcode", ""]

        def labelset():
            return {f"l{j}": rng.choice(label_values)
                    for j in range(rng.randint(0, 3))}

        reg = MetricsRegistry()
        want = {}          # (name, frozen labels) -> expected value
        want_quant = set()  # histogram family names
        want_ex = set()     # (family, request_id) expected in exemplars
        want_ex_val = {}    # family -> max observation value
        for i in range(rng.randint(5, 15)):
            style = rng.choice(["counter", "gauge", "hist"])
            name = f"m{i}_{style}" + ("_total" if style == "counter"
                                      else "")
            labels = labelset()
            key = (name, tuple(sorted(labels.items())))
            if style == "counter":
                v = rng.randint(0, 10 ** rng.randint(0, 9))
                reg.counter(name, "r", labels=labels).inc(v)
                want[key] = float(v)
            elif style == "gauge":
                v = rng.uniform(-1e6, 1e6)
                reg.gauge(name, "r", labels=labels).set(v)
                want[key] = v
            else:
                h = reg.histogram(name, "r", labels=labels)
                obs = [rng.uniform(0, 100) for _ in range(
                    rng.randint(1, 20))]
                ids = []
                for j, v in enumerate(obs):
                    rid = f"req-{i}-{j}"
                    h.observe(v, exemplar=rid)
                    ids.append(rid)
                want[(name + "_sum", key[1])] = sum(obs)
                want[(name + "_count", key[1])] = float(len(obs))
                want_quant.add((name, key[1]))
                # dump() emits one # EXEMPLAR line per histogram: the
                # newest observation at the all-time max
                best = max(range(len(obs)),
                           key=lambda j: (obs[j], j))
                want_ex.add((name, ids[best]))
                want_ex_val[name] = obs[best]
        parsed = parse_prometheus_text(reg.dump())
        got = {(n, tuple(sorted(labels.items()))): v
               for n, labels, v in parsed["samples"]
               if "quantile" not in labels}
        assert set(got) == set(want)
        for key, v in want.items():
            assert got[key] == pytest.approx(v, rel=1e-9), key
        for fam, lbls in want_quant:
            quantiles = {labels["quantile"]
                         for n, labels, _ in parsed["samples"]
                         if n == fam and "quantile" in labels
                         and tuple(sorted((k, v) for k, v in
                                          labels.items()
                                          if k != "quantile")) == lbls}
            assert {"0.5", "0.95", "0.99"} <= quantiles, fam
        got_ex = {(n, labels.get("request_id"))
                  for n, labels, _ in parsed["exemplars"]}
        assert got_ex == want_ex
        for n, labels, v in parsed["exemplars"]:
            assert v == pytest.approx(want_ex_val[n], rel=1e-9)


# ---------------------------------------------------------------------------
# Chrome trace export: schema validity
# ---------------------------------------------------------------------------

class TestChromeTrace:
    def _trace(self):
        tr = Tracer()
        with tr.span("outer"):
            with tr.span("inner"):
                pass
        tr.instant("mark")
        return to_chrome_trace(tr.events())

    def test_schema_valid(self):
        trace = self._trace()
        assert validate_chrome_trace(trace) == []
        assert trace["displayTimeUnit"] == "ms"
        phases = [e["ph"] for e in trace["traceEvents"]]
        assert "M" in phases and "X" in phases and "i" in phases

    def test_json_round_trip(self, tmp_path):
        tel = Telemetry(enabled=True)
        with tel.tracer.span("s"):
            pass
        path = tel.export_chrome_trace(str(tmp_path / "trace.json"))
        with open(path) as f:
            loaded = json.load(f)
        assert validate_chrome_trace(loaded) == []
        assert "wall_epoch" in loaded["otherData"]
        assert loaded["otherData"]["span_summary"]["s"]["count"] == 1

    def test_thread_lanes_have_metadata(self):
        tr = Tracer()

        def other():
            with tr.span("bg"):
                pass

        t = threading.Thread(target=other, name="lane-two")
        with tr.span("fg"):
            pass
        t.start()
        t.join()
        trace = to_chrome_trace(tr.events())
        meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        lane_names = {e["args"]["name"] for e in meta}
        assert "lane-two" in lane_names
        assert len(meta) == 2
        # X events from the two threads use distinct remapped tids
        tids = {e["tid"] for e in trace["traceEvents"] if e["ph"] == "X"}
        assert len(tids) == 2

    def test_validator_catches_problems(self):
        bad = {"traceEvents": [
            {"ph": "X", "name": "n", "pid": 1, "tid": 1},   # missing ts/dur
            {"ph": "Z", "name": "n", "pid": 1, "tid": 1, "ts": 0},
        ]}
        problems = validate_chrome_trace(bad)
        assert len(problems) >= 2

    def test_spans_from_profiler_samples(self):
        samples = [
            {"group": "timing", "dataloading_s": 0.1},
            {"group": "span", "name": "train_dispatch", "ts_us": 0,
             "dur_us": 5, "tid": 1, "tname": "MainThread", "depth": 0},
        ]
        recs = spans_from_profiler_samples(samples)
        assert len(recs) == 1
        trace = to_chrome_trace(recs)
        assert validate_chrome_trace(trace) == []


# ---------------------------------------------------------------------------
# wrap_jit: spans + compile detection
# ---------------------------------------------------------------------------

class TestWrapJit:
    def test_detects_compiles_and_retraces(self):
        import jax
        import jax.numpy as jnp

        tel = Telemetry(enabled=True)
        fn = jax.jit(lambda x: x * 2)
        cache = getattr(fn, "_cache_size", None)
        wrapped = tel.wrap_jit("train_dispatch", fn,
                               sync=jax.block_until_ready)
        wrapped(jnp.ones((4,)))
        wrapped(jnp.ones((4,)))          # cache hit: no new compile
        assert tel.compile_count() == 1
        if cache is not None:
            wrapped(jnp.ones((8,)))      # new shape => retrace
            assert tel.compile_count() == 2
        names = [e["name"] for e in tel.tracer.events()]
        assert "xla_compile" in names
        assert names.count("train_dispatch") >= 2
        hist = tel.registry.histogram("train_dispatch_seconds")
        assert hist.count >= 2

    def test_fallback_first_call_timing(self):
        tel = Telemetry(enabled=True)
        calls = []
        wrapped = tel.wrap_jit("step", lambda x: calls.append(x) or x)
        wrapped(1)
        wrapped(2)
        assert calls == [1, 2]
        assert tel.compile_count() == 1  # first call counted as compile

    def test_disabled_returns_same_objects(self):
        tel = Telemetry(enabled=False)
        fn = lambda x: x  # noqa: E731
        feed = iter([1, 2])
        assert tel.wrap_jit("step", fn) is fn
        assert tel.wrap_feeder(feed) is feed

    def test_traced_feeder_delegates_and_observes(self):
        from determined_clone_tpu.utils.data import DevicePrefetcher

        tel = Telemetry(enabled=True)
        pf = DevicePrefetcher(iter(range(5)), depth=2)
        feed = tel.wrap_feeder(pf)
        try:
            assert list(feed) == list(range(5))
            assert feed.take_queue_wait() >= 0.0
            assert feed.take_host_time() >= 0.0
        finally:
            feed.close()
        hist = tel.registry.histogram("dataload_wait_seconds")
        assert hist.count == 5
        # 5 item pulls + the exhaustion pull (span exits via StopIteration)
        assert [e["name"] for e in tel.tracer.events()].count(
            "dataload_wait") == 6


# ---------------------------------------------------------------------------
# Publishing over the profiler channel
# ---------------------------------------------------------------------------

class FakeProfiler:
    def __init__(self):
        self.samples = []

    def record(self, sample):
        self.samples.append(sample)


class TestPublish:
    def test_metrics_snapshot_shipped(self):
        tel = Telemetry(enabled=True)
        tel.registry.counter("hits", "x").inc(7)
        prof = FakeProfiler()
        tel.publish(prof, batches_trained=42)
        (s,) = prof.samples
        assert s["group"] == "telemetry"
        assert s["batches_trained"] == 42
        assert s["metrics"]["hits"]["value"] == 7

    def test_spans_shipped_incrementally(self):
        tel = Telemetry(enabled=True, ship_spans=True, ship_metrics=False)
        prof = FakeProfiler()
        with tel.tracer.span("a"):
            pass
        tel.publish(prof)
        with tel.tracer.span("b"):
            pass
        tel.publish(prof)
        names = [s["name"] for s in prof.samples if s["group"] == "span"]
        assert names == ["a", "b"]
        # and the shipped form converts straight back to a valid trace
        recs = spans_from_profiler_samples(prof.samples)
        assert validate_chrome_trace(to_chrome_trace(recs)) == []

    def test_profiler_drop_counter_wired(self):
        from determined_clone_tpu.profiler import ProfilerAgent

        class FailingSession:
            def post(self, path, body, retryable=False):
                raise ConnectionError("master unreachable")

        reg = MetricsRegistry()
        prof = ProfilerAgent(FailingSession(), 1, enabled=True,
                             sample_system=False, registry=reg)
        prof.start()
        prof.record({"time": time.time(), "group": "timing"})
        prof.stop()
        assert reg.counter("profiler_samples_dropped").value >= 1
        assert prof.samples_dropped >= 1


# ---------------------------------------------------------------------------
# Config plumbing
# ---------------------------------------------------------------------------

class TestConfig:
    def test_disabled_by_default(self):
        from determined_clone_tpu.config.experiment import ExperimentConfig

        cfg = ExperimentConfig.from_dict({"name": "t"})
        assert cfg.observability.enabled is False
        assert telemetry_from_config(cfg) is None

    def test_enabled_builds_telemetry(self):
        from determined_clone_tpu.config.experiment import ExperimentConfig

        cfg = ExperimentConfig.from_dict({
            "name": "t",
            "observability": {"enabled": True, "ship_spans": True,
                              "max_events": 5000},
        })
        tel = telemetry_from_config(cfg)
        assert tel is not None and tel.ship_spans
        assert tel.tracer.max_events == 5000

    def test_env_force_enable(self, monkeypatch):
        from determined_clone_tpu.config.experiment import ExperimentConfig

        monkeypatch.setenv("DCT_OBSERVABILITY", "1")
        cfg = ExperimentConfig.from_dict({"name": "t"})
        assert telemetry_from_config(cfg) is not None

    def test_raw_dict_accepted(self):
        tel = telemetry_from_config({"observability": {"enabled": True}})
        assert tel is not None

    def test_flight_and_anomaly_fields_pass_full_schema(self, tmp_path):
        """The flight/anomaly knobs must survive the FULL ExperimentConfig
        path — the closed `observability` block in config/schema.py, not
        just ObservabilityConfig.from_dict — and build a wired Telemetry."""
        from determined_clone_tpu.config.experiment import ExperimentConfig

        flight_dir = str(tmp_path / "flight")
        cfg = ExperimentConfig.from_dict({
            "name": "t",
            "observability": {"flight_dir": flight_dir,
                              "flight_segment_events": 32,
                              "flight_segments": 4,
                              "anomaly_window": 16,
                              "anomaly_threshold": 4.0,
                              "anomaly_min_samples": 8},
        })
        assert cfg.observability.flight_dir == flight_dir
        tel = telemetry_from_config(cfg)
        # flight_dir implies enabled: telemetry built without enabled: true
        assert tel is not None and tel.flight is not None
        assert tel.flight.segment_events == 32
        assert tel.anomaly_window == 16
        assert tel.anomaly_min_samples == 8
        tel.close()


# ---------------------------------------------------------------------------
# CLI: dct trace export --from-file
# ---------------------------------------------------------------------------

class TestCliExport:
    def test_export_from_file(self, tmp_path, capsys):
        from determined_clone_tpu.cli.cli import main

        samples = [
            {"group": "telemetry", "metrics": {}},
            {"group": "span", "name": "train_dispatch", "ts_us": 10,
             "dur_us": 100, "tid": 1, "tname": "MainThread", "depth": 0},
        ]
        src = tmp_path / "samples.jsonl"
        src.write_text("\n".join(json.dumps(s) for s in samples) + "\n")
        out = tmp_path / "trace.json"
        rc = main(["trace", "export", "--from-file", str(src),
                   "-o", str(out)])
        assert rc in (0, None)
        with open(out) as f:
            trace = json.load(f)
        assert validate_chrome_trace(trace) == []
        assert any(e["ph"] == "X" for e in trace["traceEvents"])

    def test_export_no_spans_errors(self, tmp_path):
        from determined_clone_tpu.cli.cli import main

        src = tmp_path / "samples.jsonl"
        src.write_text(json.dumps({"group": "timing"}) + "\n")
        rc = main(["trace", "export", "--from-file", str(src),
                   "-o", str(tmp_path / "t.json")])
        assert rc == 1


# ---------------------------------------------------------------------------
# Acceptance smoke: an instrumented training run end to end
# ---------------------------------------------------------------------------

class RecordingProfiler:
    """Profiler-channel stand-in capturing what the trainer ships."""

    def __init__(self):
        self.samples = []

    def record(self, sample):
        self.samples.append(sample)

    def record_batch_timing(self, batches, dataloading_s, compute_s,
                            queue_wait_s=None, **kw):
        self.samples.append({"group": "timing", "batches": batches,
                             "dataloading_s": dataloading_s,
                             "compute_s": compute_s,
                             "queue_wait_s": queue_wait_s})


class TestTrainerSmoke:
    def _run(self, tmp_path, observability):
        import jax
        import optax
        from determined_clone_tpu import core
        from determined_clone_tpu.config import ExperimentConfig
        from determined_clone_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )
        from determined_clone_tpu.training import (
            JaxTrial,
            Trainer,
            TrialContext,
        )

        class MatmulTrial(JaxTrial):
            # big enough that device compute dominates Python overhead —
            # the compute_s agreement check below needs that
            def initial_params(self, rng):
                import jax.numpy as jnp
                return {"w": jnp.eye(512) * 0.1}

            def optimizer(self):
                return optax.sgd(0.01)

            def loss(self, params, batch, rng):
                import jax.numpy as jnp
                h = batch @ params["w"]
                h = jnp.tanh(h) @ params["w"]
                return jnp.mean(h * h), {}

            def training_data(self):
                rng = np.random.default_rng(0)  # seeded
                for _ in range(48):
                    yield rng.standard_normal((32, 512)).astype(np.float32)

            def validation_data(self):
                rng = np.random.default_rng(1)
                return [rng.standard_normal((32, 512)).astype(np.float32)]

            @property
            def global_batch_size(self):
                return 32

        cfg = ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 48}},
            "scheduling_unit": 16,
            "checkpoint_storage": {"type": "shared_fs",
                                   "host_path": str(tmp_path / "ckpt")},
            "observability": observability,
        })
        prof = RecordingProfiler()
        with core.init(config=cfg, trial_id=1) as cctx:
            cctx.profiler = prof
            mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
            ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
            result = Trainer(MatmulTrial(ctx)).fit()
            tel = cctx.telemetry
            events = tel.tracer.events() if tel is not None else []
        return result, prof, events, cctx

    def test_instrumented_run_meets_acceptance(self, tmp_path):
        trace_path = str(tmp_path / "trace.json")
        result, prof, events, cctx = self._run(
            tmp_path, {"enabled": True, "trace_path": trace_path})
        assert result["batches_trained"] == 48

        # trace.json was written on core.init exit and is schema-valid
        with open(trace_path) as f:
            trace = json.load(f)
        assert validate_chrome_trace(trace) == []

        # spans from >= 2 threads: consumer loop + prefetch producer
        lanes = {e["tname"] for e in events}
        assert "MainThread" in lanes
        assert any(n.startswith("train-prefetch") for n in lanes), lanes

        # nesting: producer device_put sits inside produce_batch
        assert any(e["name"] == "device_put" and e["depth"] == 1
                   for e in events)

        # the taxonomy's trainer-side spans all showed up
        names = {e["name"] for e in events}
        assert {"train_dispatch", "host_sync", "validate",
                "checkpoint_save", "xla_compile"} <= names

        # span/profiler reconciliation: compute_s is (chunk wall - queue
        # wait), so it still contains host_sync and the consumer-visible
        # input cost beyond the queue wait (sync device_put). Before the
        # explicit AOT capture the first-call compile (~100ms) sat in both
        # sums and amortized those residues under 10%; now compile happens
        # out-of-band, so reconcile the residues explicitly.
        dispatch_s = sum(e["dur_us"] for e in events
                         if e["name"] in ("train_dispatch",
                                          "host_sync")) / 1e6
        dataload_s = sum(e["dur_us"] for e in events
                         if e["name"] == "dataload_wait") / 1e6
        timing = [s for s in prof.samples if s["group"] == "timing"]
        compute_s = sum(s["compute_s"] for s in timing)
        queue_wait_s = sum(s["queue_wait_s"] for s in timing)
        assert compute_s > 0
        adjusted = compute_s - max(dataload_s - queue_wait_s, 0.0)
        # spans can't exceed the wall they live in (2% timing jitter)
        assert dispatch_s <= adjusted * 1.02, (
            f"span sum {dispatch_s:.4f}s exceeds chunk compute "
            f"{adjusted:.4f}s")
        # what remains is per-step loop overhead outside any span (fault
        # points, cache probes, span bookkeeping, accumulator) — budget it
        # per step rather than as a fraction of compute, which at this toy
        # step size (~3ms) would make the bound about Python, not tracing
        overhead_per_step = (adjusted - dispatch_s) / 48
        assert overhead_per_step < 1e-3, (
            f"{overhead_per_step * 1e3:.3f}ms/step untraced overhead "
            f"(dispatch+host_sync {dispatch_s:.4f}s, adjusted compute "
            f"{adjusted:.4f}s, dataload {dataload_s:.4f}s, queue_wait "
            f"{queue_wait_s:.4f}s)")

        # telemetry snapshots rode the profiler channel at chunk boundaries
        snaps = [s for s in prof.samples if s.get("group") == "telemetry"]
        assert len(snaps) == 3  # 48 batches / scheduling_unit 16
        assert snaps[-1]["metrics"]["train_dispatch_seconds"]["count"] == 48

    def test_disabled_adds_no_threads_or_events(self, tmp_path):
        before = {t.name for t in threading.enumerate()
                  if not t.name.startswith(("train-prefetch",
                                            "eval-prefetch"))}
        result, prof, events, cctx = self._run(tmp_path, {"enabled": False})
        assert result["batches_trained"] == 48
        assert cctx.telemetry is None
        assert events == []
        assert not any(s.get("group") == "telemetry" for s in prof.samples)
        after = {t.name for t in threading.enumerate()
                 if not t.name.startswith(("train-prefetch",
                                           "eval-prefetch"))}
        assert after <= before
        assert not (tmp_path / "trace.json").exists()
