"""Test configuration: force a virtual 8-device CPU mesh before JAX initializes.

Mirrors the reference's "artificial slots" trick (agent/internal/detect/detect.go:39-56)
— an 8-"chip" gang runs on one box — but via XLA's host-platform device count so that
jax.sharding.Mesh code paths are exercised exactly as they would be on a v5e-8.

The axon sitecustomize (TPU tunnel) may have already imported jax and
registered a TPU PJRT plugin at interpreter startup — before this conftest
runs — so plain env mutation is not enough: we also steer the platform via
``jax.config``, which takes effect as long as no backend has been
initialized yet (no ``jax.devices()`` call has happened).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
