"""Test configuration: force a virtual 8-device CPU mesh before JAX initializes.

Mirrors the reference's "artificial slots" trick (agent/internal/detect/detect.go:39-56)
— an 8-"chip" gang runs on one box — but via XLA's host-platform device count so that
jax.sharding.Mesh code paths are exercised exactly as they would be on a v5e-8.

The steering itself (env + jax.config, because the axon sitecustomize may have
pre-registered a TPU PJRT plugin at interpreter start) lives in
determined_clone_tpu.utils.host_steering, shared with __graft_entry__ and bench.py.
"""
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from determined_clone_tpu.utils.host_steering import steer_to_host_cpu  # noqa: E402

steer_to_host_cpu(8)


@pytest.fixture(autouse=True)
def no_leaked_nondaemon_threads():
    """Fail any test that leaks a non-daemon thread.

    Library threads (prefetcher, profiler, checkpoint uploader, tb-sync) are
    all daemon AND joined on their owners' shutdown paths; a surviving
    non-daemon thread would hang interpreter exit in production. A short
    grace window lets threads a test just signalled finish dying.
    """
    before = set(threading.enumerate())
    yield

    def leaked():
        return [t for t in threading.enumerate()
                if t not in before and not t.daemon and t.is_alive()]

    deadline = time.monotonic() + 2.0
    while leaked() and time.monotonic() < deadline:
        time.sleep(0.05)
    remaining = leaked()
    assert not remaining, (
        f"test leaked non-daemon threads: {[t.name for t in remaining]}")
