"""Test configuration: force a virtual 8-device CPU mesh before JAX initializes.

Mirrors the reference's "artificial slots" trick (agent/internal/detect/detect.go:39-56)
— an 8-"chip" gang runs on one box — but via XLA's host-platform device count so that
jax.sharding.Mesh code paths are exercised exactly as they would be on a v5e-8.

The steering itself (env + jax.config, because the axon sitecustomize may have
pre-registered a TPU PJRT plugin at interpreter start) lives in
determined_clone_tpu.utils.host_steering, shared with __graft_entry__ and bench.py.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from determined_clone_tpu.utils.host_steering import steer_to_host_cpu  # noqa: E402

steer_to_host_cpu(8)
