"""Test configuration: force a virtual 8-device CPU mesh before JAX initializes.

Mirrors the reference's "artificial slots" trick (agent/internal/detect/detect.go:39-56)
— an 8-"chip" gang runs on one box — but via XLA's host-platform device count so that
jax.sharding.Mesh code paths are exercised exactly as they would be on a v5e-8.

The steering itself (env + jax.config, because the axon sitecustomize may have
pre-registered a TPU PJRT plugin at interpreter start) lives in
determined_clone_tpu.utils.host_steering, shared with __graft_entry__ and bench.py.
"""
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from determined_clone_tpu.utils.host_steering import steer_to_host_cpu  # noqa: E402

steer_to_host_cpu(8)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running chaos/e2e tests, excluded from the tier-1 "
        "lane (-m 'not slow'); run_tests.sh --chaos runs them")


# Library threads are daemon (so a leak can't hang interpreter exit), but
# every one of them has a join()ing owner — a survivor means a test skipped
# a close()/stop() path. Named prefixes cover the telemetry-adjacent fleet:
# the device feeders (spans ride the producer thread), the profiler's
# sampler/flusher, checkpoint uploads and tb-sync.
_LIBRARY_THREAD_PREFIXES = (
    "train-prefetch", "eval-prefetch", "device-prefetch",
    "profiler-", "ckpt-upload", "tb-sync",
    "serving-engine", "serving-http",
    "fleet-link", "fleet-drain", "fleet-autoscaler", "fleet-http",
    "fleet-supervisor",
    "dct-tsdb-scrape",
)

# Deliberately process-lifetime daemon threads: the shared transfer pool's
# workers (storage/transfer.py) park on a queue between checkpoint
# uploads/restores by design — surviving a test is correct, not a leak.
_PERSISTENT_THREAD_PREFIXES = ("dct-xfer",)


@pytest.fixture(autouse=True)
def no_leaked_nondaemon_threads():
    """Fail any test that leaks a non-daemon thread, or a *library* daemon
    thread (by name prefix — see _LIBRARY_THREAD_PREFIXES).

    A surviving non-daemon thread would hang interpreter exit in
    production; a surviving library daemon thread means a feeder/profiler
    shutdown path was skipped. A short grace window lets threads a test
    just signalled finish dying. Threads in _PERSISTENT_THREAD_PREFIXES
    are exempt — they are shared process-wide by design.
    """
    before = set(threading.enumerate())
    yield

    def leaked():
        return [t for t in threading.enumerate()
                if t not in before and t.is_alive()
                and not t.name.startswith(_PERSISTENT_THREAD_PREFIXES)
                and (not t.daemon
                     or t.name.startswith(_LIBRARY_THREAD_PREFIXES))]

    deadline = time.monotonic() + 2.0
    while leaked() and time.monotonic() < deadline:
        time.sleep(0.05)
    remaining = leaked()
    assert not remaining, (
        f"test leaked threads: "
        f"{[(t.name, 'daemon' if t.daemon else 'non-daemon') for t in remaining]}")
