"""Serving raw-speed features: COW prefix sharing, draft-model
speculative decoding, and chunked prefill (docs/serving.md).

Every optimisation here is a *scheduling/memory* trick over the same
jitted paged forward, so the acceptance property throughout is the one
tests/test_serving.py pins for the base engine: greedy output stays
bit-identical to the naive uncached forward, with all three features
on at once. The allocator-refcount tests pin the invariants the COW
protocol leans on (never freed while referenced, fork-then-release),
and the compile-budget test pins that warmup covers the extended
program ladder — draft, k+1 verify, and block-copy included — so
traffic never compiles.
"""
import dataclasses
import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from determined_clone_tpu.config import schema
from determined_clone_tpu.config.experiment import (
    ConfigError,
    ServingConfig,
    SpeculativeConfig,
)
from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving import (
    BlockAllocator,
    BucketSpec,
    InferenceEngine,
    KVCacheConfig,
    PrefixCache,
)
from determined_clone_tpu.serving.http import (
    ServingHTTPServer,
    generate_over_http,
)
from determined_clone_tpu.telemetry import flops as flops_mod

CFG = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32, n_heads=4,
                    d_ff=64, max_seq_len=48, remat=False,
                    attention_impl="mha")

BUCKETS = BucketSpec.build(4, 16)
CACHE = KVCacheConfig(num_blocks=16, block_size=8)

PROMPTS = [[5, 17, 3, 88, 41], [9] * 11, [1, 2, 3]]


@pytest.fixture(scope="module")
def params():
    return gpt.init(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def draft_params():
    """A draft with the target's architecture but different weights: it
    genuinely disagrees with the target, which is the adversarial case
    for the accepted-prefix rule (and, sharing the target's shapes, it
    rides the already-compiled program ladder)."""
    return gpt.init(jax.random.PRNGKey(7), CFG)


def naive_greedy(params, prompt, max_new, cfg=CFG):
    """Reference decode: full-context uncached forward every step."""
    toks = list(prompt)
    for _ in range(max_new):
        logits = gpt.apply(params, cfg, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def make_engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("cache", CACHE)
    return InferenceEngine(params, CFG, **kw)


def assert_pool_accounted(eng):
    """Idle-engine allocator invariant: every block is either free or
    held by exactly the prefix cache."""
    stats = eng.stats()
    assert stats.free_blocks == eng.cache.num_blocks \
        - stats.prefix_cached_entries, stats


# -- allocator refcounts: the substrate COW leans on --------------------------

def test_allocator_refcount_sharing():
    alloc = BlockAllocator(KVCacheConfig(num_blocks=4, block_size=8))
    a = alloc.allocate(16)  # 2 blocks at refcount 1
    assert [alloc.refcount(b) for b in a] == [1, 1]
    alloc.retain(a)         # second owner (a prefix-cache entry, say)
    assert [alloc.refcount(b) for b in a] == [2, 2]

    # never freed while referenced: first release drops a reference but
    # returns nothing to the free list
    alloc.release(a)
    assert alloc.free_blocks() == 2
    assert [alloc.refcount(b) for b in a] == [1, 1]
    alloc.release(a)
    assert alloc.free_blocks() == 4
    assert [alloc.refcount(b) for b in a] == [0, 0]

    # over-release of a now-free block and retain of a dead/bogus block
    # are bookkeeping bugs, not soft errors
    with pytest.raises(ValueError):
        alloc.release(a[:1])
    with pytest.raises(ValueError):
        alloc.retain(a[:1])
    with pytest.raises(ValueError):
        alloc.retain([99])


def test_prefix_cache_match_register_evict():
    cache = KVCacheConfig(num_blocks=8, block_size=8)
    alloc = BlockAllocator(cache)
    pc = PrefixCache(cache, alloc)
    prompt = list(range(1, 21))          # 2 full blocks + 4-token tail
    blocks = alloc.allocate(len(prompt))  # 3 blocks, as a sequence would

    pc.register(prompt, blocks)
    assert len(pc) == 3
    assert [alloc.refcount(b) for b in blocks] == [2, 2, 2]

    # byte-identical prompt hits all three entries, including the tail
    m = pc.match(prompt)
    assert m.blocks == blocks and m.shared_len == 20
    assert [alloc.refcount(b) for b in blocks] == [3, 3, 3]
    alloc.release(m.blocks)

    # a different tail only matches the full blocks (tail keys include
    # the exact tail tokens)
    m = pc.match(prompt[:16] + [55, 56])
    assert m.blocks == blocks[:2] and m.shared_len == 16
    alloc.release(m.blocks)

    # divergence in block 0 shares nothing — chained hashes make a key
    # identify tokens AND absolute position
    m = pc.match([77] + prompt[1:])
    assert m.blocks == [] and m.shared_len == 0

    # retire the sequence: blocks survive on the cache's reference alone
    alloc.release(blocks)
    assert alloc.free_blocks() == 5
    assert [alloc.refcount(b) for b in blocks] == [1, 1, 1]

    # eviction drops cache references until the pool has headroom
    dropped = pc.evict(cache.num_blocks)
    assert dropped == 3 and len(pc) == 0
    assert alloc.free_blocks() == cache.num_blocks

    # flush releases everything it holds (hot-swap invalidation)
    blocks = alloc.allocate(8)
    pc.register(prompt[:8], blocks)
    alloc.release(blocks)
    assert pc.flush() == 1
    assert alloc.free_blocks() == cache.num_blocks


# -- COW prefix sharing through the engine ------------------------------------

def test_prefix_sharing_parity_counters_and_cow(params):
    """Repeat and prefix-sharing prompts alias cached blocks (hit/miss
    counters prove it) and still decode bit-identically — the COW fork
    of the written block is what keeps the aliased copy immutable."""
    base = list(range(1, 12))            # 1 full block + 3-token tail
    fork = base[:8] + [61, 62, 63]       # shares the full block only
    expected = {tuple(p): naive_greedy(params, p, 8)
                for p in (base, fork)}
    with make_engine(params, prefix_cache=True) as eng:
        r1 = eng.generate(base, 8)       # cold: everything misses
        assert r1.tokens == expected[tuple(base)]
        assert (r1.prefix_hit_blocks, r1.prefix_miss_blocks) == (0, 2)

        r2 = eng.generate(base, 8)       # exact repeat: full + tail hit
        assert r2.tokens == r1.tokens    # COW fork, not corruption
        assert (r2.prefix_hit_blocks, r2.prefix_miss_blocks) == (2, 0)

        r3 = eng.generate(fork, 8)       # shares the full block only
        assert r3.tokens == expected[tuple(fork)]
        assert (r3.prefix_hit_blocks, r3.prefix_miss_blocks) == (1, 1)

        stats = eng.stats()
        assert stats.prefix_hit_blocks == 3
        assert stats.prefix_miss_blocks == 3
        assert stats.prefix_cached_entries > 0
        assert_pool_accounted(eng)
        dump = eng.registry.dump()   # Prometheus text exposition
    assert "prefix_cache_hit_blocks_total 3" in dump
    assert "prefix_cache_miss_blocks_total 3" in dump


# -- speculative decoding -----------------------------------------------------

def test_speculative_parity_with_disagreeing_draft(params, draft_params):
    """A randomly-initialised draft disagrees with the target almost
    everywhere; the accepted-prefix rule must still emit exactly the
    target's greedy tokens — a bad draft only costs speed."""
    expected = {i: naive_greedy(params, p, 8)
                for i, p in enumerate(PROMPTS)}
    with make_engine(params, speculative_k=3, draft_params=draft_params,
                     draft_cfg=CFG) as eng:
        handles = [eng.submit(p, 8, request_id=str(i))
                   for i, p in enumerate(PROMPTS)]
        results = [h.result(timeout=120.0) for h in handles]
        stats = eng.stats()
    for i, r in enumerate(results):
        assert r.tokens == expected[int(r.request_id)], f"request {i}"
        assert 0 <= r.spec_accepted <= r.spec_proposed
        assert r.spec_proposed > 0
        assert 0.0 <= r.spec_acceptance <= 1.0
    assert stats.spec_tokens_proposed == sum(r.spec_proposed
                                             for r in results)
    assert stats.spec_acceptance_rate == pytest.approx(
        stats.spec_tokens_accepted / stats.spec_tokens_proposed)


def test_identity_extension_and_prefix_slice(params):
    """extend_with_identity_layers is logit-exact (zeroed residual adds
    contribute nothing) and slice_prefix_layers inverts it — the pair
    that builds the bench's perfectly-distilled draft."""
    ext_params, ext_cfg = gpt.extend_with_identity_layers(params, CFG, 2)
    assert ext_cfg.n_layers == 4
    x = jnp.asarray([PROMPTS[1]], jnp.int32)
    assert bool(jnp.array_equal(gpt.apply(ext_params, ext_cfg, x),
                                gpt.apply(params, CFG, x)))
    sliced, scfg = gpt.slice_prefix_layers(ext_params, ext_cfg, 2)
    assert scfg.n_layers == 2
    assert all(bool(jnp.array_equal(a, b)) for a, b in
               zip(jax.tree_util.tree_leaves(sliced),
                   jax.tree_util.tree_leaves(params)))
    with pytest.raises(ValueError):
        gpt.slice_prefix_layers(ext_params, ext_cfg, 0)
    with pytest.raises(ValueError):
        gpt.slice_prefix_layers(ext_params, ext_cfg, 5)


def test_speculative_identity_draft_accepts_everything(params):
    """Target = identity-extended core, draft = its layer-slice ⇒ both
    compute the same function, so every proposal verifies: acceptance
    is exactly 1.0 and output still matches the core's greedy tokens.
    The bench's ≥2x speedup lane is this setup at scale."""
    ext_params, ext_cfg = gpt.extend_with_identity_layers(params, CFG, 2)
    dparams, dcfg = gpt.slice_prefix_layers(ext_params, ext_cfg, 2)
    expected = naive_greedy(params, PROMPTS[0], 8)
    with InferenceEngine(ext_params, ext_cfg, buckets=BUCKETS, cache=CACHE,
                         speculative_k=3, draft_params=dparams,
                         draft_cfg=dcfg) as eng:
        r = eng.generate(PROMPTS[0], 8)
    assert r.tokens == expected
    assert r.spec_acceptance == 1.0


# -- chunked prefill ----------------------------------------------------------

def test_chunked_prefill_long_prompt_parity(params):
    """Chunking lifts the prompt-length admission limit: a prompt longer
    than the largest prefill bucket is served chunk-at-a-time, decoding
    bit-identically, while short co-resident requests keep decoding."""
    long_prompt = [i % 90 + 1 for i in range(20)]   # > max bucket 16
    with make_engine(params) as eng:
        with pytest.raises(ValueError, match="exceeds the largest"):
            eng.submit(long_prompt, 4)
    with pytest.raises(ValueError):
        make_engine(params, chunk_prefill_len=5)    # not a bucket size

    expected_long = naive_greedy(params, long_prompt, 6)
    expected_short = naive_greedy(params, PROMPTS[0], 6)
    with make_engine(params, chunk_prefill_len=8) as eng:
        h_long = eng.submit(long_prompt, 6)
        h_short = eng.submit(PROMPTS[0], 6)
        assert h_long.result(timeout=120.0).tokens == expected_long
        assert h_short.result(timeout=120.0).tokens == expected_short
        assert eng.stats().free_blocks == CACHE.num_blocks


def test_run_static_chunked_replay(params):
    """run_static shares the chunked prefill path, so a chunked-engine
    workload (long prompts included) replays under the static policy
    with identical tokens — the bench's A/B depends on this."""
    long_prompt = [i % 90 + 1 for i in range(20)]
    reqs = [(long_prompt, 6), (PROMPTS[0], 6), (PROMPTS[2], 6)]
    with make_engine(params, chunk_prefill_len=8) as eng:
        cont = [eng.generate(p, mx) for p, mx in reqs]
        static = eng.run_static(reqs, timeout=120.0)
    for c, s in zip(cont, static):
        assert s.tokens == c.tokens
        assert s.finish_reason == "length"


# -- all three at once: budgeted warmup, no mid-traffic compiles --------------

def test_all_features_warmup_budget_and_parity():
    """With prefix sharing + speculation + chunking on, warmup compiles
    EXACTLY the extended program budget (base ladder, draft ladder, k+1
    verify per batch bucket, two block-copies) and traffic adds nothing.
    The jit cache probes are process-global (they key on the underlying
    function, which every engine shares), so the assertion is on the
    warmup DELTA — and the shapes here (vocab 101, 12-block pool,
    1-layer draft) are unique to this test, so the delta is exactly
    this engine's ladder."""
    cfg = gpt.GPTConfig(vocab_size=101, n_layers=2, d_model=32, n_heads=4,
                        d_ff=64, max_seq_len=48, remat=False,
                        attention_impl="mha")
    params = gpt.init(jax.random.PRNGKey(11), cfg)
    # a 1-layer draft: distinct param/pool shapes from the target, so
    # the draft ladder really is its own 9 programs (a same-shape draft
    # would alias the target's cache entries and land under budget)
    draft_cfg = dataclasses.replace(cfg, n_layers=1)
    draft = gpt.init(jax.random.PRNGKey(12), draft_cfg)
    cache = KVCacheConfig(num_blocks=12, block_size=8)
    buckets = BucketSpec.build(2, 16)   # small ladder: 16 programs warmed
    long_prompt = [i % 90 + 1 for i in range(20)]
    expected = naive_greedy(params, long_prompt, 8, cfg=cfg)
    with InferenceEngine(params, cfg, buckets=buckets, cache=cache,
                         prefix_cache=True, chunk_prefill_len=8,
                         speculative_k=3, draft_params=draft,
                         draft_cfg=draft_cfg) as eng:
        budget = eng.program_budget()
        assert budget == buckets.extended_budget(
            speculative=True, prefix_cache=True)
        before = eng.programs_compiled()
        compiled = eng.warmup()
        assert compiled - before == budget
        for _ in range(2):   # second pass hits the prefix cache
            assert eng.generate(long_prompt, 8).tokens == expected
        hs = [eng.submit(p, 4) for p in PROMPTS]
        for h in hs:
            h.result(timeout=120.0)
        assert eng.programs_compiled() == compiled
        assert eng.stats().prefix_hit_blocks > 0
        assert_pool_accounted(eng)


# -- abort accounting with sharing live ---------------------------------------

def test_abort_mid_decode_releases_blocks(params, draft_params):
    """Aborting a shared-prefix speculative request releases exactly the
    sequence's references: cached blocks stay resident (the cache still
    holds them), everything else returns to the free list."""
    with make_engine(params, prefix_cache=True, speculative_k=3,
                     draft_params=draft_params, draft_cfg=CFG,
                     iteration_floor_s=0.05) as eng:
        eng.generate(PROMPTS[1], 4)          # seed the prefix cache
        h = eng.submit(PROMPTS[1], 30)
        time.sleep(0.25)                     # let a few iterations run
        assert eng.abort(h)
        r = h.result(timeout=120.0)
        assert r.finish_reason == "aborted"
        assert len(r.tokens) < 30
        assert not eng.abort(h)              # already finished
        eng.wait_idle(timeout=60.0)
        assert_pool_accounted(eng)


# -- HTTP surface -------------------------------------------------------------

def test_http_exposes_speed_fields_and_metrics(params, draft_params):
    with make_engine(params, prefix_cache=True, speculative_k=3,
                     draft_params=draft_params, draft_cfg=CFG) as eng, \
            ServingHTTPServer(eng) as srv:
        generate_over_http(srv.url, PROMPTS[1], max_new_tokens=5)
        out = generate_over_http(srv.url, PROMPTS[1], max_new_tokens=5)
        lat = out["latency"]
        assert lat["prefix_hit_blocks"] == 2   # full block + exact tail
        assert lat["prefix_miss_blocks"] == 0
        assert lat["spec_proposed"] >= lat["spec_accepted"] >= 0
        assert lat["spec_acceptance"] is None or \
            0.0 <= lat["spec_acceptance"] <= 1.0

        with urllib.request.urlopen(f"{srv.url}/metrics",
                                    timeout=30) as resp:
            metrics = resp.read().decode()
    for name in ("prefix_cache_hit_blocks_total",
                 "prefix_cache_miss_blocks_total",
                 "spec_acceptance_rate",
                 "serving_spec_tokens_proposed_total",
                 "serving_spec_tokens_accepted_total"):
        assert name in metrics, name


# -- FLOPs accounting ---------------------------------------------------------

def test_speculative_flops_hand_checks():
    """d=4, f=8, L=2, V=16 (the suite's worked example): decode at
    context 10 costs 960, at 11 costs 992, so a k=1 verify call is
    1952 — the sum of the two consecutive decode steps it replaces."""
    class _Tiny:
        d_model, d_ff, n_layers, vocab_size = 4, 8, 2, 16

    verify = flops_mod.gpt_verify_flops(_Tiny, 10, 1)
    assert verify["total"] == 1952
    for k in (1, 3):
        assert flops_mod.gpt_verify_flops(_Tiny, 10, k)["total"] == sum(
            flops_mod.gpt_decode_flops_per_token(_Tiny, 10 + i)["total"]
            for i in range(k + 1))

    step = flops_mod.gpt_speculative_step_flops(_Tiny, _Tiny, 10, 3)
    assert step["total"] == step["draft"] + step["verify"]
    assert step["verify"] == flops_mod.gpt_verify_flops(_Tiny, 10, 3)["total"]
    assert step["draft"] == sum(
        flops_mod.gpt_decode_flops_per_token(_Tiny, 10 + i)["total"]
        for i in range(3))

    # prefix sharing: skipping s prefill tokens saves exactly s tokens
    # at full-sequence-length cost, and at least one token always pays
    # (the re-scored last prompt position)
    per_tok = sum(flops_mod.gpt_forward_flops_per_token(_Tiny, 10).values())
    full = flops_mod.gpt_generation_flops(_Tiny, 10, 4)
    shared = flops_mod.gpt_generation_flops(_Tiny, 10, 4, prefill_from=6)
    assert shared == pytest.approx(full - 6 * per_tok)
    assert flops_mod.gpt_generation_flops(_Tiny, 10, 4, prefill_from=10) \
        == flops_mod.gpt_generation_flops(_Tiny, 10, 4, prefill_from=9)


# -- config surface -----------------------------------------------------------

def test_speculative_config_roundtrip_and_validation():
    raw = {"prefix_cache": True, "chunk_prefill_len": 16,
           "speculative": {"enabled": True, "k": 3, "draft_layers": 2,
                           "draft_d_model": 64, "draft_n_heads": 2,
                           "draft_d_ff": 256}}
    scfg = ServingConfig.from_dict(raw)
    assert scfg.prefix_cache and scfg.chunk_prefill_len == 16
    assert scfg.speculative.enabled and scfg.speculative.k == 3
    assert scfg.speculative.draft_layers == 2

    with pytest.raises(ConfigError):
        SpeculativeConfig.from_dict({"k": 0})
    with pytest.raises(ConfigError):
        SpeculativeConfig.from_dict({"k": 17})
    with pytest.raises(ConfigError):
        SpeculativeConfig.from_dict({"draft_d_model": 10,
                                     "draft_n_heads": 4})
    with pytest.raises(ConfigError):
        ServingConfig.from_dict({"chunk_prefill_len": 5})  # not pow2

    good = {"name": "e", "entrypoint": "m:T",
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 1}},
            "serving": dict(raw, max_batch=4)}
    assert schema.validate(good) == []
    bad = json.loads(json.dumps(good))
    bad["serving"]["speculative"]["draught"] = 1
    errors = schema.validate(bad)
    assert any("speculative.draught" in e and "unknown field" in e
               for e in errors)
