"""API load smoke: the BASELINE.md perf gate, in-process k6 analogue.

≈ the reference's k6 API performance tests
(performance/src/api_performance_tests.ts:336-374): N concurrent virtual
users hammer the read endpoints of a master with realistic history and the
p95 latency must stay under 1 s. Runs against the sqlite store (the
default) with thousands of metric records so indexed reads are actually
exercised.
"""
import json
import statistics
import subprocess
import threading
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"

VUS = 25             # concurrent virtual users (the reference gate's 25)
REQS_PER_VU = 40
P95_BUDGET_S = 1.0   # BASELINE.md: p95 < 1 s

# control-plane artifact (gitignored): submit→running latency recorded per
# run so history is comparable; the budget is ADVISORY — printed, not
# asserted (docs/observability.md)
ARTIFACT = REPO / "tests" / "artifacts" / "control_plane_load.json"
S2R_P95_ADVISORY_S = 30.0


@pytest.fixture(scope="module")
def loaded_master(tmp_path_factory):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("load")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "data"), "--db", "sqlite"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")

    def req(method, path, body=None):
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            method=method, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=30) as resp:
            return json.loads(resp.read() or "{}")

    # seed realistic history: an experiment with trials and a deep metric
    # stream (the read path that used to rescan whole files per request)
    exp = req("POST", "/api/v1/experiments", {"config": {
        "name": "load", "entrypoint": "m:T",
        "searcher": {"name": "custom", "metric": "loss"},
        "hyperparameters": {"lr": 0.1}}})["experiment"]
    req("POST", f"/api/v1/experiments/{exp['id']}/searcher/operations",
        {"ops": [{"type": "create", "request_id": 0, "hparams": {"lr": 0.1}},
                 {"type": "create", "request_id": 1, "hparams": {"lr": 0.2}},
                 {"type": "validate_after", "request_id": 0, "units": 100},
                 {"type": "validate_after", "request_id": 1, "units": 100}]})
    trials = req("GET", f"/api/v1/experiments/{exp['id']}")["trials"]
    t0 = time.time()
    for t in trials:
        for step in range(0, 2000, 50):
            req("POST", f"/api/v1/trials/{t['id']}/metrics",
                {"group": "training", "steps_completed": step,
                 "metrics": {"loss": 1.0 / (step + 1)}})
    alloc = f"trial-{trials[0]['id']}.0"
    for i in range(0, 2000, 100):
        req("POST", f"/api/v1/allocations/{alloc}/logs",
            {"logs": [f"line-{i + j}" for j in range(100)]})
    seed_s = time.time() - t0

    yield {"port": port, "exp_id": exp["id"],
           "trial_ids": [t["id"] for t in trials], "alloc": alloc,
           "seed_s": seed_s}
    proc.kill()
    proc.wait(timeout=10)


def test_p95_under_budget_at_25_vus(loaded_master):
    port = loaded_master["port"]
    exp_id = loaded_master["exp_id"]
    trial_ids = loaded_master["trial_ids"]
    alloc = loaded_master["alloc"]

    paths = [
        "/api/v1/experiments",
        f"/api/v1/experiments/{exp_id}",
        f"/api/v1/trials/{trial_ids[0]}/metrics?limit=1000",
        f"/api/v1/trials/{trial_ids[-1]}/metrics?limit=200",
        f"/api/v1/allocations/{alloc}/logs?limit=500",
        "/api/v1/agents",
        "/api/v1/job-queue",
        "/api/v1/master",
    ]
    latencies = []
    errors = []
    lock = threading.Lock()

    def vu(vu_idx):
        for i in range(REQS_PER_VU):
            path = paths[(vu_idx + i) % len(paths)]
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                    r.read()
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)
            except Exception as exc:  # noqa: BLE001
                with lock:
                    errors.append(f"{path}: {exc!r}")

    threads = [threading.Thread(target=vu, args=(i,)) for i in range(VUS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    assert not errors, errors[:5]
    assert len(latencies) == VUS * REQS_PER_VU
    latencies.sort()
    p50 = latencies[len(latencies) // 2]
    p95 = latencies[int(len(latencies) * 0.95)]
    rps = len(latencies) / wall
    print(f"\n[load] {VUS} VUs x {REQS_PER_VU} reqs: "
          f"p50={p50 * 1000:.1f}ms p95={p95 * 1000:.1f}ms "
          f"({rps:.0f} req/s, seed took {loaded_master['seed_s']:.1f}s)")
    assert p95 < P95_BUDGET_S, f"p95 {p95:.3f}s over the {P95_BUDGET_S}s gate"


def test_indexed_offset_reads_do_not_degrade(loaded_master):
    """Paged reads deep into the stream must not rescan from the start:
    the last page must cost about the same as the first."""
    port = loaded_master["port"]
    trial_id = loaded_master["trial_ids"][0]

    def timed(path, n=30):
        out = []
        for _ in range(n):
            t0 = time.perf_counter()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                r.read()
            out.append(time.perf_counter() - t0)
        return statistics.median(out)

    base = f"/api/v1/trials/{trial_id}/metrics?limit=10"
    first = timed(base)
    # the metric route has no offset param; use the logs stream which pages
    alloc = loaded_master["alloc"]
    early = timed(f"/api/v1/allocations/{alloc}/logs?limit=10&offset=0")
    late = timed(f"/api/v1/allocations/{alloc}/logs?limit=10&offset=1950")
    print(f"\n[load] paged read: first-page {early * 1000:.2f}ms, "
          f"last-page {late * 1000:.2f}ms (metrics head {first * 1000:.2f}ms)")
    # generous bound: deep pages may cost more, but not order-of-magnitude
    assert late < max(early * 20, 0.25)


def test_sched_families_nonzero_after_load(loaded_master):
    """Control-plane telemetry after a load run (docs/observability.md):
    the dct_master_sched_* families are present and non-zero, and the
    p95 submit→running latency lands in the JSON artifact as an advisory
    budget (printed, never a hard assert — CI boxes vary too much)."""
    port = loaded_master["port"]

    def req(method, path, body=None):
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            method=method, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=30) as resp:
            return json.loads(resp.read() or "{}")

    # run the seeded trials to completion through a simulated agent so the
    # whole lifecycle (submit→schedule→run→end) populates the reservoirs
    req("POST", "/api/v1/agents/register",
        {"id": "load-smoke-agent", "slots": 4, "topology": "fake-4",
         "address": "127.0.0.1:0", "resource_pool": "default"})
    deadline = time.time() + 30
    done = 0
    while done < len(loaded_master["trial_ids"]) and time.time() < deadline:
        hb = req("POST", "/api/v1/agents/load-smoke-agent/heartbeat",
                 {"exited": [], "running": []})
        for cmd in hb.get("commands", []):
            if cmd.get("type") != "start":
                continue
            aid = cmd["allocation_id"]
            trial = cmd.get("trial") or {}
            req("POST", "/api/v1/agents/load-smoke-agent/task_event",
                {"allocation_id": aid, "event": "running"})
            req("POST", f"/api/v1/trials/{trial['id']}/searcher/completed_op",
                {"metric": 0.0, "units": trial.get("target_units", 1)})
            req("POST", "/api/v1/agents/load-smoke-agent/task_event",
                {"allocation_id": aid, "event": "exited", "exit_code": 0})
            done += 1
        time.sleep(0.1)
    assert done == len(loaded_master["trial_ids"]), \
        f"only {done} trials ran within the deadline"

    from determined_clone_tpu.telemetry.metrics import parse_prometheus_text

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode("utf-8")
    parsed = parse_prometheus_text(text)
    values = {}
    for name, labels, value in parsed["samples"]:
        values.setdefault(name, 0.0)
        values[name] += value
    for family in ("dct_master_sched_submitted_total",
                   "dct_master_sched_scheduled_total",
                   "dct_master_sched_running_total",
                   "dct_master_sched_completed_total",
                   "dct_master_sched_decisions_total",
                   "dct_master_sched_considered_total",
                   "dct_master_sched_submit_to_running_seconds_count"):
        assert values.get(family, 0) > 0, f"{family} missing or zero"
    assert parsed["types"][
        "dct_master_sched_submit_to_running_seconds"] == "summary"

    sched = req("GET", "/api/v1/cluster/scheduler")
    s2r = sched["latency"]["submit_to_running_seconds"]
    assert s2r["count"] >= len(loaded_master["trial_ids"])
    p95 = s2r["p95"]
    ARTIFACT.parent.mkdir(parents=True, exist_ok=True)
    with open(ARTIFACT, "w") as f:
        json.dump({"recorded_at": time.time(),
                   "submit_to_running_s": s2r,
                   "advisory_p95_budget_s": S2R_P95_ADVISORY_S,
                   "counters": sched["counters"]}, f, indent=2)
    verdict = ("within" if p95 <= S2R_P95_ADVISORY_S
               else "OVER (advisory only)")
    print(f"\n[load] submit→running p95={p95:.3f}s — {verdict} the "
          f"{S2R_P95_ADVISORY_S:.0f}s advisory budget; artifact: {ARTIFACT}")
