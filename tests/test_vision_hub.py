"""Vision model hub: ViT backbone + single-stage detection trials.

≈ the reference's mmdetection model-hub tests (trials driven through the
controller on tiny synthetic data, model_hub/tests/) — here the whole
domain is JAX-native (models/vit.py, model_hub/vision.py) and runs
through the real Trainer.
"""
import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_clone_tpu import core
from determined_clone_tpu.config.experiment import ExperimentConfig
from determined_clone_tpu.model_hub import (
    DetectorConfig,
    SingleStageDetectionTrial,
    ViTClassificationTrial,
    detection_loss,
    detector_apply,
    detector_init,
    synthetic_detection_batches,
)
from determined_clone_tpu.models import vit
from determined_clone_tpu.training import Trainer, TrialContext


class TestViT:
    def test_forward_shapes(self):
        cfg = vit.ViTConfig.tiny()
        params = vit.init(jax.random.PRNGKey(0), cfg)
        images = jnp.ones((2, cfg.image_size, cfg.image_size, 3))
        logits = vit.apply(params, cfg, images)
        assert logits.shape == (2, cfg.n_classes)
        tokens = vit.encode(params, cfg, images)
        assert tokens.shape == (2, 1 + cfg.n_patches, cfg.d_model)

    def test_patchify_is_invertible_layout(self):
        cfg = vit.ViTConfig.tiny()
        images = jnp.arange(2 * 32 * 32 * 3, dtype=jnp.float32).reshape(
            2, 32, 32, 3)
        patches = vit.patchify(cfg, images)
        assert patches.shape == (2, cfg.n_patches, cfg.patch_dim)
        # first patch = top-left 8x8 block, row-major
        expect = images[0, :8, :8, :].reshape(-1)
        np.testing.assert_array_equal(patches[0, 0], expect)

    def test_remat_matches_plain(self):
        cfg = vit.ViTConfig.tiny()
        params = vit.init(jax.random.PRNGKey(1), cfg)
        images = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
        plain = vit.apply(params, cfg, images)
        import dataclasses

        rcfg = dataclasses.replace(cfg, remat=True)
        np.testing.assert_allclose(plain, vit.apply(params, rcfg, images),
                                   rtol=1e-5)

    def test_loss_decreases(self):
        cfg = vit.ViTConfig.tiny()
        params = vit.init(jax.random.PRNGKey(0), cfg)
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)
        images = jax.random.normal(jax.random.PRNGKey(3), (16, 32, 32, 3))
        labels = jnp.arange(16) % cfg.n_classes

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(vit.loss_fn)(
                params, cfg, images, labels)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        first = None
        for _ in range(20):
            params, opt_state, loss = step(params, opt_state)
            first = first if first is not None else float(loss)
        assert float(loss) < first * 0.8


class TestDetector:
    def test_apply_shapes(self):
        cfg = DetectorConfig(image_size=64, n_classes=4)
        params = detector_init(jax.random.PRNGKey(0), cfg)
        preds = detector_apply(params, cfg, jnp.ones((2, 64, 64, 3)))
        g = cfg.grid
        assert preds["objectness"].shape == (2, g, g)
        assert preds["boxes"].shape == (2, g, g, 4)
        assert preds["class_logits"].shape == (2, g, g, 4)

    def test_loss_masks_padding(self):
        cfg = DetectorConfig(image_size=32, widths=(8, 16), n_classes=3)
        params = detector_init(jax.random.PRNGKey(0), cfg)
        images = jnp.zeros((1, 32, 32, 3))
        boxes = jnp.array([[[0.5, 0.5, 0.2, 0.2], [0.9, 0.9, 0.1, 0.1]]])
        labels = jnp.array([[1, 2]])
        # with the second box masked out, its cell must not contribute
        loss_masked, _ = detection_loss(params, cfg, images, boxes, labels,
                                        jnp.array([[1.0, 0.0]]))
        loss_full, _ = detection_loss(params, cfg, images, boxes, labels,
                                      jnp.array([[1.0, 1.0]]))
        assert float(loss_masked) != float(loss_full)

    def test_detection_trial_converges(self, tmp_path):
        config = ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 30}},
            "scheduling_unit": 15,
            "resources": {"slots_per_trial": 1},
        })

        class SyntheticDetection(SingleStageDetectionTrial):
            def detector_config(self):
                return DetectorConfig(image_size=32, widths=(8, 16),
                                      n_classes=3)

            def training_data(self):
                yield from synthetic_detection_batches(
                    self.detector_config(), batch_size=8, n_batches=30)

            def validation_data(self):
                return synthetic_detection_batches(
                    self.detector_config(), batch_size=8, n_batches=2,
                    seed=99)

        with contextlib.ExitStack() as stack:
            ctx = stack.enter_context(
                core.init(config=config, storage_path=str(tmp_path)))
            tctx = TrialContext(config=config, hparams={"lr": 3e-3},
                                core=ctx)
            result = Trainer(SyntheticDetection(tctx)).fit()
        assert result["batches_trained"] == 30
        # training metrics move: colored-rectangle classes are learnable
        assert np.isfinite(result["last_validation"]["loss"])


class TestViTTrial:
    def test_vit_classification_trial(self, tmp_path):
        config = ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 8}},
            "scheduling_unit": 4,
            "resources": {"slots_per_trial": 1},
        })

        class SyntheticViT(ViTClassificationTrial):
            @staticmethod
            def _batches(seed, n):
                rng = np.random.RandomState(seed)
                for _ in range(n):
                    labels = rng.randint(0, 10, size=8)
                    # class-dependent mean makes the task learnable
                    images = rng.randn(8, 32, 32, 3).astype(np.float32)
                    images += labels[:, None, None, None] / 10.0
                    yield {"image": images, "label": labels}

            def training_data(self):
                return self._batches(0, 8)

            def validation_data(self):
                return self._batches(99, 2)

        with contextlib.ExitStack() as stack:
            ctx = stack.enter_context(
                core.init(config=config, storage_path=str(tmp_path)))
            tctx = TrialContext(
                config=config,
                hparams={"lr": 1e-3, "full_precision": True,
                         "global_batch_size": 8},
                core=ctx)
            result = Trainer(SyntheticViT(tctx)).fit()
        assert result["batches_trained"] == 8
        assert np.isfinite(result["last_validation"]["loss"])
