"""SSO login against an OIDC-shaped fake identity provider.

≈ the reference's OIDC plugin hooks (user service SSO integration): the
master redirects to the issuer's /authorize, exchanges the callback code
at /token, auto-provisions the user, and hands the session token to the
SPA via a URL fragment. The IdP here is an in-process HTTP server.
"""
import json
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tests.test_platform import build_binaries, start_master


class FakeIdP(BaseHTTPRequestHandler):
    """Authorization server: /authorize bounces straight back with a code;
    /token redeems it for an identity."""

    codes = {}
    identity = {"username": "sso-user", "email": "sso-user@example.com",
                "name": "S. So"}
    token_requests = []

    def do_GET(self):
        url = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(url.query))
        if url.path == "/authorize":
            code = f"code-{len(self.codes)}"
            self.codes[code] = True
            dest = (f"{q['redirect_uri']}?code={code}"
                    f"&state={q['state']}")
            self.send_response(302)
            self.send_header("Location", dest)
            self.end_headers()
        else:
            self.send_response(404)
            self.end_headers()

    def do_POST(self):
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0))))
        type(self).token_requests.append(body)
        if self.path == "/token" and self.codes.pop(body.get("code"), None):
            payload = json.dumps(self.identity).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        else:
            self.send_response(401)
            self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture(scope="module")
def idp():
    server = HTTPServer(("127.0.0.1", 0), FakeIdP)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield server.server_port
    server.shutdown()


@pytest.fixture(scope="module")
def master(tmp_path_factory, idp):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("sso")
    # hostname (not IP literal) issuer: exercises the master's outbound
    # DNS resolution on the token exchange
    proc, session, port = start_master(
        tmp, "--auth-required",
        "--sso-issuer", f"localhost:{idp}",
        "--sso-client-id", "dct-test",
        "--sso-client-secret", "s3cret")
    yield {"session": session, "port": port, "proc": proc}
    proc.kill()
    proc.wait(timeout=10)


def fetch(port, path, follow=False):
    class NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **kw):
            return None

    opener = (urllib.request.build_opener() if follow
              else urllib.request.build_opener(NoRedirect))
    try:
        resp = opener.open(f"http://127.0.0.1:{port}{path}", timeout=10)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_sso_login_flow(master, idp):
    port = master["port"]
    # 1. the login route bounces to the issuer with a state nonce
    status, headers, _ = fetch(port, "/api/v1/auth/sso/login")
    assert status == 302
    auth_url = headers["Location"]
    assert auth_url.startswith(f"http://localhost:{idp}/authorize")
    q = dict(urllib.parse.parse_qsl(urllib.parse.urlparse(auth_url).query))
    assert q["client_id"] == "dct-test" and q["state"]
    # the callback target is ABSOLUTE — a browser resolves a relative
    # Location against the IdP's origin, which would lose the flow
    assert q["redirect_uri"].startswith("http://")
    assert urllib.parse.urlparse(q["redirect_uri"]).port == port

    # 2. the browser visits the IdP, which redirects back with a code
    idp_status, idp_headers, _ = fetch(
        idp, "/authorize?" + urllib.parse.urlencode(q))
    assert idp_status == 302
    callback_url = urllib.parse.urlparse(idp_headers["Location"])
    assert callback_url.port == port  # back to the master, not the IdP
    callback = f"{callback_url.path}?{callback_url.query}"

    # 3. the callback exchanges the code and mints a session
    status, headers, _ = fetch(port, callback)
    assert status == 302
    assert headers["Location"].startswith("/#sso_token=")
    token = headers["Location"].split("=", 1)[1]
    # the exchange carried the client secret to the issuer
    assert FakeIdP.token_requests[-1]["client_secret"] == "s3cret"

    # 4. the token is a live session for the auto-provisioned user
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/auth/me",
        headers={"Authorization": f"Bearer {token}"})
    me = json.loads(urllib.request.urlopen(req, timeout=10).read())
    assert me["user"]["username"] == "sso-user"
    assert me["user"]["admin"] is False

    # 5. a replayed callback (state consumed) is rejected
    status, _, _ = fetch(port, callback)
    assert status == 401


def test_sso_user_cannot_password_login(master):
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError) as err:
        master["session"].login("sso-user", "")
    assert err.value.status == 401
    with pytest.raises(MasterError):
        master["session"].login("sso-user", "sso")


def test_sso_forged_state_rejected(master):
    status, _, _ = fetch(
        master["port"],
        "/api/v1/auth/sso/callback?code=code-x&state=forged")
    assert status == 401


def test_sso_unconfigured_master_declines(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    proc, session, port = start_master(tmp_path)
    try:
        status, _, body = fetch(port, "/api/v1/auth/sso/login")
        assert status == 400
        assert b"not configured" in body
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_sso_redirect_rejects_forged_host(master):
    """Round-3 ADVICE (low): the authorize redirect_uri must not be built
    from the request's Host header — a forged Host would point the
    authorization code at an attacker-controlled callback. Without a
    configured --sso-external-host, a non-loopback Host fails loudly (no
    code is issued at all) instead of silently redirecting to loopback."""
    import http.client

    port = master["port"]
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.putrequest("GET", "/api/v1/auth/sso/login", skip_host=True)
    conn.putheader("Host", "evil.example.com:8080")
    conn.endheaders()
    resp = conn.getresponse()
    body = resp.read().decode()
    conn.close()
    assert resp.status == 400
    assert "sso-external-host" in body
