"""Platform breadth: auth/users, workspaces/projects, model registry,
templates, webhooks — against a real C++ master (no agent needed).

≈ the reference's api_{user,workspace,model,template,webhook}_intg_test.go
surface, driven over REST like e2e_tests/tests/cluster/test_rbac.py.
"""
import json
import os
import subprocess
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"


def build_binaries():
    if MASTER_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


def start_master(tmp, *extra_args):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data"), *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            session.master_info()
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    return proc, session, port


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("platform")
    proc, session, port = start_master(tmp)
    yield {"session": session, "tmp": tmp, "port": port, "proc": proc}
    proc.kill()
    proc.wait(timeout=10)


def test_bootstrap_users_and_login(master):
    session = master["session"]
    users = {u["username"] for u in session.list_users()}
    assert {"admin", "determined"} <= users

    me = session.login("admin")  # empty password bootstrap, like det
    assert me["username"] == "admin"
    assert me["admin"] is True
    assert session.whoami()["username"] == "admin"

    from determined_clone_tpu.api.client import MasterError

    bad = type(session)(session.host, session.port, timeout=5, retries=1)
    with pytest.raises(MasterError) as err:
        bad.login("admin", "wrong-password")
    assert err.value.status == 401


def test_user_management(master):
    session = master["session"]
    u = session.create_user("alice", "s3cret")
    assert u["admin"] is False

    alice = type(session)(session.host, session.port, timeout=5, retries=1)
    assert alice.login("alice", "s3cret")["username"] == "alice"

    # deactivate blocks login
    session.post(f"/api/v1/users/{u['id']}/deactivate")
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):
        alice.login("alice", "s3cret")
    session.post(f"/api/v1/users/{u['id']}/activate")
    assert alice.login("alice", "s3cret")


def test_workspaces_and_projects(master):
    session = master["session"]
    names = {w["name"] for w in session.list_workspaces()}
    assert "Uncategorized" in names  # bootstrap workspace

    ws = session.create_workspace("research")
    proj = session.create_project(ws["id"], "llms", "gpt work")
    detail = session.get_workspace(ws["id"])
    assert {p["name"] for p in detail["projects"]} == {"Uncategorized", "llms"}
    assert proj["workspace_id"] == ws["id"]

    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):  # dup name
        session.create_workspace("research")

    # experiment create auto-registers its workspace/project
    session.create_experiment({
        "name": "ws-exp", "entrypoint": "x:Y", "workspace": "auto-ws",
        "project": "auto-proj",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
        "hyperparameters": {},
    })
    ws_names = {w["name"] for w in session.list_workspaces()}
    assert "auto-ws" in ws_names


def test_model_registry(master):
    session = master["session"]
    m = session.create_model("resnet", description="image model",
                             labels=["vision"], metadata={"arch": "cnn"})
    assert m["name"] == "resnet"
    assert session.get_model("resnet")["id"] == m["id"]

    # versions must reference a known checkpoint: report one through a trial
    exp = session.create_experiment({
        "name": "ckpt-exp", "entrypoint": "x:Y",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
        "hyperparameters": {},
    })
    detail = session.get_experiment(exp["id"])
    trial_id = detail["trials"][0]["id"]
    session.post(f"/api/v1/trials/{trial_id}/checkpoints",
                 {"uuid": "ckpt-abc", "metadata": {"steps_completed": 1},
                  "resources": {}})

    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):  # unknown checkpoint rejected
        session.register_model_version("resnet", "no-such-ckpt")

    v1 = session.register_model_version("resnet", "ckpt-abc", name="first")
    assert v1["version"] == 1
    v2 = session.register_model_version("resnet", "ckpt-abc")
    assert v2["version"] == 2

    session.request("PATCH", "/api/v1/models/resnet",
                    {"description": "updated"})
    assert session.get_model("resnet")["description"] == "updated"

    session.request("DELETE", "/api/v1/models/resnet/versions/1")
    versions = session.get(f"/api/v1/models/resnet/versions")["versions"]
    assert [v["version"] for v in versions] == [2]


def test_templates_merge_into_experiment_config(master):
    session = master["session"]
    session.set_template("tpl-base", {
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "resources": {"slots_per_trial": 2},
        "max_restarts": 3,
        "hyperparameters": {"lr": 0.1},
    })
    assert {t["name"] for t in session.list_templates()} == {"tpl-base"}

    exp = session.create_experiment({
        "name": "from-template", "entrypoint": "x:Y", "template": "tpl-base",
        "resources": {"slots_per_trial": 1},  # override wins
    })
    cfg = session.get_experiment(exp["id"])["experiment"]["config"]
    assert cfg["max_restarts"] == 3                       # from template
    assert cfg["resources"]["slots_per_trial"] == 1       # override
    assert cfg["searcher"]["max_length"]["batches"] == 4  # nested merge

    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):
        session.create_experiment({"name": "x", "entrypoint": "x:Y",
                                   "template": "missing"})


def test_webhook_fires_on_experiment_completion(master):
    session = master["session"]
    received = []

    class Hook(BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", "0"))
            received.append(json.loads(self.rfile.read(length)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    server = HTTPServer(("127.0.0.1", 0), Hook)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    hook_port = server.server_address[1]

    session.create_webhook(f"http://127.0.0.1:{hook_port}/hook",
                           triggers=["CANCELED"])
    exp = session.create_experiment({
        "name": "hooked", "entrypoint": "x:Y",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 100}},
        "hyperparameters": {},
    })
    session.kill_experiment(exp["id"])

    deadline = time.time() + 10
    while time.time() < deadline and not received:
        time.sleep(0.2)
    server.shutdown()
    assert received, "webhook never fired"
    assert received[0]["event"] == "experiment_state_change"
    assert received[0]["experiment_id"] == exp["id"]
    assert received[0]["state"] == "CANCELED"


def test_log_pattern_webhook_fires_on_matching_log(master):
    """A webhook with a log_pattern fires on matching task-log lines
    (≈ the reference's TRIGGER_TYPE_TASK_LOG webhooks)."""
    session = master["session"]
    received = []

    class Hook(BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", "0"))
            received.append(json.loads(self.rfile.read(length)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    server = HTTPServer(("127.0.0.1", 0), Hook)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    hook_port = server.server_address[1]

    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):  # bad regex rejected at creation
        session.create_webhook(f"http://127.0.0.1:{hook_port}/lp",
                               log_pattern="CUDA [")
    hook = session.create_webhook(f"http://127.0.0.1:{hook_port}/lp",
                                  log_pattern=r"OOM|CUDA error")
    assert hook["log_pattern"] == r"OOM|CUDA error"

    task = session.create_task("command", cmd=["sleep", "1"], slots=0)
    session.post(f"/api/v1/allocations/{task['id']}/logs",
                 {"logs": ["all fine", "device OOM while allocating",
                           "another OOM line"]})
    deadline = time.time() + 10
    while time.time() < deadline and not received:
        time.sleep(0.2)
    time.sleep(1.0)  # settle: a per-line double-fire must get time to land
    server.shutdown()
    assert received, "log-pattern webhook never fired"
    assert received[0]["event"] == "task_log_pattern"
    assert received[0]["allocation_id"] == task["id"]
    assert "OOM" in received[0]["line"]
    assert len(received) == 1  # one firing per batch, not per line
    session.kill_task(task["id"])


def test_auth_enforcement_and_persistence(tmp_path):
    """--auth-required master: anonymous writes are 401; sessions survive a
    master restart (snapshot persistence)."""
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    proc, session, port = start_master(tmp_path, "--auth-required")
    try:
        from determined_clone_tpu.api.client import MasterError, MasterSession

        with pytest.raises(MasterError) as err:
            session.create_workspace("nope")
        assert err.value.status == 401

        session.login("admin")
        ws = session.create_workspace("authed")
        assert ws["name"] == "authed"
        token = session.token

        # restart: sessions + workspaces persist
        proc.terminate()
        proc.wait(timeout=10)
        proc, session2, port = start_master(tmp_path, "--auth-required")
        session2.token = token
        assert session2.whoami()["username"] == "admin"
        assert "authed" in {w["name"] for w in session2.list_workspaces()}
    finally:
        proc.kill()
        proc.wait(timeout=10)
