"""Cloud checkpoint-storage backends against in-memory fake clients.

≈ the reference's moto-style storage unit tests
(harness/tests/storage/test_{s3,gcs,azure}.py): the GCS/S3/Azure managers
take injectable clients, so the full upload/download/delete/list and
store_path/restore_path surfaces run without cloud credentials. The fakes
mimic each SDK's exact call signatures (boto3 list_objects_v2 pagination
included).
"""
import os

import pytest

from determined_clone_tpu.storage import (
    AzureStorageManager,
    GCSStorageManager,
    S3StorageManager,
    build,
)
from determined_clone_tpu.config.experiment import (
    CheckpointStorageConfig,
    ConfigError,
)


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------

class FakeGCSBlob:
    def __init__(self, store, name):
        self.store, self.name = store, name

    @property
    def size(self):
        return len(self.store[self.name])

    def upload_from_filename(self, path):
        with open(path, "rb") as f:
            self.store[self.name] = f.read()

    def download_to_filename(self, path):
        with open(path, "wb") as f:
            f.write(self.store[self.name])

    def delete(self):
        del self.store[self.name]


class FakeGCSBucket:
    def __init__(self, store):
        self.store = store

    def blob(self, name):
        return FakeGCSBlob(self.store, name)


class FakeGCSClient:
    def __init__(self):
        self.store = {}

    def bucket(self, name):
        return FakeGCSBucket(self.store)

    def list_blobs(self, bucket, prefix=""):
        for name in sorted(self.store):
            if name.startswith(prefix):
                yield FakeGCSBlob(self.store, name)


class FakeS3Client:
    """Paginates at page_size to exercise the continuation-token loop."""

    def __init__(self, page_size=2):
        self.store = {}
        self.page_size = page_size

    def upload_file(self, path, bucket, key):
        with open(path, "rb") as f:
            self.store[key] = f.read()

    def download_file(self, bucket, key, path):
        with open(path, "wb") as f:
            f.write(self.store[key])

    def delete_object(self, Bucket, Key):
        del self.store[Key]

    def list_objects_v2(self, Bucket, Prefix, ContinuationToken=None):
        keys = sorted(k for k in self.store if k.startswith(Prefix))
        start = int(ContinuationToken) if ContinuationToken else 0
        page = keys[start:start + self.page_size]
        resp = {"Contents": [{"Key": k, "Size": len(self.store[k])}
                             for k in page]}
        if start + self.page_size < len(keys):
            resp["IsTruncated"] = True
            resp["NextContinuationToken"] = str(start + self.page_size)
        return resp


class FakeAzureBlobProps:
    def __init__(self, store, name):
        self.name = name
        self.size = len(store[name])


class FakeAzureDownload:
    def __init__(self, data):
        self._data = data

    def readall(self):
        return self._data


class FakeAzureContainerClient:
    def __init__(self):
        self.store = {}

    def upload_blob(self, name, data, overwrite=False):
        if name in self.store and not overwrite:
            raise RuntimeError("blob exists")
        self.store[name] = data.read()

    def list_blobs(self, name_starts_with=""):
        for name in sorted(self.store):
            if name.startswith(name_starts_with):
                yield FakeAzureBlobProps(self.store, name)

    def download_blob(self, name):
        return FakeAzureDownload(self.store[name])

    def delete_blob(self, name):
        del self.store[name]


def make_backends():
    gcs_client = FakeGCSClient()
    s3_client = FakeS3Client()
    azure_client = FakeAzureContainerClient()
    return [
        ("gcs", GCSStorageManager("bkt", "ckpts", client=gcs_client),
         gcs_client.store),
        ("s3", S3StorageManager("bkt", "ckpts", client=s3_client),
         s3_client.store),
        ("azure", AzureStorageManager("cont", prefix="ckpts",
                                      container_client=azure_client),
         azure_client.store),
    ]


def seed(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "weights.bin").write_bytes(b"W" * 64)
    (src / "sub" / "opt.bin").write_bytes(b"O" * 32)
    (src / "meta.json").write_text("{}")
    return str(src)


@pytest.mark.parametrize("name,mgr,store", make_backends(),
                         ids=lambda v: v if isinstance(v, str) else "")
def test_roundtrip_delete_and_prefix(name, mgr, store, tmp_path):
    src = seed(tmp_path)
    mgr.upload(src, "uuid-1")

    # keys carry the prefix and uuid
    assert all(k.startswith("ckpts/uuid-1/") for k in store)
    assert mgr.list_files("uuid-1") == {
        "meta.json": 2, "sub/opt.bin": 32, "weights.bin": 64}

    dst = tmp_path / "dst"
    dst.mkdir()
    mgr.download("uuid-1", str(dst))
    assert (dst / "weights.bin").read_bytes() == b"W" * 64
    assert (dst / "sub" / "opt.bin").read_bytes() == b"O" * 32

    # selective download (sharded-restore path)
    part = tmp_path / "part"
    part.mkdir()
    mgr.download("uuid-1", str(part), paths=["meta.json"])
    assert os.listdir(part) == ["meta.json"]

    # selective upload
    mgr.upload(src, "uuid-2", paths=["meta.json"])
    assert mgr.list_files("uuid-2") == {"meta.json": 2}

    mgr.delete("uuid-1")
    assert mgr.list_files("uuid-1") == {}
    assert mgr.list_files("uuid-2") == {"meta.json": 2}  # untouched


@pytest.mark.parametrize("name,mgr,store", make_backends(),
                         ids=lambda v: v if isinstance(v, str) else "")
def test_store_and_restore_path(name, mgr, store, tmp_path):
    with mgr.store_path("ck-1") as path:
        with open(os.path.join(path, "model.bin"), "wb") as f:
            f.write(b"M" * 16)
    assert mgr.list_files("ck-1") == {"model.bin": 16}
    with mgr.restore_path("ck-1") as path:
        with open(os.path.join(path, "model.bin"), "rb") as f:
            assert f.read() == b"M" * 16


@pytest.mark.parametrize("name,mgr,store", make_backends(),
                         ids=lambda v: v if isinstance(v, str) else "")
def test_prefix_sibling_ids_do_not_collide(name, mgr, store, tmp_path):
    """'ck-1' must never match 'ck-12' blobs (trailing-slash listing)."""
    src = tmp_path / "src"
    src.mkdir()
    (src / "w.bin").write_bytes(b"A")
    mgr.upload(str(src), "ck-1")
    mgr.upload(str(src), "ck-12")
    assert set(mgr.list_files("ck-1")) == {"w.bin"}
    mgr.delete("ck-1")
    assert mgr.list_files("ck-1") == {}
    assert set(mgr.list_files("ck-12")) == {"w.bin"}  # sibling untouched


def test_s3_pagination_covers_all_keys(tmp_path):
    client = FakeS3Client(page_size=2)
    mgr = S3StorageManager("bkt", client=client)
    src = tmp_path / "many"
    src.mkdir()
    for i in range(7):  # 7 keys > 3 pages of 2
        (src / f"shard-{i}.bin").write_bytes(b"x" * (i + 1))
    mgr.upload(str(src), "big")
    assert len(mgr.list_files("big")) == 7
    mgr.delete("big")
    assert client.store == {}


def test_azure_config_build_and_validation():
    cfg = CheckpointStorageConfig.from_dict(
        {"type": "azure", "container": "ckpts",
         "connection_string": "UseDevelopmentStorage=true"})
    assert cfg.container == "ckpts"
    with pytest.raises(ConfigError):
        CheckpointStorageConfig.from_dict({"type": "azure"})
    # build() reaches the azure branch (gated on the client lib here)
    with pytest.raises(RuntimeError):
        build(cfg)
