"""Pipeline parallelism (pp) and MoE/expert parallelism (ep) tests.

Runs on the 8-device virtual CPU mesh from conftest.py — the same trick as
the reference's artificial slots (agent/internal/detect/detect.go:39-56).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from determined_clone_tpu.models import gpt
from determined_clone_tpu.ops.moe import expert_capacity, moe_ffn, moe_init
from determined_clone_tpu.parallel import (
    MeshSpec,
    make_mesh,
    pipeline_apply,
    pipeline_bubble_fraction,
    shard_put,
)
from jax.sharding import NamedSharding, PartitionSpec as P


# ---------------------------------------------------------------------------
# pipeline_apply
# ---------------------------------------------------------------------------

def _affine_stage_fn(local_params, x):
    """Scan this stage's layers: x -> tanh(x @ w + b)."""
    def body(h, lp):
        return jnp.tanh(h @ lp["w"] + lp["b"]), None
    out, _ = jax.lax.scan(body, x, local_params)
    return out


def _sequential_reference(stacked, x):
    return _affine_stage_fn(stacked, x)


@pytest.mark.parametrize("pp", [2, 4])
def test_pipeline_matches_sequential(pp):
    mesh = make_mesh(MeshSpec(dp=-1, pp=pp))
    L, B, D, M = 8, 8, 16, 4
    key = jax.random.PRNGKey(0)
    kw, kb, kx = jax.random.split(key, 3)
    stacked = {
        "w": jax.random.normal(kw, (L, D, D)) * 0.3,
        "b": jax.random.normal(kb, (L, D)) * 0.1,
    }
    x = jax.random.normal(kx, (B, D))

    expected = _sequential_reference(stacked, x)

    def run(params, x):
        return pipeline_apply(_affine_stage_fn, params, x, mesh=mesh,
                              num_microbatches=M)

    got = jax.jit(run)(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential():
    mesh = make_mesh(MeshSpec(dp=-1, pp=2))
    L, B, D, M = 4, 4, 8, 2
    key = jax.random.PRNGKey(1)
    kw, kx = jax.random.split(key)
    stacked = {"w": jax.random.normal(kw, (L, D, D)) * 0.3,
               "b": jnp.zeros((L, D))}
    x = jax.random.normal(kx, (B, D))

    def loss_pp(params):
        y = pipeline_apply(_affine_stage_fn, params, x, mesh=mesh,
                           num_microbatches=M)
        return jnp.sum(y ** 2)

    def loss_seq(params):
        return jnp.sum(_sequential_reference(params, x) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_pytree_carrier():
    """Aux leaves ride through the pipeline alongside activations."""
    mesh = make_mesh(MeshSpec(dp=-1, pp=2))
    L, B, D = 4, 4, 8
    stacked = {"w": jnp.stack([jnp.eye(D) * (i + 1) for i in range(L)])}

    def stage(local, carrier):
        def body(c, lp):
            h, acc = c
            h = h @ lp["w"]
            return (h, acc + jnp.sum(h, axis=-1)), None
        (h, acc), _ = jax.lax.scan(body, (carrier["x"], carrier["acc"]), local)
        return {"x": h, "acc": acc}

    x = jnp.ones((B, D))
    carrier = {"x": x, "acc": jnp.zeros((B,))}
    out = jax.jit(lambda p, c: pipeline_apply(stage, p, c, mesh=mesh,
                                              num_microbatches=2))(stacked, carrier)
    # h after layer i: prod_{j<=i} (j+1) * ones; acc = sum_i D * i!
    factors = np.cumprod(np.arange(1, L + 1))
    np.testing.assert_allclose(np.asarray(out["x"]),
                               np.full((B, D), factors[-1]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["acc"]),
                               np.full((B,), D * factors.sum()), rtol=1e-6)


def test_pipeline_pp1_shortcut():
    mesh = make_mesh(MeshSpec(dp=-1, pp=1))
    stacked = {"w": jnp.ones((2, 4, 4)), "b": jnp.zeros((2, 4))}
    x = jnp.ones((4, 4))
    out = pipeline_apply(_affine_stage_fn, stacked, x, mesh=mesh,
                         num_microbatches=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential_reference(stacked, x)))


def test_pipeline_rejects_bad_microbatch():
    mesh = make_mesh(MeshSpec(dp=-1, pp=2))
    stacked = {"w": jnp.ones((2, 4, 4)), "b": jnp.zeros((2, 4))}
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_affine_stage_fn, stacked, jnp.ones((5, 4)), mesh=mesh,
                       num_microbatches=2)


def test_bubble_fraction():
    assert pipeline_bubble_fraction(1, 4) == pytest.approx(3 / 4)
    assert pipeline_bubble_fraction(15, 2) == pytest.approx(1 / 16)


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------

def test_moe_ffn_shapes_and_aux():
    key = jax.random.PRNGKey(0)
    params = moe_init(key, n_experts=4, d_model=16, d_ff=32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, aux = moe_ffn(params, x, k=2, compute_dtype=jnp.float32)
    assert y.shape == x.shape
    assert y.dtype == x.dtype
    assert jnp.isfinite(aux)
    # perfectly balanced routing gives aux == 1; anything routed gives >= 1-ish
    assert float(aux) > 0.5


def test_moe_capacity_drops_overflow():
    """With capacity 1 slot per expert, most tokens fall through (output 0)."""
    key = jax.random.PRNGKey(0)
    E, D = 2, 8
    params = moe_init(key, n_experts=E, d_model=D, d_ff=16)
    # Router biased so all tokens pick expert 0.
    params["router"]["kernel"] = jnp.zeros((D, E)).at[:, 0].set(1.0)
    N = 16
    x = jnp.ones((1, N, D))
    cap = expert_capacity(N, E, 0.1)
    assert cap == 1
    y, _ = moe_ffn(params, x, k=1, capacity_factor=0.1,
                   compute_dtype=jnp.float32)
    # exactly `cap` tokens routed to expert 0 produce nonzero output
    nonzero_rows = int(jnp.sum(jnp.any(jnp.abs(y[0]) > 1e-6, axis=-1)))
    assert nonzero_rows == cap


def test_moe_grads_flow():
    key = jax.random.PRNGKey(0)
    params = moe_init(key, n_experts=4, d_model=8, d_ff=16)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 8))

    def loss(p):
        y, aux = moe_ffn(p, x, compute_dtype=jnp.float32)
        return jnp.sum(y ** 2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    gnorm = sum(float(jnp.sum(jnp.abs(g))) for g in jax.tree.leaves(grads))
    assert gnorm > 0


def test_moe_gpt_trains_on_ep_mesh():
    """MoE GPT runs a jitted fwd/bwd with expert weights sharded over ep."""
    import optax

    from determined_clone_tpu.training.train_step import (
        create_train_state, make_train_step, state_shardings)

    mesh = make_mesh(MeshSpec(dp=-1, ep=2))
    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, d_model=32, n_heads=2,
                        d_ff=64, max_seq_len=32, remat=False, moe_experts=4)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    assert "moe" in params["blocks"] and "mlp_up" not in params["blocks"]

    tx = optax.adam(1e-3)
    state = create_train_state(params, tx, jax.random.PRNGKey(1))
    sharding = state_shardings(state, mesh, gpt.GPT_SHARDING_RULES)
    state = shard_put(state, sharding)
    # expert dim actually sharded over ep
    up_sh = sharding.params["blocks"]["moe"]["up"]["kernel"]
    assert "ep" in str(up_sh.spec)

    batch_sharding = NamedSharding(mesh, gpt.TOKENS_SPEC)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 17), 0, 128)
    tokens = shard_put(tokens, batch_sharding)

    def loss_fn(p, b, rng):
        return gpt.loss_fn(p, cfg, b[:, :-1], b[:, 1:]), {}

    step = make_train_step(loss_fn, tx, mesh=mesh, state_sharding=sharding,
                           batch_sharding=batch_sharding)
    state, m = step(state, tokens)
    assert jnp.isfinite(m["loss"])
    assert int(state.step) == 1


def test_pipelined_gpt_matches_scan_gpt():
    """The pipelined GPT forward equals the lax.scan forward, params shared."""
    mesh = make_mesh(MeshSpec(dp=-1, pp=2))
    cfg = gpt.GPTConfig(vocab_size=64, n_layers=4, d_model=32, n_heads=2,
                        d_ff=64, max_seq_len=16, remat=False,
                        pipeline_microbatches=2,
                        compute_dtype=jnp.float32)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)

    ref = jax.jit(lambda p, t: gpt.apply(p, cfg, t))(params, tokens)
    pp = jax.jit(lambda p, t: gpt.apply(p, cfg, t, mesh=mesh))(params, tokens)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipelined_gpt_train_step_full_mesh():
    """Full train step on a dp×pp×ep mesh: every 'missing in reference' axis
    (SURVEY.md §2.7) live at once."""
    import optax

    from determined_clone_tpu.training.train_step import (
        create_train_state, make_train_step, state_shardings)

    mesh = make_mesh(MeshSpec(dp=-1, pp=2, ep=2))
    cfg = gpt.GPTConfig(vocab_size=64, n_layers=4, d_model=32, n_heads=2,
                        d_ff=64, max_seq_len=16, remat=True, moe_experts=2,
                        pipeline_microbatches=2)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    tx = optax.adamw(1e-3)
    state = create_train_state(params, tx, jax.random.PRNGKey(1))
    sharding = state_shardings(state, mesh, gpt.GPT_PP_SHARDING_RULES)
    state = shard_put(state, sharding)

    batch_sharding = NamedSharding(mesh, gpt.TOKENS_SPEC)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 17), 0, 64)
    tokens = shard_put(tokens, batch_sharding)

    def loss_fn(p, b, rng):
        return gpt.loss_fn(p, cfg, b[:, :-1], b[:, 1:], mesh=mesh), {}

    step = make_train_step(loss_fn, tx, mesh=mesh, state_sharding=sharding,
                           batch_sharding=batch_sharding)
    state, m = step(state, tokens)
    state, m = step(state, tokens)
    assert jnp.isfinite(m["loss"])
    assert int(state.step) == 2
