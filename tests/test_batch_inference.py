"""Batch inference API: sharded offline processing with checkpointed
progress (≈ _torch_batch_process.py semantics, run with the thread-gang
simulation the Core API tests use)."""
import contextlib
from concurrent.futures import ThreadPoolExecutor

import pytest

from determined_clone_tpu import core
from determined_clone_tpu.batch_inference import (
    BatchProcessor,
    jax_batch_process,
)
from determined_clone_tpu.core import DistributedContext, FilePreemptionSource


class Collector(BatchProcessor):
    """Records which (batch_idx, items) it processed; class-level store so
    thread gangs can share."""
    seen = None  # set per-test

    def process_batch(self, batch, batch_idx):
        type(self).seen.append((batch_idx, list(batch)))

    def on_finish(self):
        type(self).seen.append(("finish", None))


def test_single_rank_processes_everything(tmp_path):
    class P(Collector):
        seen = []

    dataset = list(range(10))
    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path)))
        result = jax_batch_process(P, dataset, batch_size=3,
                                   checkpoint_interval=2, core_context=ctx)
    assert result["batches_processed"] == 4
    assert result["total_batches"] == 4
    assert not result["preempted"]
    batches = [b for b in P.seen if b[0] != "finish"]
    assert [b[0] for b in batches] == [0, 1, 2, 3]
    assert batches[-1][1] == [9]  # ragged tail batch
    assert ("finish", None) in P.seen
    assert result["storage_id"]  # final progress checkpoint


def test_multi_rank_sharding(tmp_path):
    class P(Collector):
        seen = []

    dataset = list(range(14))  # 7 batches of 2 over 3 ranks: ragged
    dists = DistributedContext.make_local_group(3)

    def run(dist):
        with contextlib.ExitStack() as stack:
            ctx = stack.enter_context(
                core.init(distributed=dist, storage_path=str(tmp_path)))
            return jax_batch_process(P, dataset, batch_size=2,
                                     checkpoint_interval=3, core_context=ctx)

    with ThreadPoolExecutor(max_workers=3) as pool:
        results = list(pool.map(run, dists))

    processed_ids = sorted(b[0] for b in P.seen if b[0] != "finish")
    assert processed_ids == list(range(7))  # every batch exactly once
    assert sum(r["batches_processed"] for r in results) == 7
    # merged per-rank progress in the final checkpoint metadata
    sid = results[0]["storage_id"]
    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path)))
        meta = ctx.checkpoint.get_metadata(sid)
    assert meta["rank_0_batches_completed"] == 3
    assert meta["rank_1_batches_completed"] == 2
    assert meta["rank_2_batches_completed"] == 2


def test_preemption_and_resume(tmp_path):
    flag = tmp_path / "preempt-flag"

    class P(Collector):
        seen = []

        def process_batch(self, batch, batch_idx):
            import time

            super().process_batch(batch, batch_idx)
            if batch_idx == 1:
                flag.write_text("now")  # trigger preemption mid-run
            time.sleep(0.15)  # give the watcher a poll cycle

    dataset = list(range(12))

    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path / "s")))
        # swap in a file-triggered preemption source
        from determined_clone_tpu.core import PreemptContext

        ctx.preempt.close()
        ctx.preempt = PreemptContext(
            ctx.distributed, FilePreemptionSource(str(flag)),
            poll_interval=0.05).start()
        result = jax_batch_process(P, dataset, batch_size=2,
                                   checkpoint_interval=100, core_context=ctx)

    assert result["preempted"]
    assert 0 < result["batches_processed"] < 6
    assert result["storage_id"]
    done_before = {b[0] for b in P.seen if b[0] != "finish"}
    assert ("finish", None) not in P.seen  # preempted: no finish hook

    # resume from the progress checkpoint: remaining batches only
    class P2(Collector):
        seen = []

    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path / "s")))
        result2 = jax_batch_process(P2, dataset, batch_size=2,
                                    checkpoint_interval=100, core_context=ctx,
                                    latest_checkpoint=result["storage_id"])
    done_after = {b[0] for b in P2.seen if b[0] != "finish"}
    assert not (done_before & done_after), "batches reprocessed after resume"
    assert done_before | done_after == set(range(6))
    assert result2["batches_processed"] == 6
    assert ("finish", None) in P2.seen


def test_max_batches_cap(tmp_path):
    class P(Collector):
        seen = []

    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path)))
        result = jax_batch_process(P, list(range(100)), batch_size=10,
                                   checkpoint_interval=100, core_context=ctx,
                                   max_batches=3)
    assert result["batches_processed"] == 3
    assert result["total_batches"] == 3


def test_dropped_examples_counted_and_warned(tmp_path, caplog):
    """max_batches clipping drops the tail examples — used to be silent;
    now counted exactly in the summary and warned once per process."""
    import logging

    import determined_clone_tpu.batch_inference as bi

    class P(Collector):
        seen = []

    bi._dropped_warned = False
    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path)))
        with caplog.at_level(logging.WARNING,
                             logger="determined_clone_tpu.batch_inference"):
            result = jax_batch_process(
                P, list(range(100)), batch_size=10, checkpoint_interval=100,
                core_context=ctx, max_batches=3)
            # second run: counter still exact, warning not repeated
            result2 = jax_batch_process(
                P, list(range(100)), batch_size=10, checkpoint_interval=100,
                core_context=ctx, max_batches=3)
    assert result["examples_dropped"] == 70
    assert result2["examples_dropped"] == 70
    warnings = [r for r in caplog.records
                if "dropped 70 examples" in r.getMessage()]
    assert len(warnings) == 1, "warn-once contract"


def test_no_drop_reports_zero(tmp_path):
    class P(Collector):
        seen = []

    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path)))
        result = jax_batch_process(P, list(range(9)), batch_size=3,
                                   checkpoint_interval=100, core_context=ctx)
    assert result["examples_dropped"] == 0


def test_resume_with_shrunken_plan_counts_dropped(tmp_path):
    """A resume whose checkpoint recorded a larger n_batches (dataset
    shrank / max_batches tightened) silently abandons the difference —
    the counter now says so."""
    class P(Collector):
        seen = []

    dataset = list(range(12))
    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(core.init(storage_path=str(tmp_path)))
        first = jax_batch_process(P, dataset, batch_size=2,
                                  checkpoint_interval=100, core_context=ctx)
        assert first["total_batches"] == 6

        class P2(Collector):
            seen = []

        second = jax_batch_process(
            P2, dataset, batch_size=2, checkpoint_interval=100,
            core_context=ctx, max_batches=4,
            latest_checkpoint=first["storage_id"])
    # 2 planned batches vanished from the resume plan = 4 examples
    assert second["examples_dropped"] == 4
