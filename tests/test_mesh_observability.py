"""Mesh observability tests (ISSUE 15): collective accounting from
post-SPMD HLO text, cross-device straggler detection, per-device Chrome
trace lanes, the MULTICHIP artifact schema, and the cluster rollup.

HLO fixtures use both replica-group syntaxes the parser understands
(explicit lists and the iota form) on a {dp: 4, tp: 2} logical mesh:
flattened partition ids arange(8).reshape(4, 2), so the dp groups are
{{0,2,4,6},{1,3,5,7}} (vary dp, hold tp) and the tp groups are
{{0,1},{2,3},{4,5},{6,7}}.
"""
import json

import jax
import jax.numpy as jnp
import pytest

from determined_clone_tpu.telemetry import MetricsRegistry
from determined_clone_tpu.telemetry.aggregate import ClusterMetricsAggregator
from determined_clone_tpu.telemetry.chrome_trace import (
    stitch_chrome_trace,
    validate_chrome_trace,
)
from determined_clone_tpu.telemetry.collectives import (
    CollectiveSummary,
    comm_compute_fraction,
    export_collectives,
    parse_hlo_collectives,
    parse_replica_groups,
)
from determined_clone_tpu.telemetry.mesh import (
    MULTICHIP_SCHEMA_VERSION,
    MeshStragglerDetector,
    device_lane_records,
    format_multichip,
    per_device_completion_seconds,
    validate_multichip,
)

MESH = {"dp": 4, "tp": 2}

HLO_ALL_REDUCE_DP = """
ENTRY main {
  %p0 = f32[128]{0} parameter(0)
  %ar = f32[128]{0} all-reduce(%p0), replica_groups={{0,2,4,6},{1,3,5,7}}, to_apply=%add
  ROOT %r = f32[128]{0} copy(%ar)
}
"""

# iota form of the SAME dp groups: arange(8).reshape(4,2) transposed to
# (tp, dp) and raveled -> [0,2,4,6,1,3,5,7], split into 2 groups of 4
HLO_ALL_GATHER_DP_IOTA = """
ENTRY main {
  %p0 = bf16[8,64]{1,0} parameter(0)
  %ag = bf16[32,64]{1,0} all-gather(%p0), replica_groups=[2,4]<=[4,2]T(1,0), dimensions={0}
}
"""

HLO_REDUCE_SCATTER_TP = """
ENTRY main {
  %p0 = f32[64]{0} parameter(0)
  %rs = f32[32]{0} reduce-scatter(%p0), replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}, to_apply=%add
}
"""

# empty replica_groups: one group of all 8 partitions -> the full-mesh
# dp+tp combo
HLO_ALL_TO_ALL_FULL = """
ENTRY main {
  %p0 = f32[16,16]{1,0} parameter(0)
  %a2a = f32[16,16]{1,0} all-to-all(%p0), replica_groups={}, dimensions={0}
}
"""

# ring shift inside each tp group
HLO_PERMUTE_TP = """
ENTRY main {
  %p0 = f32[256]{0} parameter(0)
  %cp = f32[256]{0} collective-permute(%p0), source_target_pairs={{0,1},{1,0},{2,3},{3,2},{4,5},{5,4},{6,7},{7,6}}
}
"""

# async pair describes ONE transfer; tuple result sums both operands
HLO_ASYNC_VARIADIC = """
ENTRY main {
  %ars = (f32[64]{0}, f32[64]{0}) all-reduce-start(%a, %b), replica_groups={{0,2,4,6},{1,3,5,7}}, to_apply=%add
  %ard = (f32[64]{0}, f32[64]{0}) all-reduce-done(%ars)
}
"""

HLO_NO_COLLECTIVES = """
ENTRY main {
  %p0 = f32[128]{0} parameter(0)
  ROOT %t = f32[128]{0} tanh(%p0)
}
"""


class TestHloParsing:
    def test_all_reduce_dp_count_and_bytes(self):
        s = parse_hlo_collectives(HLO_ALL_REDUCE_DP, mesh=MESH)
        assert s.count("all-reduce", "dp") == 1
        assert s.bytes("all-reduce", "dp") == 128 * 4
        assert s.n_partitions == 8

    def test_all_gather_iota_groups_attribute_to_dp(self):
        s = parse_hlo_collectives(HLO_ALL_GATHER_DP_IOTA, mesh=MESH)
        assert s.count("all-gather", "dp") == 1
        assert s.bytes("all-gather", "dp") == 32 * 64 * 2  # bf16 result

    def test_reduce_scatter_tp(self):
        s = parse_hlo_collectives(HLO_REDUCE_SCATTER_TP, mesh=MESH)
        assert s.count("reduce-scatter", "tp") == 1
        assert s.bytes("reduce-scatter", "tp") == 32 * 4

    def test_all_to_all_empty_groups_span_full_mesh(self):
        s = parse_hlo_collectives(HLO_ALL_TO_ALL_FULL, mesh=MESH)
        assert s.count("all-to-all", "dp+tp") == 1

    def test_collective_permute_pairs_attribute_to_tp(self):
        s = parse_hlo_collectives(HLO_PERMUTE_TP, mesh=MESH)
        assert s.count("collective-permute", "tp") == 1
        assert s.bytes("collective-permute", "tp") == 256 * 4

    def test_async_pair_counts_once_and_sums_tuple(self):
        s = parse_hlo_collectives(HLO_ASYNC_VARIADIC, mesh=MESH)
        assert s.count("all-reduce") == 1
        assert s.bytes("all-reduce", "dp") == 2 * 64 * 4

    def test_no_collectives_is_empty(self):
        s = parse_hlo_collectives(HLO_NO_COLLECTIVES, mesh=MESH)
        assert s.total_ops == 0
        assert s.total_bytes == 0.0

    def test_without_mesh_ops_land_on_other(self):
        s = parse_hlo_collectives(HLO_ALL_REDUCE_DP)
        assert s.count("all-reduce", "other") == 1

    def test_iota_expansion(self):
        line = "x = f32[1] all-gather(y), replica_groups=[2,4]<=[4,2]T(1,0)"
        assert parse_replica_groups(line) == [[0, 2, 4, 6], [1, 3, 5, 7]]

    def test_fingerprint_tracks_structure(self):
        a = parse_hlo_collectives(HLO_ALL_REDUCE_DP, mesh=MESH)
        b = parse_hlo_collectives(HLO_ALL_REDUCE_DP, mesh=MESH)
        c = parse_hlo_collectives(HLO_REDUCE_SCATTER_TP, mesh=MESH)
        assert a.fingerprint() == b.fingerprint()
        assert a.fingerprint() != c.fingerprint()

    def test_comm_fraction_bounds_and_null_flops(self):
        s = parse_hlo_collectives(HLO_ALL_REDUCE_DP, mesh=MESH)
        assert comm_compute_fraction(
            s, None, interconnect_bytes_per_s=1e9,
            peak_flops_per_s=1e12) is None
        frac = comm_compute_fraction(
            s, 1e6, interconnect_bytes_per_s=1e9, peak_flops_per_s=1e12)
        assert 0.0 < frac < 1.0

    def test_export_lands_labeled_gauges(self):
        reg = MetricsRegistry()
        s = parse_hlo_collectives(HLO_ALL_REDUCE_DP, mesh=MESH)
        export_collectives(s, reg, program="fixture",
                           fingerprint="abcd", comm_fraction=0.25)
        text = reg.dump()
        assert 'xla_collective_ops_total{' in text
        assert 'kind="all-reduce"' in text and 'axis="dp"' in text
        assert 'xla_comm_compute_fraction{' in text


class TestStraggler:
    def test_uniform_windows_flag_nobody(self):
        det = MeshStragglerDetector()
        for _ in range(5):
            assert det.observe(
                {f"cpu:{i}": 0.10 + 0.001 * i for i in range(8)}) is None
        assert det.stragglers == 0

    def test_injected_slow_device_increments_exactly_once(self):
        """The acceptance criterion: one injected slow device raises
        exactly one mesh_straggler_events_total increment, labeled with
        THAT device."""
        reg = MetricsRegistry()
        det = MeshStragglerDetector(reg)
        base = {f"cpu:{i}": 0.10 for i in range(8)}
        det.observe(base)
        slow = dict(base, **{"cpu:5": 0.50})
        assert det.observe(slow) == "cpu:5"
        assert det.stragglers == 1
        assert det.by_device == {"cpu:5": 1}
        lines = [ln for ln in reg.dump().splitlines()
                 if ln.startswith("mesh_straggler_events_total{")]
        assert len(lines) == 1
        assert 'device="cpu:5"' in lines[0]
        assert lines[0].rstrip().endswith(" 1.0") or \
            lines[0].rstrip().endswith(" 1")

    def test_only_the_slowest_of_two_is_flagged(self):
        """Followers wait on the same collective as the gang — only the
        single slowest device is independently slow."""
        det = MeshStragglerDetector()
        window = {f"cpu:{i}": 0.10 for i in range(8)}
        window["cpu:2"] = 0.40
        window["cpu:6"] = 0.60
        assert det.observe(window) == "cpu:6"
        assert det.stragglers == 1

    def test_globally_slow_step_flags_nobody(self):
        det = MeshStragglerDetector()
        det.observe({f"cpu:{i}": 0.10 for i in range(8)})
        # everyone 5x slower (input stall): median moves with the gang
        assert det.observe({f"cpu:{i}": 0.50 for i in range(8)}) is None

    def test_min_devices_guard(self):
        det = MeshStragglerDetector()
        assert det.observe({"cpu:0": 9.0}) is None
        assert det.windows == 1

    def test_summary_shape(self):
        det = MeshStragglerDetector()
        base = {f"cpu:{i}": 0.10 for i in range(4)}
        det.observe(base)
        det.observe(dict(base, **{"cpu:1": 1.0}))
        s = det.summary()
        assert s["windows"] == 2 and s["stragglers"] == 1
        assert s["recent_events"][0]["device"] == "cpu:1"


class TestDeviceLanes:
    def test_stitched_trace_has_one_lane_per_device(self):
        n = 8
        durations = {f"cpu:{i}": 0.01 * (i + 1) for i in range(n)}
        records = device_lane_records(durations, start_s=0.0,
                                      wall_epoch=100.0, step_index=3)
        trace = stitch_chrome_trace(records)
        assert validate_chrome_trace(trace) == []
        procs = [e for e in trace["traceEvents"]
                 if e.get("name") == "process_name"]
        assert {e["args"]["name"] for e in procs} == {
            f"device:cpu:{i}" for i in range(n)}
        spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        assert len(spans) == n
        assert {e["pid"] for e in spans} == {e["pid"] for e in procs}

    def test_device_key_fallback_without_process_label(self):
        recs = device_lane_records({"cpu:0": 0.1, "cpu:1": 0.1},
                                   start_s=0.0)
        for r in recs:
            r.pop("process")
        trace = stitch_chrome_trace(recs)
        procs = {e["args"]["name"] for e in trace["traceEvents"]
                 if e.get("name") == "process_name"}
        assert procs == {"device:cpu:0", "device:cpu:1"}


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device simulated mesh")
class TestLiveMesh:
    """End-to-end on the conftest-forced 8-device CPU mesh: a real
    sharded program's compiled HLO must show the dp all-reduce, and the
    per-device completion probe must see every device."""

    def _mesh(self):
        from determined_clone_tpu.parallel.mesh import MeshSpec, make_mesh
        return make_mesh(MeshSpec(dp=-1), jax.devices()[:8])

    def test_sharded_grad_step_counts_dp_all_reduce(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from determined_clone_tpu.telemetry.xla import aot_compile

        mesh = self._mesh()
        x = jax.device_put(
            jnp.ones((8, 16), jnp.float32),
            NamedSharding(mesh, P("dp", None)))
        w = jax.device_put(jnp.ones((16,), jnp.float32),
                           NamedSharding(mesh, P()))

        @jax.jit
        def loss_grad(w, x):
            return jax.grad(lambda w: jnp.sum((x @ w) ** 2))(w)

        reg = MetricsRegistry()
        fn, record = aot_compile(loss_grad, (w, x), program="mesh_test",
                                 registry=reg, mesh=mesh)
        assert record is not None and record.collectives is not None
        # the data-parallel gradient reduction
        assert record.collectives.count("all-reduce", "dp") >= 1
        assert record.collectives.bytes("all-reduce", "dp") > 0
        assert 'xla_collective_ops_total{' in reg.dump()
        out = fn(w, x)
        assert jnp.isfinite(out).all()

    def test_per_device_completion_sees_every_device(self):
        import time
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._mesh()
        x = jax.device_put(jnp.ones((8, 4), jnp.float32),
                           NamedSharding(mesh, P("dp", None)))
        t0 = time.perf_counter()
        y = jax.jit(lambda a: a * 2.0)(x)
        durations = per_device_completion_seconds(y, t0)
        assert set(durations) == {f"cpu:{i}" for i in range(8)}
        assert all(d >= 0 for d in durations.values())


def _artifact():
    return {
        "schema_version": MULTICHIP_SCHEMA_VERSION,
        "n_devices": 8,
        "platform": "cpu",
        "baseline": {"throughput_samples_per_sec": 80.0,
                     "mfu_measured": 0.06, "mfu_analytic": 0.08},
        "meshes": {
            "dp": {"mesh_shape": {"dp": 8, "tp": 1},
                   "scaling_efficiency": 0.15,
                   "throughput_samples_per_sec": 95.0,
                   "mfu_measured": 0.009, "mfu_analytic": 0.011,
                   "program_fingerprint": "aaaa",
                   "comm_compute_fraction": 0.01,
                   "straggler": {"windows": 2, "stragglers": 0,
                                 "by_device": {}},
                   "collectives": {"fingerprint": "ffff",
                                   "ops": {"all-reduce": {
                                       "dp": {"count": 17,
                                              "bytes": 1.0}}}}},
        },
        "per_device_peak_bytes": {f"cpu:{i}": 1000.0 for i in range(8)},
    }


class TestMultichipSchema:
    def test_round_trip_valid(self):
        art = _artifact()
        assert validate_multichip(art) == []
        assert validate_multichip(json.loads(json.dumps(art))) == []

    def test_rejects_bad_shapes(self):
        assert validate_multichip([]) != []
        art = _artifact()
        art["schema_version"] = 99
        assert any("schema_version" in e for e in validate_multichip(art))
        art = _artifact()
        art["meshes"] = {}
        assert any("meshes" in e for e in validate_multichip(art))
        art = _artifact()
        art["meshes"]["dp"]["scaling_efficiency"] = "fast"
        assert any("scaling_efficiency" in e
                   for e in validate_multichip(art))
        art = _artifact()
        art["per_device_peak_bytes"] = {"cpu:0": "big"}
        assert any("per_device_peak_bytes" in e
                   for e in validate_multichip(art))

    def test_format_renders_key_numbers(self):
        text = format_multichip(_artifact())
        assert "8 x cpu devices" in text
        assert "efficiency 15.0%" in text
        assert "all-reduce[dp]=17" in text
        assert "per-device peak bytes: 8 devices" in text

    def test_bench_gate_enforces_efficiency_regression(self):
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        try:
            from bench_gate import gate
        finally:
            sys.path.pop(0)
        mc = {"runs": {"8": _artifact()}}
        base = {"metric": "m", "value": 1.0,
                "detail": {"platform": "cpu", "mfu": 0.1, "multichip": mc}}
        worse = json.loads(json.dumps(base))
        worse["detail"]["multichip"]["runs"]["8"]["meshes"]["dp"][
            "scaling_efficiency"] = 0.05
        ok, report = gate(base, worse)
        assert not ok
        assert any("FAIL: multichip" in ln for ln in report)
        ok2, _ = gate(base, json.loads(json.dumps(base)))
        assert ok2


class TestClusterRollup:
    def test_mesh_rollup_from_exposition_text(self):
        reg = MetricsRegistry()
        s = parse_hlo_collectives(HLO_ALL_REDUCE_DP, mesh=MESH)
        export_collectives(s, reg, program="train_step",
                           fingerprint="abcd", comm_fraction=0.33)
        det = MeshStragglerDetector(reg)
        base = {f"cpu:{i}": 0.10 for i in range(8)}
        det.observe(base)
        det.observe(dict(base, **{"cpu:3": 0.9}))

        agg = ClusterMetricsAggregator()
        agg.ingest_prometheus_text("trial-1", reg.dump())
        roll = agg.mesh_rollup()
        assert roll is not None
        assert roll["collective_ops"]["all-reduce"]["dp"] == 1
        assert roll["straggler_events"]["cpu:3"] == 1
        assert roll["straggler_events_total"] == 1
        assert roll["worst_comm_fraction"]["fraction"] == \
            pytest.approx(0.33)
        # the re-exported cluster families + human summary
        dumped = agg.dump()
        assert "dct_mesh_collective_ops " in dumped
        assert "dct_mesh_straggler_events " in dumped
        text_summary = agg.summary()
        assert text_summary["mesh"] is not None

    def test_rollup_none_without_mesh_series(self):
        agg = ClusterMetricsAggregator()
        agg.ingest_prometheus_text("trial-1", "foo_total 1\n")
        assert agg.mesh_rollup() is None
