"""Cluster e2e: C++ master + C++ agent + real Python trial processes.

The reference's devcluster-style test (tools/devcluster.yaml,
e2e_tests/tests/cluster/managed_cluster.py): boot master+agent from source,
submit experiments over the API, assert scheduling/training/restart behavior.
"""
import json
import os
import shutil
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"

TRIAL_MODULE = '''
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial


class Trial(JaxTrial):
    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(self.context.get_hparam("lr", 0.2))

    def loss(self, params, batch, rng):
        return (params["w"] - 2.0) ** 2, {}

    def training_data(self):
        for _ in range(64):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2
'''


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("cluster")
    workdir = tmp / "agent-work"
    workdir.mkdir()
    (workdir / "model_def.py").write_text(TRIAL_MODULE)

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",       # no TPU tunnel in subprocesses
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",           # artificial slot (detect.go:39 trick)
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "test-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port,
           "master": master, "agent": agent, "workdir": workdir}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


def wait_for(predicate, timeout=120, interval=0.5, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def exp_config(cluster, searcher, hparams=None, name="e2e"):
    return {
        "name": name,
        "entrypoint": "model_def:Trial",
        "searcher": searcher,
        "resources": {"slots_per_trial": 1},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": hparams or {"lr": 0.2},
        "max_restarts": 1,
    }


def test_master_and_agent_register(cluster):
    agents = cluster["session"].list_agents()
    assert len(agents) == 1
    assert agents[0]["slots"] == 1
    assert agents[0]["topology"] == "v5e-1"
    info = cluster["session"].master_info()
    assert info["agents"] == 1


def test_single_experiment_trains_to_completion(cluster):
    session = cluster["session"]
    exp = session.create_experiment(exp_config(cluster, {
        "name": "single", "metric": "loss", "max_length": {"batches": 6},
    }))
    detail = wait_for(
        lambda: (lambda d: d if d["experiment"]["state"] == "COMPLETED" else None)(
            session.get_experiment(exp["id"])),
        desc="experiment completion", timeout=180,
    )
    trials = detail["trials"]
    assert len(trials) == 1
    t = trials[0]
    assert t["state"] == "COMPLETED"
    assert t["units_done"] >= 6
    assert t["has_metric"]
    # metrics made it to the master
    metrics = session.trial_metrics(t["id"])
    groups = {m["group"] for m in metrics}
    assert "training" in groups and "validation" in groups
    # checkpoint was reported and linked
    assert t["latest_checkpoint"]
    ckpts = session.get(f"/api/v1/experiments/{exp['id']}/checkpoints")[
        "checkpoints"]
    assert any(c["uuid"] == t["latest_checkpoint"] for c in ckpts)
    # task logs shipped by the agent on exit (arrives after process reap)
    logs = wait_for(
        lambda: [l for l in session.task_logs(f"trial-{t['id']}.0")
                 if "leg finished" in json.dumps(l)] or None,
        desc="task logs shipped", timeout=30,
    )
    assert logs


def test_random_search_multiple_trials(cluster):
    session = cluster["session"]
    exp = session.create_experiment(exp_config(cluster, {
        "name": "random", "metric": "loss", "max_trials": 2,
        "max_length": {"batches": 4}, "max_concurrent_trials": 1,
    }, hparams={"lr": {"type": "double", "minval": 0.1, "maxval": 0.3}},
        name="e2e-random"))
    detail = wait_for(
        lambda: (lambda d: d if d["experiment"]["state"] == "COMPLETED" else None)(
            session.get_experiment(exp["id"])),
        desc="random search completion", timeout=300,
    )
    assert len(detail["trials"]) == 2
    assert all(t["state"] == "COMPLETED" for t in detail["trials"])
    lrs = {t["hparams"]["lr"] for t in detail["trials"]}
    assert len(lrs) == 2


def test_kill_experiment(cluster):
    session = cluster["session"]
    exp = session.create_experiment(exp_config(cluster, {
        "name": "single", "metric": "loss", "max_length": {"batches": 10_000},
    }, name="e2e-kill"))
    session.kill_experiment(exp["id"])
    detail = wait_for(
        lambda: (lambda d: d if d["experiment"]["state"] in
                 ("CANCELED", "COMPLETED") else None)(
            session.get_experiment(exp["id"])),
        desc="experiment cancel", timeout=60,
    )
    assert detail["experiment"]["state"] == "CANCELED"


SLOW_TRIAL = TRIAL_MODULE.replace(
    "    def training_data(self):\n"
    "        for _ in range(64):\n"
    "            yield np.zeros((2, 1), np.float32)",
    "    def training_data(self):\n"
    "        import time\n"
    "        for _ in range(64):\n"
    "            time.sleep(0.25)\n"
    "            yield np.zeros((2, 1), np.float32)")


def test_pause_activate_archive_delete(cluster):
    """≈ PauseExperiment/ActivateExperiment/Archive/Delete: pause preempts
    the running trial (it checkpoints and frees the chip), activate
    resumes from that checkpoint, archive/delete need a terminal state."""
    session = cluster["session"]
    assert SLOW_TRIAL != TRIAL_MODULE  # the replace really took
    (cluster["workdir"] / "slow_def.py").write_text(SLOW_TRIAL)
    cfg = exp_config(cluster, {"name": "single", "metric": "loss",
                               "max_length": {"batches": 30}},
                     name="pausable")
    cfg["entrypoint"] = "slow_def:Trial"
    exp = session.create_experiment(cfg)
    eid = exp["id"]

    # wait for real training progress (past compile) so the pause
    # exercises the graceful checkpoint-and-exit path, not the startup race
    wait_for(lambda: session.get_experiment(eid)["trials"] and
             session.get_experiment(eid)["trials"][0]["units_done"] > 0,
             desc="trial made progress")

    # cannot archive or delete while live
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):
        session.archive_experiment(eid)
    with pytest.raises(MasterError):
        session.delete_experiment(eid)

    paused = session.pause_experiment(eid)
    assert paused["state"] == "PAUSED"
    # the trial preempts gracefully: checkpoints, exits, parks
    wait_for(lambda: session.get_experiment(eid)["trials"][0]["state"]
             == "PAUSED", desc="trial paused")
    trial = session.get_experiment(eid)["trials"][0]
    assert 0 < trial["units_done"] < 30  # mid-run, progress persisted
    assert trial["latest_checkpoint"]    # preemption checkpoint landed
    # the chip is free again (no live allocation for this trial)
    assert not any(j["id"].startswith(f"trial-{trial['id']}.")
                   for j in session.job_queue())

    # double-pause is a no-op error; activate resumes from the checkpoint
    with pytest.raises(MasterError):
        session.pause_experiment(eid)
    activated = session.activate_experiment(eid)
    assert activated["state"] == "RUNNING"
    wait_for(lambda: session.get_experiment(eid)["experiment"]["state"]
             == "COMPLETED", desc="completed after resume")
    trial = session.get_experiment(eid)["trials"][0]
    assert trial["units_done"] >= 30

    # archive, then delete: records and checkpoints drop out
    assert session.archive_experiment(eid)["archived"] is True
    assert session.archive_experiment(eid, archive=False)[
        "archived"] is False
    assert session.get_experiment(eid)["experiment"]  # still queryable
    session.delete_experiment(eid)
    with pytest.raises(MasterError) as err:
        session.get_experiment(eid)
    assert err.value.status == 404


def test_kill_single_trial_search_continues(cluster):
    """≈ KillTrial: killing one trial of a random search cancels only that
    trial; the searcher is told it exited early and the experiment still
    finishes."""
    session = cluster["session"]
    exp = session.create_experiment(exp_config(
        cluster, {"name": "random", "metric": "loss", "max_trials": 3,
                  "max_length": {"batches": 4}},
        hparams={"lr": {"type": "double", "minval": 0.05, "maxval": 0.3}},
        name="trial-kill"))
    eid = exp["id"]
    trials = wait_for(lambda: session.get_experiment(eid)["trials"] or None,
                      desc="trials created")
    victim = trials[0]["id"]
    killed = session.kill_trial(victim)
    # fast trials can finish before the kill lands; non-terminal ones cancel
    assert killed["state"] in ("CANCELED", "COMPLETED")

    # the experiment completes with the remaining trials either way
    wait_for(lambda: session.get_experiment(eid)["experiment"]["state"]
             == "COMPLETED", desc="search completed despite the kill")
    final = {t["id"]: t["state"]
             for t in session.get_experiment(eid)["trials"]}
    assert final[victim] == killed["state"]  # the kill's outcome held
    assert sum(1 for s in final.values() if s == "COMPLETED") >= 2
    # a second kill is an idempotent no-op
    assert session.kill_trial(victim)["state"] == killed["state"]


def test_kill_only_trial_cancels_experiment(cluster):
    """Killing a single-searcher experiment's only trial is a user cancel:
    the experiment ends CANCELED (like experiment kill), never ERRORED."""
    session = cluster["session"]
    exp = session.create_experiment(exp_config(cluster, {
        "name": "single", "metric": "loss",
        "max_length": {"batches": 10_000},
    }, name="kill-only-trial"))
    trials = wait_for(lambda: session.get_experiment(exp["id"])["trials"]
                      or None, desc="trial created")
    session.kill_trial(trials[0]["id"])
    detail = wait_for(
        lambda: (lambda d: d if d["experiment"]["state"] in
                 ("CANCELED", "ERRORED", "COMPLETED") else None)(
            session.get_experiment(exp["id"])),
        desc="experiment settled", timeout=60)
    assert detail["experiment"]["state"] == "CANCELED"
    assert detail["trials"][0]["state"] == "CANCELED"
