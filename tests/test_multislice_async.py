"""Multislice (ICI x DCN) meshes and async checkpointing.

SURVEY.md §7's remaining hard parts: hybrid meshes whose inner axes stay
on a slice's ICI torus while dp/fsdp span slices over DCN, and
orbax-style async checkpoint saves that overlap upload with training
(flush-then-exit on preemption). Both run on the virtual 8-device CPU
mesh; slices are modeled as contiguous device groups.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from determined_clone_tpu.core import (
    CheckpointContext,
    DistributedContext,
    LocalCheckpointRegistry,
)
from determined_clone_tpu.parallel import (
    MeshSpec,
    make_mesh,
    make_multislice_mesh,
)
from determined_clone_tpu.storage import SharedFSStorageManager


class TestMultisliceMesh:
    def test_two_slices_dp_spans_dcn(self):
        # 8 devices = 2 slices x 4 chips; per-slice dp=2,tp=2; dp across
        mesh = make_multislice_mesh(MeshSpec(dp=2, tp=2),
                                    MeshSpec(dp=2, fsdp=1, pp=1, ep=1,
                                             sp=1, tp=1))
        assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
        devs = mesh.devices  # [dp=4, fsdp=1, pp=1, ep=1, sp=1, tp=2]
        flat_ids = [d.id for d in devs.reshape(4, 2).reshape(-1)]
        # dp-major is dcn-major: dp rows 0-1 hold slice 0 (devices 0-3),
        # rows 2-3 hold slice 1 (devices 4-7) — tp never crosses a slice
        assert sorted(flat_ids[:4]) == [0, 1, 2, 3]
        assert sorted(flat_ids[4:]) == [4, 5, 6, 7]
        for row in devs.reshape(4, 2):
            slice_of = {d.id // 4 for d in row}
            assert len(slice_of) == 1  # each tp pair is intra-slice

    def test_training_step_executes_on_hybrid_mesh(self):
        mesh = make_multislice_mesh(MeshSpec(dp=2, tp=2),
                                    MeshSpec(dp=2, fsdp=1, pp=1, ep=1,
                                             sp=1, tp=1))
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 64))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 64))
        with mesh:
            w = jax.device_put(w, NamedSharding(mesh, P(None, "tp")))
            x = jax.device_put(x, NamedSharding(mesh, P("dp", None)))

            @jax.jit
            def step(w, x):
                return ((x @ w) ** 2).mean()

            loss = step(w, x)
        assert np.isfinite(float(loss))

    def test_validation(self):
        with pytest.raises(ValueError, match="slices"):
            make_multislice_mesh(MeshSpec(dp=1), MeshSpec(dp=3, fsdp=1,
                                                          pp=1, ep=1, sp=1,
                                                          tp=1))
        with pytest.raises(ValueError, match="fully specified"):
            make_multislice_mesh(MeshSpec(dp=1), MeshSpec())


class SlowStorage(SharedFSStorageManager):
    """Records upload timing so tests can prove overlap/drain ordering."""

    def __init__(self, base, delay=0.3):
        super().__init__(str(base))
        self.delay = delay
        self.uploads_started = []
        self.uploads_finished = []

    def upload(self, src_dir, storage_id, paths=None):
        self.uploads_started.append((storage_id, time.time()))
        time.sleep(self.delay)
        super().upload(src_dir, storage_id, paths)
        self.uploads_finished.append((storage_id, time.time()))


class TestAsyncCheckpoint:
    def test_save_overlaps_and_wait_drains(self, tmp_path):
        storage = SlowStorage(tmp_path / "ckpts")
        registry = LocalCheckpointRegistry(str(tmp_path / "reg.jsonl"))
        ctx = CheckpointContext(DistributedContext.single(), storage,
                                registry, trial_id=7)

        t0 = time.time()
        with ctx.store_path_async({"step": 1}) as (path, holder):
            with open(f"{path}/weights.bin", "wb") as f:
                f.write(b"W" * 1024)
        handoff = time.time() - t0
        assert handoff < storage.delay  # training resumes before upload ends
        sid = holder["storage_id"]
        assert sid

        # nothing published until the drain
        assert registry.list() == []
        drained = ctx.wait_async()
        assert drained == [sid]
        recs = registry.list()
        assert len(recs) == 1 and recs[0]["storage_id"] == sid
        assert recs[0]["metadata"] == {"step": 1}
        assert recs[0]["resources"]["weights.bin"] == 1024

        # the checkpoint restores like any sync one
        with ctx.restore_path(sid) as path:
            import os

            assert sorted(os.listdir(path)) == ["COMMIT", "manifest.json",
                                                "metadata.json",
                                                "weights.bin"]

    def test_multiple_in_flight_preserved_in_order(self, tmp_path):
        storage = SlowStorage(tmp_path / "ckpts", delay=0.1)
        ctx = CheckpointContext(DistributedContext.single(), storage,
                                LocalCheckpointRegistry(
                                    str(tmp_path / "reg.jsonl")))
        sids = []
        for step in (1, 2, 3):
            with ctx.store_path_async({"step": step}) as (path, holder):
                with open(f"{path}/w.bin", "wb") as f:
                    f.write(b"x")
            sids.append(holder["storage_id"])
        assert ctx.wait_async() == sids
        assert ctx.wait_async() == []  # idempotent drain

    def test_upload_error_surfaces_at_wait(self, tmp_path):
        class FailingStorage(SlowStorage):
            def upload(self, *a, **kw):
                raise IOError("bucket gone")

        ctx = CheckpointContext(DistributedContext.single(),
                                FailingStorage(tmp_path / "c"),
                                LocalCheckpointRegistry(
                                    str(tmp_path / "reg.jsonl")))
        with ctx.store_path_async() as (path, holder):
            with open(f"{path}/w.bin", "wb") as f:
                f.write(b"x")
        with pytest.raises(IOError, match="bucket gone"):
            ctx.wait_async()
        assert ctx.wait_async() == []  # failed entry not retried silently

    def test_sharded_async_across_ranks(self, tmp_path):
        """4 threads = 4 ranks: per-rank async shard uploads, one drain."""
        from determined_clone_tpu.core._distributed import _ChiefTransport

        world = 4
        chief = _ChiefTransport(0, world)
        storage = SlowStorage(tmp_path / "ckpts", delay=0.05)
        registry = LocalCheckpointRegistry(str(tmp_path / "reg.jsonl"))
        results = {}

        def member(rank):
            if rank == 0:
                dist = DistributedContext(rank=0, size=world,
                                          transport=chief)
            else:
                dist = DistributedContext.from_tcp(
                    "127.0.0.1", chief.port, rank, world)
            ctx = CheckpointContext(dist, storage, registry, trial_id=1)
            with ctx.store_path_async(
                    {"step": 9}, shard=True) as (path, holder):
                with open(f"{path}/shard-{rank}.bin", "wb") as f:
                    f.write(bytes([rank]) * 8)
            ctx.wait_async()
            results[rank] = holder["storage_id"]
            dist.close()

        threads = [threading.Thread(target=member, args=(r,))
                   for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(set(results.values())) == 1  # one collective id
        sid = results[0]
        files = storage.list_files(sid)
        assert set(files) == {"COMMIT", "manifest.json", "metadata.json",
                              "shard-0.bin", "shard-1.bin", "shard-2.bin",
                              "shard-3.bin"}
        recs = LocalCheckpointRegistry(str(tmp_path / "reg.jsonl")).list()
        assert len(recs) == 1 and len(recs[0]["resources"]) == 7
