"""Tier-1 static checks: no silently swallowed exceptions.

Runs tools/check_swallowed_exceptions.py over the library so a new bare
``except Exception: pass`` without a justification comment fails the gate
(the failure mode that hid profiler sample drops before
``profiler_samples_dropped`` existed — see docs/observability.md).
"""
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import check_swallowed_exceptions as csx  # noqa: E402


def _violations(snippet, tmp_path):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(snippet))
    return list(csx.check_file(f))


def test_library_is_clean():
    assert csx.main([str(REPO / "determined_clone_tpu")]) == 0


def test_tools_and_bench_are_clean():
    assert csx.main([str(REPO / "tools"), str(REPO / "bench.py")]) == 0


def test_flags_uncommented_swallow(tmp_path):
    v = _violations(
        """
        try:
            work()
        except Exception:
            pass
        """, tmp_path)
    assert len(v) == 1
    assert "except Exception" in v[0][1]


def test_flags_bare_except_and_ellipsis(tmp_path):
    v = _violations(
        """
        try:
            work()
        except:
            ...
        """, tmp_path)
    assert len(v) == 1


def test_comment_on_pass_line_suppresses(tmp_path):
    assert _violations(
        """
        try:
            work()
        except Exception:
            pass  # best-effort cleanup; never mask the original error
        """, tmp_path) == []


def test_comment_above_try_suppresses(tmp_path):
    assert _violations(
        """
        # Transient poll failures must not kill training; the watcher
        # retries on its next tick.
        try:
            work()
        except Exception:
            pass
        """, tmp_path) == []


def test_narrow_handler_is_fine(tmp_path):
    assert _violations(
        """
        try:
            work()
        except KeyError:
            pass
        """, tmp_path) == []


def test_broad_handler_with_real_body_is_fine(tmp_path):
    assert _violations(
        """
        try:
            work()
        except Exception:
            log.warning("work failed")
        """, tmp_path) == []


def test_tuple_including_broad_is_flagged(tmp_path):
    v = _violations(
        """
        try:
            work()
        except (ValueError, Exception):
            pass
        """, tmp_path)
    assert len(v) == 1
