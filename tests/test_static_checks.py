"""Tier-1 static checks: the dctlint suite (docs/static_analysis.md).

Three layers:

1. **The gate** — ``python -m tools.dctlint determined_clone_tpu tools
   bench.py`` must exit 0, so a new JAX/concurrency/clock violation
   anywhere in the library, the tools, or the bench harness fails CI.
2. **Checker fixtures** — every rule (JAX001-003, CONC001-002, TIME001,
   EXC001, RETRY001) has paired true-positive / true-negative snippets, so a checker
   that goes blind (or trigger-happy) fails here before it lies in CI.
3. **Framework mechanics** — suppression comments require reasons,
   baselines filter exactly what they name, the legacy
   ``check_swallowed_exceptions`` shim keeps its contract.
"""
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))
sys.path.insert(0, str(REPO))

import check_swallowed_exceptions as csx  # noqa: E402
from tools.dctlint import CHECKERS, core as lint_core  # noqa: E402

TIER1_LINT_PATHS = ["determined_clone_tpu", "tools", "bench.py"]
BASELINE = REPO / "tools" / "dctlint" / "baseline.json"


def _lint(snippet, tmp_path, select=None, name="snippet.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(snippet))
    return lint_core.lint_file(f, select=select)


def _rules(diags):
    return [d.rule for d in diags]


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------

def test_tier1_tree_is_clean():
    """The committed tree passes the full suite (fix, baseline with a
    justification, or suppress inline with a reason — never ignore)."""
    diags = lint_core.run([str(REPO / p) for p in TIER1_LINT_PATHS],
                          baseline=BASELINE, relative_to=REPO)
    assert diags == [], "\n" + "\n".join(d.format() for d in diags)


def test_module_entrypoint_exit_codes(tmp_path):
    clean = subprocess.run(
        [sys.executable, "-m", "tools.dctlint", *TIER1_LINT_PATHS],
        cwd=REPO, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    bad = tmp_path / "bad.py"
    bad.write_text("import time\ndeadline = time.time() + 5\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.dctlint", str(bad)],
        cwd=REPO, capture_output=True, text=True)
    assert dirty.returncode == 1
    assert "TIME001" in dirty.stdout


def test_cli_lint_subcommand():
    from determined_clone_tpu.cli.cli import main as cli_main

    assert cli_main(["lint", "--list-checkers"]) == 0
    assert cli_main(["lint", str(REPO / "tools" / "dctlint")]) == 0


def test_all_rules_registered():
    assert {"JAX001", "JAX002", "JAX003", "JAX004", "CONC001",
            "CONC002", "CONC003", "CONC004", "CONTRACT001",
            "CONTRACT002", "CONTRACT003", "TIME001", "EXC001",
            "RETRY001"} <= set(CHECKERS)


def test_project_rules_marked_project_scope():
    for rule in ("CONC003", "CONC004", "CONTRACT001", "CONTRACT002",
                 "CONTRACT003", "JAX004"):
        assert CHECKERS[rule].project, rule
    for rule in ("JAX001", "CONC001", "TIME001"):
        assert not CHECKERS[rule].project, rule


# ---------------------------------------------------------------------------
# JAX001 — host sync / side effect inside traced code
# ---------------------------------------------------------------------------

def test_jax001_print_in_jit_decorated(tmp_path):
    v = _lint(
        """
        import jax

        @jax.jit
        def f(x):
            print(x)
            return x
        """, tmp_path, select=["JAX001"])
    assert _rules(v) == ["JAX001"]
    assert "print" in v[0].message


def test_jax001_numpy_in_scan_body(tmp_path):
    v = _lint(
        """
        import jax
        import numpy as np

        def body(carry, x):
            return carry, np.sum(x)

        def outer(xs):
            return jax.lax.scan(body, 0, xs)
        """, tmp_path, select=["JAX001"])
    assert _rules(v) == ["JAX001"]
    assert "numpy.sum" in v[0].message


def test_jax001_item_and_float_in_jit_call(tmp_path):
    v = _lint(
        """
        import jax

        def step(state, batch):
            loss = state - batch
            a = loss.item()
            b = float(loss)
            return state

        step = jax.jit(step)
        """, tmp_path, select=["JAX001"])
    assert len(v) == 2
    assert ".item()" in v[0].message and "float()" in v[1].message


def test_jax001_clean_outside_trace_and_debug_print(tmp_path):
    v = _lint(
        """
        import jax
        import numpy as np

        def host_side(x):
            print(np.sum(x))           # not traced: fine
            return float(x)

        @jax.jit
        def f(x):
            jax.debug.print("x={x}", x=x)   # the sanctioned print
            y = float(1.0)                  # constant: folds harmlessly
            return x * y
        """, tmp_path, select=["JAX001"])
    assert v == []


# ---------------------------------------------------------------------------
# JAX002 — constant PRNGKey in per-step code / key reuse without split
# ---------------------------------------------------------------------------

def test_jax002_constant_key_in_loss(tmp_path):
    v = _lint(
        """
        import jax

        def loss_fn(params, batch):
            rng = jax.random.PRNGKey(0)
            return model(params, batch, rng)
        """, tmp_path, select=["JAX002"])
    assert _rules(v) == ["JAX002"]
    assert "constant" in v[0].message


def test_jax002_seeded_key_in_setup_is_fine(tmp_path):
    v = _lint(
        """
        import jax

        def main(seed):
            rng = jax.random.PRNGKey(seed)   # non-constant: seeded chain
            return rng

        def build_bench():
            k = jax.random.PRNGKey(0)        # setup code, not per-step
            return k
        """, tmp_path, select=["JAX002"])
    assert v == []


def test_jax002_key_reused_without_split(tmp_path):
    v = _lint(
        """
        import jax

        def train(params, batch, seed):
            key = jax.random.PRNGKey(seed)
            a = dropout_a(params, key)
            b = dropout_b(params, key)
            return a + b
        """, tmp_path, select=["JAX002"])
    assert _rules(v) == ["JAX002"]
    assert "without an intervening jax.random.split" in v[0].message


def test_jax002_split_keys_are_fine(tmp_path):
    v = _lint(
        """
        import jax

        def train(params, batch, seed):
            key = jax.random.PRNGKey(seed)
            k1, k2 = jax.random.split(key)
            a = dropout_a(params, k1)
            b = dropout_b(params, k2)
            return a + b
        """, tmp_path, select=["JAX002"])
    assert v == []


# ---------------------------------------------------------------------------
# JAX003 — jitted train step without donate_argnums
# ---------------------------------------------------------------------------

def test_jax003_jit_call_missing_donation(tmp_path):
    v = _lint(
        """
        import jax

        def train_step(state, batch):
            return state

        train_step = jax.jit(train_step)
        """, tmp_path, select=["JAX003"])
    assert _rules(v) == ["JAX003"]
    assert "donate_argnums" in v[0].message


def test_jax003_decorator_missing_donation(tmp_path):
    v = _lint(
        """
        import jax

        @jax.jit
        def train_step(state, batch):
            return state
        """, tmp_path, select=["JAX003"])
    assert _rules(v) == ["JAX003"]


def test_jax003_donated_and_eval_steps_are_fine(tmp_path):
    v = _lint(
        """
        import jax

        def train_step(state, batch):
            return state

        train_step = jax.jit(train_step, donate_argnums=(0,))

        def make_eval_step(fn):
            def step_fn(state, batch):   # eval-shaped: nothing to donate
                return fn(state, batch)
            return jax.jit(step_fn)
        """, tmp_path, select=["JAX003"])
    assert v == []


def test_jax003_kwargs_splat_is_undecidable_not_flagged(tmp_path):
    v = _lint(
        """
        import jax

        def train_step(state, batch):
            return state

        kwargs = dict(donate_argnums=(0,))
        train_step = jax.jit(train_step, **kwargs)
        """, tmp_path, select=["JAX003"])
    assert v == []


# ---------------------------------------------------------------------------
# CONC001 — threading.Thread without daemon= and name=
# ---------------------------------------------------------------------------

def test_conc001_anonymous_thread(tmp_path):
    v = _lint(
        """
        import threading

        t = threading.Thread(target=print)
        u = threading.Thread(target=print, daemon=True)
        """, tmp_path, select=["CONC001"])
    assert _rules(v) == ["CONC001", "CONC001"]
    assert "daemon= and name=" in v[0].message
    assert "name=" in v[1].message and "daemon" not in v[1].message


def test_conc001_named_daemon_thread_is_fine(tmp_path):
    v = _lint(
        """
        import threading

        t = threading.Thread(target=print, daemon=True, name="worker")
        u = threading.Thread(**thread_kwargs)   # splat: undecidable
        """, tmp_path, select=["CONC001"])
    assert v == []


def test_conc001_subclass_super_init(tmp_path):
    v = _lint(
        """
        import threading

        class Bad(threading.Thread):
            def __init__(self):
                super().__init__()

        class Good(threading.Thread):
            def __init__(self):
                super().__init__(daemon=True, name="good-worker")
        """, tmp_path, select=["CONC001"])
    assert _rules(v) == ["CONC001"]
    assert "Bad" in v[0].message


# ---------------------------------------------------------------------------
# CONC002 — Lock.acquire() outside with / try-finally
# ---------------------------------------------------------------------------

def test_conc002_bare_acquire(tmp_path):
    v = _lint(
        """
        import threading

        lock = threading.Lock()

        def critical():
            lock.acquire()
            do_work()
            lock.release()
        """, tmp_path, select=["CONC002"])
    assert _rules(v) == ["CONC002"]
    assert "deadlock" in v[0].message


def test_conc002_try_finally_and_with_are_fine(tmp_path):
    v = _lint(
        """
        import threading

        lock = threading.Lock()

        def guarded():
            lock.acquire()
            try:
                do_work()
            finally:
                lock.release()

        def timed():
            if lock.acquire(timeout=1.0):
                try:
                    do_work()
                finally:
                    lock.release()

        def scoped():
            with lock:
                do_work()
        """, tmp_path, select=["CONC002"])
    assert v == []


# ---------------------------------------------------------------------------
# TIME001 — time.time() arithmetic
# ---------------------------------------------------------------------------

def test_time001_delta_and_deadline(tmp_path):
    v = _lint(
        """
        import time

        def measure():
            t0 = time.time()
            work()
            return time.time() - t0

        def wait():
            deadline = time.time() + 5
            return deadline
        """, tmp_path, select=["TIME001"])
    assert _rules(v) == ["TIME001", "TIME001"]


def test_time001_aliased_import(tmp_path):
    v = _lint(
        """
        import time as _t

        def wait(timeout):
            return _t.time() + timeout
        """, tmp_path, select=["TIME001"])
    assert _rules(v) == ["TIME001"]


def test_time001_monotonic_and_reported_wallclock_are_fine(tmp_path):
    v = _lint(
        """
        import time

        def measure():
            t0 = time.monotonic()
            work()
            return time.monotonic() - t0

        def report():
            return {"time": time.time(), "stamp": int(time.time())}
        """, tmp_path, select=["TIME001"])
    assert v == []


def test_time001_taint_does_not_leak_across_scopes(tmp_path):
    v = _lint(
        """
        import time

        def reports():
            now = time.time()       # wall clock, reported only
            return {"time": now}

        def rates(prev):
            now = time.monotonic()  # same name, different clock
            return now - prev
        """, tmp_path, select=["TIME001"])
    assert v == []


# ---------------------------------------------------------------------------
# RETRY001 — hand-rolled retry loop (sleep + except in a loop)
# ---------------------------------------------------------------------------

def test_retry001_sleep_in_retry_loop(tmp_path):
    v = _lint(
        """
        import time as _t

        def fetch(call):
            while True:
                try:
                    return call()
                except ConnectionError:
                    _t.sleep(1.0)
        """, tmp_path, select=["RETRY001"])
    assert _rules(v) == ["RETRY001"]
    assert "hand-rolled" in v[0].message


def test_retry001_for_loop_with_backoff(tmp_path):
    v = _lint(
        """
        import time

        def fetch(call):
            for attempt in range(5):
                try:
                    return call()
                except OSError:
                    pass
                time.sleep(2 ** attempt)
        """, tmp_path, select=["RETRY001"])
    assert _rules(v) == ["RETRY001"]


def test_retry001_poll_loop_without_handler_is_fine(tmp_path):
    v = _lint(
        """
        import time

        def wait_ready(check):
            while not check():
                time.sleep(0.5)   # plain poll, no exception pacing
        """, tmp_path, select=["RETRY001"])
    assert v == []


def test_retry001_handler_in_nested_function_is_fine(tmp_path):
    v = _lint(
        """
        import time

        def tick(fns):
            for fn in fns:
                def guarded():
                    try:
                        fn()
                    except Exception:
                        raise
                guarded()
                time.sleep(0.1)   # pacing, not retry: no handler in loop
        """, tmp_path, select=["RETRY001"])
    assert v == []


def test_retry001_retry_module_itself_is_exempt(tmp_path):
    (tmp_path / "utils").mkdir()
    v = _lint(
        """
        import time

        def retry_call(fn):
            while True:
                try:
                    return fn()
                except Exception:
                    time.sleep(0.1)
        """, tmp_path / "utils", select=["RETRY001"], name="retry.py")
    assert v == []


# ---------------------------------------------------------------------------
# suppression mechanism
# ---------------------------------------------------------------------------

def test_suppression_with_reason(tmp_path):
    v = _lint(
        """
        import time

        deadline = time.time() + 5  # dctlint: disable=TIME001 NTP-aware wall deadline is the point here
        """, tmp_path, select=["TIME001"])
    assert v == []


def test_suppression_without_reason_is_itself_flagged(tmp_path):
    v = _lint(
        """
        import time

        deadline = time.time() + 5  # dctlint: disable=TIME001
        """, tmp_path, select=["TIME001"])
    # the reasonless disable does NOT suppress, and is reported itself
    assert sorted(_rules(v)) == ["DCT000", "TIME001"]


def test_suppression_next_line(tmp_path):
    v = _lint(
        """
        import time

        # dctlint: disable-next-line=TIME001 demo fixture for the docs
        deadline = time.time() + 5
        """, tmp_path, select=["TIME001"])
    assert v == []


def test_suppression_wrong_rule_does_not_apply(tmp_path):
    v = _lint(
        """
        import time

        deadline = time.time() + 5  # dctlint: disable=JAX001 wrong rule id
        """, tmp_path, select=["TIME001"])
    assert _rules(v) == ["TIME001"]


def test_suppression_all_with_reason(tmp_path):
    v = _lint(
        """
        import time

        deadline = time.time() + 5  # dctlint: disable=all generated fixture, exempt wholesale
        """, tmp_path, select=["TIME001"])
    assert v == []


# ---------------------------------------------------------------------------
# baseline mechanism
# ---------------------------------------------------------------------------

def test_baseline_roundtrip_filters_exactly_whats_named(tmp_path):
    bad = tmp_path / "legacy.py"
    bad.write_text("import time\ndeadline = time.time() + 5\n")
    diags = lint_core.lint_file(bad, select=["TIME001"])
    assert len(diags) == 1

    baseline = tmp_path / "baseline.json"
    assert lint_core.write_baseline(baseline, diags) == 1
    entries = lint_core.load_baseline(baseline)
    assert entries[0]["rule"] == "TIME001"
    assert "justification" in entries[0]

    # the baselined violation is filtered...
    assert lint_core.apply_baseline(diags, entries) == []
    # ...but a new violation in the same file is not
    bad.write_text("import time\ndeadline = time.time() + 5\n"
                   "other = time.time() - 1\n")
    fresh = lint_core.lint_file(bad, select=["TIME001"])
    remaining = lint_core.apply_baseline(fresh, entries)
    assert len(remaining) == 1
    assert "time.time() - 1" in remaining[0].message


def test_committed_baseline_entries_all_have_justifications():
    for e in lint_core.load_baseline(BASELINE):
        assert e.get("justification", "").strip(), \
            f"baseline entry without justification: {e}"
        assert "TODO" not in e["justification"], \
            f"unfilled baseline justification: {e}"


# ---------------------------------------------------------------------------
# EXC001 + the legacy shim contract (tools/check_swallowed_exceptions.py)
# ---------------------------------------------------------------------------

def _violations(snippet, tmp_path):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(snippet))
    return list(csx.check_file(f))


def test_library_is_clean():
    assert csx.main([str(REPO / "determined_clone_tpu")]) == 0


def test_tools_and_bench_are_clean():
    assert csx.main([str(REPO / "tools"), str(REPO / "bench.py")]) == 0


def test_flags_uncommented_swallow(tmp_path):
    v = _violations(
        """
        try:
            work()
        except Exception:
            pass
        """, tmp_path)
    assert len(v) == 1
    assert "except Exception" in v[0][1]


def test_flags_bare_except_and_ellipsis(tmp_path):
    v = _violations(
        """
        try:
            work()
        except:
            ...
        """, tmp_path)
    assert len(v) == 1


def test_comment_on_pass_line_suppresses(tmp_path):
    assert _violations(
        """
        try:
            work()
        except Exception:
            pass  # best-effort cleanup; never mask the original error
        """, tmp_path) == []


def test_comment_above_try_suppresses(tmp_path):
    assert _violations(
        """
        # Transient poll failures must not kill training; the watcher
        # retries on its next tick.
        try:
            work()
        except Exception:
            pass
        """, tmp_path) == []


def test_narrow_handler_is_fine(tmp_path):
    assert _violations(
        """
        try:
            work()
        except KeyError:
            pass
        """, tmp_path) == []


def test_broad_handler_with_real_body_is_fine(tmp_path):
    assert _violations(
        """
        try:
            work()
        except Exception:
            log.warning("work failed")
        """, tmp_path) == []


def test_tuple_including_broad_is_flagged(tmp_path):
    v = _violations(
        """
        try:
            work()
        except (ValueError, Exception):
            pass
        """, tmp_path)
    assert len(v) == 1


def test_exc001_is_the_same_check_via_dctlint(tmp_path):
    v = _lint(
        """
        try:
            work()
        except Exception:
            pass
        """, tmp_path, select=["EXC001"])
    assert _rules(v) == ["EXC001"]
