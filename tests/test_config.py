"""Config-system tests, modeled on the reference's expconf schema test cases
(schemas/test_cases/, run by master/pkg/schemas/expconf schema_test.go)."""
import random

import pytest

from determined_clone_tpu.config import (
    ConfigError,
    ExperimentConfig,
    HyperparameterSpace,
    Length,
    SearcherConfig,
    merge_configs,
)
from determined_clone_tpu.config.length import Unit


class TestLength:
    def test_units_parse(self):
        assert Length.from_dict({"batches": 100}) == Length.batches(100)
        assert Length.from_dict({"records": 640}) == Length.records(640)
        assert Length.from_dict({"epochs": 3}) == Length.epochs(3)
        assert Length.from_dict(50) == Length.batches(50)

    def test_bad_unit(self):
        with pytest.raises(ValueError, match="unknown length unit"):
            Length.from_dict({"steps": 10})
        with pytest.raises(ValueError):
            Length.from_dict({"batches": 1, "epochs": 2})

    def test_to_batches(self):
        assert Length.batches(7).to_batches(32) == 7
        assert Length.records(640).to_batches(64) == 10
        assert Length.epochs(2).to_batches(64, records_per_epoch=640) == 20

    def test_epochs_require_records_per_epoch(self):
        with pytest.raises(ValueError, match="records_per_epoch"):
            Length.epochs(1).to_batches(32)

    def test_roundtrip(self):
        l = Length(Unit.EPOCHS, 4)
        assert Length.from_dict(l.to_dict()) == l


class TestHyperparameters:
    def test_implicit_const(self):
        space = HyperparameterSpace({"lr": 0.1, "layers": [1, 2]})
        got = space.sample(random.Random(0))
        assert got == {"lr": 0.1, "layers": [1, 2]}

    def test_sample_ranges(self):
        space = HyperparameterSpace({
            "lr": {"type": "log", "minval": -4, "maxval": -1},
            "width": {"type": "int", "minval": 8, "maxval": 64},
            "act": {"type": "categorical", "vals": ["relu", "gelu"]},
            "drop": {"type": "double", "minval": 0.0, "maxval": 0.5},
        })
        rng = random.Random(1234)
        for _ in range(50):
            s = space.sample(rng)
            assert 1e-4 <= s["lr"] <= 1e-1
            assert 8 <= s["width"] <= 64
            assert s["act"] in ("relu", "gelu")
            assert 0.0 <= s["drop"] <= 0.5

    def test_sampling_deterministic_per_seed(self):
        space = HyperparameterSpace({"w": {"type": "int", "minval": 0, "maxval": 1000}})
        a = space.sample(random.Random(7))
        b = space.sample(random.Random(7))
        assert a == b

    def test_nested_spaces(self):
        space = HyperparameterSpace({
            "optimizer": {"lr": {"type": "double", "minval": 0.1, "maxval": 0.1, "count": 1},
                          "name": "adam"},
        })
        s = space.sample(random.Random(0))
        assert s == {"optimizer": {"lr": 0.1, "name": "adam"}}

    def test_grid_enumeration(self):
        space = HyperparameterSpace({
            "a": {"type": "categorical", "vals": [1, 2, 3]},
            "b": {"type": "double", "minval": 0.0, "maxval": 1.0, "count": 2},
        })
        points = list(space.grid())
        assert space.grid_size() == 6
        assert len(points) == 6
        assert {(p["a"], p["b"]) for p in points} == {
            (a, b) for a in (1, 2, 3) for b in (0.0, 1.0)
        }

    def test_grid_requires_count_for_double(self):
        space = HyperparameterSpace({"b": {"type": "double", "minval": 0, "maxval": 1}})
        with pytest.raises(ValueError, match="count"):
            list(space.grid())

    def test_int_grid_without_count_enumerates(self):
        space = HyperparameterSpace({"n": {"type": "int", "minval": 2, "maxval": 5}})
        assert [p["n"] for p in space.grid()] == [2, 3, 4, 5]


class TestSearcherConfig:
    def test_defaults_single(self):
        cfg = SearcherConfig.from_dict({})
        assert cfg.name == "single"
        assert cfg.smaller_is_better

    def test_unknown_name_rejected(self):
        with pytest.raises(ConfigError, match="unknown searcher"):
            SearcherConfig.from_dict({"name": "bayesian"})

    def test_asha_validation(self):
        with pytest.raises(ConfigError, match="divisor"):
            SearcherConfig.from_dict({"name": "asha", "divisor": 1, "max_trials": 4})

    def test_roundtrip(self):
        raw = {"name": "adaptive_asha", "metric": "accuracy", "smaller_is_better": False,
               "max_trials": 16, "max_length": {"batches": 1000}, "mode": "aggressive"}
        cfg = SearcherConfig.from_dict(raw)
        again = SearcherConfig.from_dict(cfg.to_dict())
        assert again.name == "adaptive_asha"
        assert again.metric == "accuracy"
        assert again.max_length == Length.batches(1000)
        assert again.mode == "aggressive"


class TestExperimentConfig:
    def test_minimal(self):
        cfg = ExperimentConfig.from_dict({})
        assert cfg.searcher.name == "single"
        assert cfg.resources.slots_per_trial == 1
        assert cfg.max_restarts == 5

    def test_full(self):
        cfg = ExperimentConfig.from_dict({
            "name": "mnist-tpu",
            "entrypoint": "model_def:MnistTrial",
            "searcher": {"name": "random", "metric": "accuracy",
                         "smaller_is_better": False, "max_trials": 8,
                         "max_length": {"epochs": 2}},
            "resources": {"slots_per_trial": 8, "topology": "v5e-8"},
            "hyperparameters": {"lr": {"type": "log", "minval": -4, "maxval": -2}},
            "checkpoint_storage": {"type": "shared_fs", "host_path": "/tmp/ckpt"},
            "records_per_epoch": 60000,
            "reproducibility": {"experiment_seed": 42},
            "log_policies": [{"pattern": "XlaRuntimeError", "action": "exclude_node"}],
        })
        assert cfg.resources.topology == "v5e-8"
        assert cfg.experiment_seed == 42
        assert cfg.checkpoint_storage.host_path == "/tmp/ckpt"
        assert cfg.log_policies[0].action == "exclude_node"
        # roundtrip through to_dict keeps the essentials
        again = ExperimentConfig.from_dict(cfg.to_dict())
        assert again.resources.slots_per_trial == 8
        assert again.searcher.max_trials == 8

    def test_multislice_topology_object(self):
        cfg = ExperimentConfig.from_dict({
            "resources": {"slots_per_trial": 16,
                          "topology": {"slices": 2, "slice_shape": "v5e-8"}},
        })
        assert cfg.resources.slices == 2
        assert cfg.resources.topology == "v5e-8"
        # round-trip preserves the object form the master parses
        again = ExperimentConfig.from_dict(cfg.to_dict())
        assert again.resources.slices == 2
        assert again.resources.topology == "v5e-8"
        # slices must divide slots_per_trial
        with pytest.raises(ConfigError):
            ExperimentConfig.from_dict({
                "resources": {"slots_per_trial": 9,
                              "topology": {"slices": 2}},
            })

    def test_invalid_fields(self):
        with pytest.raises(ConfigError):
            ExperimentConfig.from_dict({"checkpoint_policy": "sometimes"})
        with pytest.raises(ConfigError):
            ExperimentConfig.from_dict({"max_restarts": -1})
        with pytest.raises(ConfigError):
            ExperimentConfig.from_dict({"resources": {"priority": 1000}})
        with pytest.raises(ConfigError):
            ExperimentConfig.from_dict(
                {"checkpoint_storage": {"type": "gcs"}}  # missing bucket
            )

    def test_yaml(self, tmp_path):
        p = tmp_path / "exp.yaml"
        p.write_text(
            "name: yaml-exp\nsearcher:\n  name: grid\n  metric: loss\n"
            "hyperparameters:\n  depth:\n    type: categorical\n    vals: [2, 4]\n"
        )
        cfg = ExperimentConfig.from_yaml(str(p))
        assert cfg.name == "yaml-exp"
        assert cfg.hyperparameters.grid_size() == 2


class TestTemplateMerge:
    def test_merge_nested(self):
        base = {"resources": {"slots_per_trial": 1, "resource_pool": "default"},
                "labels": ["a"]}
        override = {"resources": {"slots_per_trial": 8}, "labels": ["b"]}
        merged = merge_configs(base, override)
        assert merged["resources"] == {"slots_per_trial": 8, "resource_pool": "default"}
        assert merged["labels"] == ["b"]  # lists replace, not append
