"""TPU-VM provisioner e2e: queue depth drives dry-run gcloud scale actions.

≈ the reference's provisioner flow (agentrm/provisioner/provisioner.go:44):
pending workload → scale decider → instance launch; agent registers →
startup tracking clears; idle fleet → terminate. Dry-run records the exact
gcloud tpu-vm command lines.
"""
import subprocess
import time
import urllib.request
import json as jsonlib
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"


@pytest.fixture()
def master(tmp_path):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp_path / "data"),
         "--provision-accelerator", "v5litepod-8",
         "--provision-zone", "us-central2-b",
         "--provision-slots", "8", "--provision-max", "2",
         "--provision-cooldown", "0", "--provision-idle-timeout", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    yield port
    proc.kill()
    proc.wait(timeout=10)


def req(port, method, path, body=None):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=jsonlib.dumps(body).encode() if body is not None else None,
        method=method, headers={"Content-Type": "application/json"})
    return jsonlib.loads(urllib.request.urlopen(r, timeout=5).read() or "{}")


def wait_for(fn, timeout=20, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {desc}")


def test_queue_depth_launches_and_idle_terminates(master):
    port = master
    status = req(port, "GET", "/api/v1/provisioner")
    assert status["enabled"] and status["dry_run"]
    assert status["commands"] == []

    # queue a 12-slot gang with no agents: decider wants 2 slices (capped
    # at provision-max 2)
    req(port, "POST", "/api/v1/tasks",
        {"type": "command", "cmd": ["sleep", "1"], "slots": 12})
    status = wait_for(
        lambda: (lambda s: s if len(s.get("commands", [])) >= 2 else None)(
            req(port, "GET", "/api/v1/provisioner")),
        desc="launch commands recorded")
    creates = [c for c in status["commands"] if " create " in c]
    assert len(creates) == 2
    assert all("gcloud compute tpus tpu-vm create" in c for c in creates)
    assert all("--accelerator-type v5litepod-8" in c for c in creates)
    assert all("--zone us-central2-b" in c for c in creates)
    assert len(status["starting"]) == 2

    # the instances' agents register → startup tracking clears
    names = [s["name"] for s in status["starting"]]
    for name in names:
        req(port, "POST", "/api/v1/agents/register",
            {"id": name, "slots": 8, "topology": "v5e-8",
             "address": "127.0.0.1:0"})
    wait_for(
        lambda: not req(port, "GET", "/api/v1/provisioner")["starting"],
        desc="starting cleared after registration")

    # kill the queued task → fleet idle → terminated after idle-timeout
    task_id = req(port, "GET", "/api/v1/tasks")["tasks"][0]["id"]
    req(port, "POST", f"/api/v1/tasks/{task_id}/kill")
    status = wait_for(
        lambda: (lambda s: s if sum(" delete " in c for c in
                                    s.get("commands", [])) >= 2 else None)(
            req(port, "GET", "/api/v1/provisioner")),
        timeout=30, desc="idle fleet terminated")
    deletes = [c for c in status["commands"] if " delete " in c]
    assert all(any(n in c for n in names) for c in deletes)
    # terminated agents are disabled so the scheduler stops using them
    agents = req(port, "GET", "/api/v1/agents")["agents"]
    assert all(not a["enabled"] for a in agents)


def test_provisioner_disabled_by_default(tmp_path):
    if not MASTER_BIN.exists():
        pytest.skip("C++ master build unavailable")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp_path / "data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
                break
            except Exception:
                time.sleep(0.2)
        assert req(port, "GET", "/api/v1/provisioner") == {"enabled": False}
    finally:
        proc.kill()
        proc.wait(timeout=10)
