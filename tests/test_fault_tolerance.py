"""Chaos suite: deterministic fault injection driving the crash-consistent
checkpoint commit protocol and the unified retry/backoff layer
(docs/fault_tolerance.md).

Every scenario here is seeded — a FaultPlan's rule RNGs derive from
(seed, rule index), so a failing chaos run reproduces exactly from its
seed. The invariant under test throughout: **restore never loads a
partial checkpoint** — any save interrupted before its COMMIT marker is
refused with CheckpointCorruptError and callers fall back to the last
committed state.
"""
import json
import logging
import os
import random
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_clone_tpu import core, faults
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.config.experiment import ConfigError
from determined_clone_tpu.core._checkpoint import (
    CheckpointCorruptError,
    validate_checkpoint_dir,
)
from determined_clone_tpu.experiment import LocalExperimentRunner
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.storage import transfer
from determined_clone_tpu.storage.base import (
    COMMIT_FILE,
    STORAGE_IO_POLICY,
    SharedFSStorageManager,
)
from determined_clone_tpu.training import JaxTrial, Trainer, TrialContext
from determined_clone_tpu.utils import retry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pin_sequential_pool(monkeypatch):
    """Force the shared transfer pool inline/in-order for this test.

    Fault rules that target the Nth hit of a transfer point (or mirror a
    seeded RNG draw-for-draw) need per-file order to be deterministic;
    parallel workers would race the hit counter. monkeypatch restores the
    real pool afterwards."""
    monkeypatch.setattr(transfer, "_pool",
                        transfer.TransferPool(workers=0))


@pytest.fixture(autouse=True)
def clean_fault_state(monkeypatch):
    """Every test starts with no active plan, empty plan caches, and a
    clean retry-stats table; DCT_FAULT_PLAN never leaks in from outside."""
    monkeypatch.delenv("DCT_FAULT_PLAN", raising=False)
    faults.reset()
    retry.reset_stats()
    yield
    faults.reset()
    retry.reset_stats()


# ---------------------------------------------------------------------------
# FaultPlan mechanics
# ---------------------------------------------------------------------------

def fired_pattern(plan, point, hits):
    out = []
    for _ in range(hits):
        try:
            plan.hit(point)
            out.append(False)
        except faults.FaultInjected:
            out.append(True)
    return out


def test_nth_and_times_fire_window():
    plan = faults.plan_from_dict({"rules": [
        {"point": "storage.upload", "nth": 2, "times": 2}]})
    assert fired_pattern(plan, "storage.upload", 5) == \
        [False, True, True, False, False]
    assert plan.stats() == [{"point": "storage.upload", "action": "error",
                             "hits": 5, "fires": 2}]


def test_times_zero_is_unlimited_and_glob_matches():
    plan = faults.plan_from_dict({"rules": [
        {"point": "storage.*", "nth": 1, "times": 0}]})
    assert fired_pattern(plan, "storage.download", 4) == [True] * 4
    # non-matching points never fire
    plan.hit("api.request")


def test_seeded_probability_is_reproducible():
    raw = {"seed": 42, "rules": [
        {"point": "p", "times": 0, "probability": 0.5}]}
    a = fired_pattern(faults.plan_from_dict(raw), "p", 32)
    b = fired_pattern(faults.plan_from_dict(raw), "p", 32)
    assert a == b
    assert True in a and False in a  # the coin actually flips at p=0.5


def test_injected_exception_types_map_to_retryability():
    for exc, types in [("fault", (faults.FaultInjected,)),
                       ("io", (faults.FaultInjected, OSError)),
                       ("conn", (faults.FaultInjected, ConnectionError))]:
        plan = faults.plan_from_dict({"rules": [{"point": "p", "exc": exc}]})
        with pytest.raises(types):
            plan.hit("p")
    # plain "fault" must NOT be retryable under the default policy
    plan = faults.plan_from_dict({"rules": [{"point": "p"}]})
    try:
        plan.hit("p")
    except faults.FaultInjected as e:
        assert not isinstance(e, retry.DEFAULT_RETRYABLE)


def test_point_is_noop_without_plan_and_truncate_is_separate():
    faults.point("anything.at.all")  # no plan: must be free and silent
    plan = faults.activate(faults.plan_from_dict({"rules": [
        {"point": "p", "action": "truncate", "keep_bytes": 3}]}))
    # truncate rules never raise from point(); only truncate_bytes consults
    faults.point("p")
    assert faults.truncate_bytes("p") == 3
    assert faults.truncate_bytes("p") is None  # times=1 exhausted
    faults.deactivate(plan)


def test_env_install_caches_plan_and_counters(monkeypatch, tmp_path):
    payload = json.dumps({"rules": [{"point": "p", "nth": 2}]})
    monkeypatch.setenv("DCT_FAULT_PLAN", payload)
    plan1 = faults.install_from_env()
    plan1.hit("p")  # hit 1: below nth, doesn't fire
    plan2 = faults.install_from_env()
    assert plan2 is plan1  # cached by payload: counters carried over
    with pytest.raises(faults.FaultInjected):
        plan2.hit("p")
    # a file path works too
    f = tmp_path / "plan.json"
    f.write_text(payload)
    monkeypatch.setenv("DCT_FAULT_PLAN", str(f))
    assert faults.install_from_env() is not plan1


def test_config_faults_block_roundtrip_and_validation(tmp_path):
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path)},
        "faults": {"seed": 7, "rules": [
            {"point": "storage.upload", "exc": "io", "times": 2}]},
    })
    assert cfg.faults.seed == 7
    d = cfg.to_dict()
    assert d["faults"]["rules"][0]["point"] == "storage.upload"
    assert ExperimentConfig.from_dict(d).faults.rules == cfg.faults.rules
    with pytest.raises(ConfigError):
        ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 4}},
            "faults": {"rules": [{"point": "p", "action": "explode"}]},
        })


# ---------------------------------------------------------------------------
# unified retry/backoff
# ---------------------------------------------------------------------------

def test_backoff_sequence_without_jitter():
    p = retry.RetryPolicy(name="t", base_delay_s=0.1, multiplier=2.0,
                          max_delay_s=0.5, jitter="none")
    assert [p.backoff(f) for f in range(1, 6)] == \
        [0.1, 0.2, 0.4, 0.5, 0.5]


def test_full_jitter_draws_below_exponential_cap():
    p = retry.RetryPolicy(name="t", base_delay_s=0.1, multiplier=2.0,
                          max_delay_s=5.0)
    rng, mirror = random.Random(123), random.Random(123)
    drawn = [p.backoff(f, rng=rng) for f in range(1, 5)]
    expect = [mirror.uniform(0.0, min(5.0, 0.1 * 2.0 ** (f - 1)))
              for f in range(1, 5)]
    # each draw is mirrored exactly and bounded by its cap
    for f, (got, want) in enumerate(zip(drawn, expect), start=1):
        assert got == want
        assert 0.0 <= got <= 0.1 * 2.0 ** (f - 1)


def test_retry_call_sleeps_then_succeeds_and_records():
    p = retry.RetryPolicy(name="unit", max_attempts=4, base_delay_s=0.1,
                          jitter="none")
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    assert retry.retry_call(flaky, policy=p, sleep=sleeps.append) == "ok"
    assert sleeps == [0.1, 0.2]
    assert retry.stats()["unit"] == 2


def test_retry_call_exhaustion_and_non_retryable():
    p = retry.RetryPolicy(name="unit", max_attempts=3, jitter="none")

    def always():
        raise TimeoutError("down")

    with pytest.raises(TimeoutError):
        retry.retry_call(always, policy=p, sleep=lambda s: None)

    calls = {"n": 0}

    def raises_value_error():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry.retry_call(raises_value_error, policy=p,
                         sleep=lambda s: None)
    assert calls["n"] == 1  # never retried


def test_retry_call_deadline_caps_and_stops():
    p = retry.RetryPolicy(name="unit", max_attempts=100, base_delay_s=10.0,
                          jitter="none", deadline_s=0.0)

    def always():
        raise ConnectionError("down")

    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        retry.retry_call(always, policy=p)
    assert time.monotonic() - t0 < 1.0  # gave up at the deadline, no sleep


# ---------------------------------------------------------------------------
# storage: flaky uploads retry with the policy's exact backoff
# ---------------------------------------------------------------------------

def test_flaky_upload_retries_and_resumes(tmp_path, monkeypatch):
    pin_sequential_pool(monkeypatch)
    src = tmp_path / "src"
    src.mkdir()
    (src / "a.bin").write_bytes(b"aaaa")
    (src / "b.bin").write_bytes(b"bbbb")
    mgr = SharedFSStorageManager(str(tmp_path / "store"))

    sleeps = []
    monkeypatch.setattr(retry, "_sleep", sleeps.append)
    monkeypatch.setattr(retry, "_rng", random.Random(5))
    mirror = random.Random(5)

    # first file's copy fails twice (io = retryable), then all succeed
    with faults.plan_active({"rules": [
            {"point": "storage.upload", "exc": "io", "nth": 1,
             "times": 2}]}):
        mgr.upload(str(src), "ck-1")

    assert mgr.list_files("ck-1") == {"a.bin": 4, "b.bin": 4}
    # two retries, each delay drawn from the storage policy's jitter window
    assert sleeps == [STORAGE_IO_POLICY.backoff(1, rng=mirror),
                      STORAGE_IO_POLICY.backoff(2, rng=mirror)]
    assert retry.stats()["storage_io"] == 2


def test_flaky_upload_exhausts_to_caller(tmp_path, monkeypatch):
    src = tmp_path / "src"
    src.mkdir()
    (src / "a.bin").write_bytes(b"aaaa")
    mgr = SharedFSStorageManager(str(tmp_path / "store"))
    monkeypatch.setattr(retry, "_sleep", lambda s: None)
    with faults.plan_active({"rules": [
            {"point": "storage.upload", "exc": "io", "times": 0}]}):
        with pytest.raises(faults.InjectedIOError):
            mgr.upload(str(src), "ck-1")


# ---------------------------------------------------------------------------
# commit protocol
# ---------------------------------------------------------------------------

def make_core(tmp_path, trial_id=1):
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path)},
    })
    return core.init(config=cfg, trial_id=trial_id)


def test_upload_commits_manifest_and_marker(tmp_path):
    with make_core(tmp_path) as cctx:
        with cctx.checkpoint.store_path() as (path, holder):
            with open(os.path.join(path, "weights.bin"), "wb") as f:
                f.write(b"\x01" * 64)
        sid = holder["storage_id"]
        stored = tmp_path / sid
        assert (stored / COMMIT_FILE).exists()
        manifest = json.loads((stored / "manifest.json").read_text())
        assert manifest["storage_id"] == sid
        assert manifest["files"]["weights.bin"]["size"] == 64
        # protocol files never list themselves
        assert COMMIT_FILE not in manifest["files"]
        assert "manifest.json" not in manifest["files"]
        with cctx.checkpoint.restore_path(sid) as rpath:
            assert open(os.path.join(rpath, "weights.bin"), "rb"
                        ).read() == b"\x01" * 64
        assert cctx.checkpoint.committed_checkpoints() == [sid]


def test_uncommitted_checkpoint_is_refused(tmp_path):
    with make_core(tmp_path) as cctx:
        with cctx.checkpoint.store_path() as (path, holder):
            with open(os.path.join(path, "weights.bin"), "wb") as f:
                f.write(b"\x02" * 16)
        sid = holder["storage_id"]
        os.unlink(tmp_path / sid / COMMIT_FILE)  # simulate crash pre-commit
        with pytest.raises(CheckpointCorruptError) as ei:
            with cctx.checkpoint.restore_path(sid):
                pass
        assert "no COMMIT marker" in str(ei.value)
        assert ei.value.storage_id == sid


def test_torn_write_detected_by_manifest(tmp_path, monkeypatch):
    pin_sequential_pool(monkeypatch)
    monkeypatch.setattr(retry, "_sleep", lambda s: None)
    with make_core(tmp_path) as cctx:
        # truncate the 2nd uploaded file (manifest goes first, then data)
        with faults.plan_active({"rules": [
                {"point": "storage.upload", "action": "truncate",
                 "nth": 2, "keep_bytes": 3}]}):
            with cctx.checkpoint.store_path() as (path, holder):
                with open(os.path.join(path, "weights.bin"), "wb") as f:
                    f.write(b"\x03" * 32)
        sid = holder["storage_id"]
        # committed — but the manifest convicts the torn file on restore
        assert (tmp_path / sid / COMMIT_FILE).exists()
        with pytest.raises(CheckpointCorruptError) as ei:
            with cctx.checkpoint.restore_path(sid):
                pass
        assert "torn write" in ei.value.reason


def test_commit_fault_leaves_checkpoint_unpublished(tmp_path):
    with make_core(tmp_path) as cctx:
        with pytest.raises(faults.FaultInjected):
            with faults.plan_active({"rules": [
                    {"point": "storage.commit"}]}):
                with cctx.checkpoint.store_path() as (path, _):
                    with open(os.path.join(path, "w.bin"), "wb") as f:
                        f.write(b"\x04" * 8)
        # nothing published: restore-fallback candidates stay empty, and
        # the on-disk leftover is refused by validation
        assert cctx.checkpoint.committed_checkpoints() == []
        leftovers = SharedFSStorageManager(str(tmp_path)).list_storage_ids()
        dirs = [d for d in leftovers if (tmp_path / d / "w.bin").exists()]
        assert dirs
        with pytest.raises(CheckpointCorruptError):
            validate_checkpoint_dir(str(tmp_path / dirs[0]))


def test_validate_rejects_empty_and_accepts_legacy(tmp_path):
    legacy = tmp_path / "legacy"
    legacy.mkdir()
    (legacy / "state.bin").write_bytes(b"old")
    # pre-protocol checkpoint: nothing to verify, but not refused
    assert validate_checkpoint_dir(str(legacy)) is False
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(CheckpointCorruptError):
        validate_checkpoint_dir(str(empty))


@pytest.mark.parametrize("seed", [7, 11])
def test_interrupted_saves_never_restorable(tmp_path, monkeypatch, seed):
    """The core chaos invariant, on two seeds: under random injected
    storage failures, every checkpoint id on disk is either committed
    (and fully validates) or is refused by restore — there is no third
    state where a partial save loads."""
    pin_sequential_pool(monkeypatch)
    monkeypatch.setattr(retry, "_sleep", lambda s: None)
    with make_core(tmp_path) as cctx:
        ck = cctx.checkpoint
        # p=0.5 per copy attempt: with 4 attempts/file some files pull
        # through and some uploads die partway — the interesting mix
        with faults.plan_active({"seed": seed, "rules": [
                {"point": "storage.upload", "exc": "io", "times": 0,
                 "probability": 0.5}]}):
            outcomes = []
            for i in range(8):
                try:
                    with ck.store_path() as (path, holder):
                        for j in range(3):
                            with open(os.path.join(path, f"f{j}.bin"),
                                      "wb") as f:
                                f.write(bytes([i]) * 128)
                    outcomes.append(("ok", holder["storage_id"]))
                except OSError:
                    outcomes.append(("failed", None))
        assert {o for o, _ in outcomes} == {"ok", "failed"}, \
            f"seed {seed} produced no failure/success mix: {outcomes}"

        committed = set(ck.committed_checkpoints())
        on_disk = SharedFSStorageManager(str(tmp_path)).list_storage_ids()
        ckpt_dirs = [d for d in on_disk
                     if d != "checkpoints.jsonl" and (tmp_path / d).is_dir()]
        assert committed <= set(ckpt_dirs)
        for sid in ckpt_dirs:
            if sid in committed:
                with ck.restore_path(sid) as path:  # validates
                    assert sorted(os.listdir(path)) == \
                        ["COMMIT", "f0.bin", "f1.bin", "f2.bin",
                         "manifest.json", "metadata.json"]
            else:
                with pytest.raises(CheckpointCorruptError):
                    with ck.restore_path(sid):
                        pass


# ---------------------------------------------------------------------------
# trainer: restore falls back past a refused checkpoint
# ---------------------------------------------------------------------------

class DriftTrial(JaxTrial):
    """Loss depends on the batch content, so replay/skip mistakes after a
    restore change the final params — resume equivalence is a real check."""

    n_batches = 24

    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.05)

    def loss(self, params, batch, rng):
        target = jnp.mean(batch)
        loss = (params["w"] - target) ** 2
        return loss, {"w": params["w"]}

    def training_data(self):
        for i in range(self.n_batches):
            yield np.full((4, 1), float(i % 7), np.float32)

    def validation_data(self):
        return [np.ones((4, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 4


def drift_config(tmp_path, batches=24):
    return {
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": batches}},
        "scheduling_unit": 4,
        "min_checkpoint_period": {"batches": 8},
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path)},
        "optimizations": {"prefetch_depth": 0},
    }


def test_restore_falls_back_past_corrupt_checkpoint(tmp_path, caplog):
    cfg = ExperimentConfig.from_dict(drift_config(tmp_path, batches=16))
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    with core.init(config=cfg, trial_id=1) as cctx:
        ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
        Trainer(DriftTrial(ctx)).fit()
        sids = cctx.checkpoint.committed_checkpoints()  # newest first
    assert len(sids) >= 2
    newest, previous = sids[0], sids[1]
    # corrupt the newest AFTER it was published (crash wouldn't publish;
    # this models storage losing the marker post-hoc — same refusal path)
    os.unlink(tmp_path / newest / COMMIT_FILE)

    cfg2 = ExperimentConfig.from_dict(drift_config(tmp_path, batches=24))
    with core.init(config=cfg2, trial_id=1) as cctx:
        ctx = TrialContext(config=cfg2, hparams={}, core=cctx, mesh=mesh)
        with caplog.at_level(
                logging.WARNING,
                logger="determined_clone_tpu.training.trainer"):
            result = Trainer(DriftTrial(ctx)).fit(latest_checkpoint=newest)
    assert result["batches_trained"] == 24
    assert any(f"checkpoint {newest} refused" in r.getMessage()
               for r in caplog.records)

    # the fallback resumed from `previous`, and the end state matches a
    # straight 24-batch run (the restore replayed the data stream right)
    baseline_dir = tmp_path / "baseline"
    baseline_dir.mkdir()
    cfg3 = ExperimentConfig.from_dict(drift_config(baseline_dir, batches=24))
    with core.init(config=cfg3, trial_id=1) as cctx:
        ctx = TrialContext(config=cfg3, hparams={}, core=cctx, mesh=mesh)
        Trainer(DriftTrial(ctx)).fit()
        base_sid = cctx.checkpoint.committed_checkpoints()[0]
        with cctx.checkpoint.restore_path(base_sid) as p:
            base_meta = json.load(open(os.path.join(p, "metadata.json")))
    assert base_meta["steps_completed"] == 24
    assert previous in sids


def test_restore_raises_when_every_candidate_corrupt(tmp_path):
    cfg = ExperimentConfig.from_dict(drift_config(tmp_path, batches=8))
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    with core.init(config=cfg, trial_id=1) as cctx:
        ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
        Trainer(DriftTrial(ctx)).fit()
        sids = cctx.checkpoint.committed_checkpoints()
        for sid in sids:
            os.unlink(tmp_path / sid / COMMIT_FILE)
        ctx2 = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
        with pytest.raises(CheckpointCorruptError):
            Trainer(DriftTrial(ctx2)).fit(latest_checkpoint=sids[0])


# ---------------------------------------------------------------------------
# content-addressed store: chunk faults during save are refused on restore
# ---------------------------------------------------------------------------

def cas_storage(tmp_path):
    return {"type": "cas", "chunk_size_kb": 1,
            "inner": {"type": "shared_fs", "host_path": str(tmp_path)}}


def make_cas_core(tmp_path, trial_id=1):
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "checkpoint_storage": cas_storage(tmp_path),
    })
    return core.init(config=cfg, trial_id=trial_id)


def test_torn_chunk_makes_checkpoint_unrestorable(tmp_path, monkeypatch):
    pin_sequential_pool(monkeypatch)
    monkeypatch.setattr(retry, "_sleep", lambda s: None)
    with make_cas_core(tmp_path) as cctx:
        ck = cctx.checkpoint
        # truncate the 2nd chunk object as it is staged for upload;
        # chunks must differ or dedup collapses them to one upload
        with faults.plan_active({"rules": [
                {"point": "cas.chunk_upload", "action": "truncate",
                 "nth": 2, "keep_bytes": 5}]}):
            with ck.store_path() as (path, holder):
                with open(os.path.join(path, "weights.bin"), "wb") as f:
                    f.write(b"".join(bytes([i]) * 1024 for i in range(4)))
        sid = holder["storage_id"]
        # committed — the torn chunk is only convicted when restore
        # digest-checks it against the chunk manifest
        assert (tmp_path / sid / COMMIT_FILE).exists()
        with pytest.raises(CheckpointCorruptError) as ei:
            with ck.restore_path(sid):
                pass
        assert "torn chunk" in ei.value.reason
        assert ei.value.storage_id == sid


def test_dropped_chunk_makes_checkpoint_unrestorable(tmp_path, monkeypatch):
    pin_sequential_pool(monkeypatch)
    monkeypatch.setattr(retry, "_sleep", lambda s: None)
    with make_cas_core(tmp_path) as cctx:
        ck = cctx.checkpoint
        # the 1st chunk silently never reaches the backend (lost PUT)
        with faults.plan_active({"rules": [
                {"point": "cas.chunk_drop", "action": "truncate",
                 "keep_bytes": 0, "nth": 1, "times": 1}]}):
            with ck.store_path() as (path, holder):
                with open(os.path.join(path, "weights.bin"), "wb") as f:
                    f.write(b"\x07" * 3000)
        sid = holder["storage_id"]
        assert (tmp_path / sid / COMMIT_FILE).exists()
        with pytest.raises(CheckpointCorruptError) as ei:
            with ck.restore_path(sid):
                pass
        assert "missing from the chunk store" in ei.value.reason


def cas_drift_config(tmp_path, batches=24, telemetry=False):
    cfg = drift_config(tmp_path, batches)
    cfg["checkpoint_storage"] = cas_storage(tmp_path)
    if telemetry:
        cfg["observability"] = {"enabled": True}
    return cfg


def test_trainer_falls_back_past_missing_chunk_checkpoint(
        tmp_path, caplog, monkeypatch):
    """End-to-end: a committed CAS checkpoint that lost a chunk is refused
    at restore, the trainer falls back to the previous committed one, the
    fallback is counted, and training still reaches the full length."""
    from determined_clone_tpu.storage import cas as cas_mod

    pin_sequential_pool(monkeypatch)
    cfg = ExperimentConfig.from_dict(cas_drift_config(tmp_path, batches=16))
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    with core.init(config=cfg, trial_id=1) as cctx:
        ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
        Trainer(DriftTrial(ctx)).fit()
        sids = cctx.checkpoint.committed_checkpoints()  # newest first
    assert len(sids) >= 2
    newest, previous = sids[0], sids[1]

    # lose a chunk only the newest checkpoint references — exactly the
    # state a `cas.chunk_drop` fault during its save leaves behind
    mgr = cas_mod.CASStorageManager(
        SharedFSStorageManager(str(tmp_path)), chunk_size=1024)
    victims = sorted(mgr._referenced_digests(newest)
                     - mgr._referenced_digests(previous))
    assert victims  # the drifted params produced at least one new chunk
    os.unlink(tmp_path / cas_mod.CHUNK_NAMESPACE
              / cas_mod.chunk_rel(victims[0]))

    cfg2 = ExperimentConfig.from_dict(
        cas_drift_config(tmp_path, batches=24, telemetry=True))
    with core.init(config=cfg2, trial_id=1) as cctx:
        ctx = TrialContext(config=cfg2, hparams={}, core=cctx, mesh=mesh)
        with caplog.at_level(
                logging.WARNING,
                logger="determined_clone_tpu.training.trainer"):
            result = Trainer(DriftTrial(ctx)).fit(latest_checkpoint=newest)
        fallbacks = cctx.telemetry.registry.counter(
            "checkpoint_restore_fallbacks").value
    assert result["batches_trained"] == 24
    assert fallbacks == 1
    assert any(f"checkpoint {newest} refused" in r.getMessage()
               for r in caplog.records)
    assert previous in sids


# ---------------------------------------------------------------------------
# experiment runner: restarts back off with jitter and are counted
# ---------------------------------------------------------------------------

def test_runner_restart_backs_off_and_counts(tmp_path, monkeypatch):
    sleeps = []
    monkeypatch.setattr(retry, "_sleep", sleeps.append)
    cfg = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 8}},
        "scheduling_unit": 4,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(tmp_path)},
        "max_restarts": 2,
        "optimizations": {"prefetch_depth": 0},
        # leg 1 dies on its first step; the cached plan is exhausted by
        # leg 2, which then completes
        "faults": {"rules": [{"point": "training.pre_step",
                              "nth": 1, "times": 1}]},
    })
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    runner = LocalExperimentRunner(cfg, DriftTrial,
                                   storage_path=str(tmp_path), mesh=mesh)
    result = runner.run()
    t = list(result.trials.values())[0]
    assert t.state == "completed"
    assert t.restarts == 1
    assert runner.registry.counter("trial_restarts_total").value == 1
    restart_sleeps = [s for s in sleeps if s > 0]
    assert len(restart_sleeps) >= 1  # the backoff actually ran
    assert all(s <= runner.restart_backoff.max_delay_s for s in sleeps)
    # the restart was snapshotted before the backoff sleep
    snap = json.loads((tmp_path / "experiment_snapshot.json").read_text())
    assert list(snap["trials"].values())[0]["restarts"] == 1


# ---------------------------------------------------------------------------
# kill -9 mid-step: resume lands on the right batch
# ---------------------------------------------------------------------------

CHAOS_RUNNER = '''
import json, os, sys
sys.path.insert(0, {repo!r})
from determined_clone_tpu.utils.host_steering import steer_to_host_cpu
steer_to_host_cpu(8)
import jax
sys.path.insert(0, {testdir!r})
from test_fault_tolerance import DriftTrial, drift_config
from determined_clone_tpu import core
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.training import Trainer, TrialContext

cfg = ExperimentConfig.from_dict(drift_config({storage!r}, batches=24))
mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
with core.init(config=cfg, trial_id=1) as cctx:
    ctx = TrialContext(config=cfg, hparams={{}}, core=cctx, mesh=mesh)
    result = Trainer(DriftTrial(ctx)).fit()
print("COMPLETED", result["batches_trained"])
'''


@pytest.mark.slow
def test_kill9_mid_step_resumes_at_right_batch(tmp_path):
    """A subprocess trial is hard-killed (os._exit via an `exit` fault —
    no atexit, no flushes: kill -9 semantics) between the batch-8
    checkpoint and the batch-16 one. The resume must restore the batch-8
    state and land on the exact same final params as an uninterrupted
    run — proving both that the orphaned partial state is never loaded
    and that data replay after restore is off-by-none."""
    storage = tmp_path / "ckpts"
    storage.mkdir()
    script = tmp_path / "chaos_run.py"
    script.write_text(CHAOS_RUNNER.format(
        repo=REPO, testdir=os.path.join(REPO, "tests"),
        storage=str(storage)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PALLAS_AXON_POOL_IPS": "",
        # die on the 13th step dispatch — after the batch-8 commit
        "DCT_FAULT_PLAN": json.dumps({"rules": [
            {"point": "training.pre_step", "action": "exit",
             "nth": 13, "exit_code": 137}]}),
    }
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 137, proc.stdout + proc.stderr
    assert "COMPLETED" not in proc.stdout

    reg = core.LocalCheckpointRegistry(str(storage / "checkpoints.jsonl"))
    recs = reg.list()
    assert len(recs) == 1  # only the batch-8 save committed before death
    sid = recs[0]["storage_id"]
    assert recs[0]["metadata"]["steps_completed"] == 8

    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])

    def final_w(storage_dir, latest=None):
        cfg = ExperimentConfig.from_dict(
            drift_config(storage_dir, batches=24))
        with core.init(config=cfg, trial_id=1) as cctx:
            ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
            result = Trainer(DriftTrial(ctx)).fit(latest_checkpoint=latest)
            assert result["batches_trained"] == 24
            newest = cctx.checkpoint.committed_checkpoints()[0]
            with cctx.checkpoint.restore_path(newest) as p:
                state = json.load(open(os.path.join(p, "metadata.json")))
                assert state["steps_completed"] == 24
            backend = cctx.train._backend
            return [r for r in backend.records
                    if r["group"] == "training"][-1]["metrics"]["w"]

    resumed = final_w(storage, latest=sid)
    baseline_dir = tmp_path / "baseline"
    baseline_dir.mkdir()
    baseline = final_w(baseline_dir)
    np.testing.assert_allclose(resumed, baseline, rtol=1e-6)


# ---------------------------------------------------------------------------
# GC: orphaned uncommitted checkpoints are swept, committed ones kept
# ---------------------------------------------------------------------------

def test_gc_sweeps_old_uncommitted_dirs(tmp_path, monkeypatch, capsys):
    from determined_clone_tpu.exec import gc_checkpoints

    base = tmp_path / "store"
    mgr = SharedFSStorageManager(str(base))
    src = tmp_path / "src"
    src.mkdir()
    (src / "w.bin").write_bytes(b"x" * 8)
    mgr.upload(str(src), "committed-1")
    mgr.commit("committed-1")
    mgr.upload(str(src), "orphan-old")
    mgr.upload(str(src), "orphan-fresh")
    # backdate the old orphan past the age floor
    old = time.time() - 7200
    for root, _, files in os.walk(base / "orphan-old"):
        for f in files:
            os.utime(os.path.join(root, f), (old, old))
    os.utime(base / "orphan-old", (old, old))

    monkeypatch.setenv("DCT_GC_STORAGE", json.dumps(
        {"type": "shared_fs", "host_path": str(base)}))
    monkeypatch.setenv("DCT_GC_UUIDS", "")
    monkeypatch.setenv("DCT_GC_SWEEP_UNCOMMITTED", "1")
    monkeypatch.setenv("DCT_GC_UNCOMMITTED_AGE_S", "3600")
    assert gc_checkpoints.main() == 0
    out = capsys.readouterr().out
    assert "swept uncommitted checkpoint orphan-old" in out
    ids = mgr.list_storage_ids()
    assert "orphan-old" not in ids
    assert "committed-1" in ids     # COMMIT marker protects it
    assert "orphan-fresh" in ids    # too young: may still be uploading


def test_gc_sweep_disabled_by_default(tmp_path, monkeypatch):
    from determined_clone_tpu.exec import gc_checkpoints

    base = tmp_path / "store"
    mgr = SharedFSStorageManager(str(base))
    src = tmp_path / "src"
    src.mkdir()
    (src / "w.bin").write_bytes(b"x")
    mgr.upload(str(src), "orphan-old")
    old = time.time() - 7200
    for root, _, files in os.walk(base / "orphan-old"):
        for f in files:
            os.utime(os.path.join(root, f), (old, old))
    monkeypatch.setenv("DCT_GC_STORAGE", json.dumps(
        {"type": "shared_fs", "host_path": str(base)}))
    monkeypatch.setenv("DCT_GC_UUIDS", "")
    monkeypatch.delenv("DCT_GC_SWEEP_UNCOMMITTED", raising=False)
    assert gc_checkpoints.main() == 0
    assert "orphan-old" in mgr.list_storage_ids()


# ---------------------------------------------------------------------------
# preemption watcher: poll failures counted + rate-limited warning
# ---------------------------------------------------------------------------

def test_preempt_poll_failures_counted_and_warned(caplog):
    from determined_clone_tpu.core._distributed import DistributedContext
    from determined_clone_tpu.core._preempt import (
        PreemptContext,
        PreemptionSource,
    )
    from determined_clone_tpu.telemetry import MetricsRegistry

    class BrokenSource(PreemptionSource):
        def poll(self):
            raise RuntimeError("source is down")

    reg = MetricsRegistry()
    with caplog.at_level(logging.WARNING,
                         logger="determined_clone_tpu.core._preempt"):
        pc = PreemptContext(DistributedContext.single(), BrokenSource(),
                            poll_interval=0.01, registry=reg).start()
        try:
            deadline = time.monotonic() + 5.0
            while pc.poll_failures < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            pc.close()
    assert pc.poll_failures >= 3
    assert reg.counter("preempt_poll_failures").value == pc.poll_failures
    warnings = [r for r in caplog.records
                if "preemption poll failed" in r.getMessage()]
    assert len(warnings) == 1  # rate-limited: one per window, not per poll
    assert not pc.should_preempt()  # failures never read as "preempted"


# ---------------------------------------------------------------------------
# api client: transport retries + idempotency keys
# ---------------------------------------------------------------------------

def test_api_request_retries_transport_and_sends_idempotency_key(
        monkeypatch):
    import io
    import urllib.error
    import urllib.request

    from determined_clone_tpu.api.client import MasterError, MasterSession

    seen = {"bodies": [], "n": 0}

    class FakeResp(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_urlopen(req, timeout=None):
        seen["n"] += 1
        seen["bodies"].append(json.loads(req.data.decode()))
        if seen["n"] < 3:
            raise urllib.error.URLError("connection refused")
        return FakeResp(b'{"ok": true}')

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    monkeypatch.setattr(retry, "_sleep", lambda s: None)
    s = MasterSession("127.0.0.1", 1, retries=3)
    out = s.post("/api/v1/trials/1/metrics", {"loss": 1.0},
                 retryable=True, idempotency_key="k-123")
    assert out == {"ok": True}
    assert seen["n"] == 3
    assert retry.stats()["api_request"] == 2
    # every attempt (original + replays) carried the same key: the master
    # dedups instead of double-counting the metric report
    assert all(b["idempotency_key"] == "k-123" for b in seen["bodies"])

    # an HTTP answer from the master is NOT a transport error: no retry
    def http_error(req, timeout=None):
        seen["n"] += 1
        raise urllib.error.HTTPError(req.full_url, 400, "bad", {},
                                     io.BytesIO(b'{"error": "nope"}'))

    seen["n"] = 0
    monkeypatch.setattr(urllib.request, "urlopen", http_error)
    with pytest.raises(MasterError):
        s.post("/x", {}, retryable=True)
    assert seen["n"] == 1
