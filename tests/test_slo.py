"""SLO burn-rate engine + latency exemplars (docs/observability.md
"Request tracing & SLOs"): multi-window burn-rate math on simulated
clocks, verdict ordering, gauge export, the aggregator/inprocess-master
SLO surface, and the histogram exemplar ring that trades an aggregate
percentile for a concrete request id."""
import math

import pytest

from determined_clone_tpu.api.inprocess import InProcessMaster
from determined_clone_tpu.telemetry import MetricsRegistry
from determined_clone_tpu.telemetry.aggregate import (
    ClusterMetricsAggregator,
    format_summary,
)
from determined_clone_tpu.telemetry.metrics import parse_prometheus_text
from determined_clone_tpu.telemetry.slo import (
    FAST_BURN_THRESHOLD,
    WINDOWS,
    SLOEngine,
    format_slo,
)

T0 = 1_000_000.0  # simulated wall-clock origin; nothing reads time.time


def make_engine(**kw):
    kw.setdefault("clock", lambda: T0)
    return SLOEngine(**kw)


# -- engine math -------------------------------------------------------------


def test_no_traffic_is_no_data():
    ev = make_engine().evaluate(now=T0)
    assert ev["verdict"] == "no_data"
    for obj in ev["objectives"].values():
        assert obj["verdict"] == "no_data"
        assert all(w["burn_rate"] is None for w in obj["windows"].values())


def test_healthy_traffic_is_ok():
    slo = make_engine()
    for tick in range(72):  # 3 days of hourly traffic
        slo.record_request(ok=True, latency_s=0.01, n=50,
                           t=T0 - tick * 3600.0)
    ev = slo.evaluate(now=T0)
    assert ev["verdict"] == "ok"
    av = ev["objectives"]["availability"]
    assert av["windows"]["3d"]["total"] == 72 * 50
    assert av["windows"]["3d"]["burn_rate"] == 0.0


def test_burn_rate_is_bad_fraction_over_budget():
    slo = make_engine(availability_objective=0.999)
    # 2 errors in 100 requests → bad_fraction 0.02, budget 0.001 → 20x
    slo.record_request(ok=True, n=98, t=T0)
    slo.record_request(ok=False, n=2, t=T0)
    w = slo.evaluate(now=T0)["objectives"]["availability"]["windows"]["5m"]
    assert w["bad_fraction"] == pytest.approx(0.02)
    assert w["burn_rate"] == pytest.approx(20.0)


def test_fast_burn_needs_both_fast_windows():
    # errors only in the last 5 minutes: the 5m window burns hot but the
    # 1h window dilutes under 14.4x → not a fast burn (transient spike)
    slo = make_engine(availability_objective=0.999)
    slo.record_request(ok=False, n=20, t=T0)
    slo.record_request(ok=True, n=980, t=T0)
    slo.record_request(ok=True, n=100_000, t=T0 - 1800.0)
    av = slo.evaluate(now=T0)["objectives"]["availability"]
    assert av["windows"]["5m"]["burn_rate"] >= FAST_BURN_THRESHOLD
    assert av["windows"]["1h"]["burn_rate"] < FAST_BURN_THRESHOLD
    assert not av["burning_fast"]
    # sustain the error rate across the full hour → both windows burn
    for tick in range(12):
        slo.record_request(ok=False, n=5000, t=T0 - tick * 300.0)
    av = slo.evaluate(now=T0)["objectives"]["availability"]
    assert av["burning_fast"]
    assert av["verdict"] == "fast_burn"


def test_slow_burn_tickets_without_paging():
    # a steady 2x burn: over 1.0 on the slow pair (ticket) but nowhere
    # near 14.4 on the fast pair (no page)
    slo = make_engine(availability_objective=0.999)
    for tick in range(72):
        slo.record_request(ok=True, n=998, t=T0 - tick * 3600.0)
        slo.record_request(ok=False, n=2, t=T0 - tick * 3600.0)
    av = slo.evaluate(now=T0)["objectives"]["availability"]
    assert not av["burning_fast"]
    assert av["burning_slow"]
    assert av["verdict"] == "slow_burn"
    # overall verdict is the worst objective; latency saw no samples with
    # latency_s=None → but totals exist only for availability
    assert slo.evaluate(now=T0)["verdict"] == "slow_burn"


def test_latency_objective_judges_threshold():
    slo = make_engine(latency_objective=0.99, latency_threshold_s=0.5)
    for tick in range(72):
        slo.record_request(ok=True, latency_s=2.0, n=30,
                           t=T0 - tick * 3600.0)
        slo.record_request(ok=True, latency_s=0.05, n=70,
                           t=T0 - tick * 3600.0)
    lat = slo.evaluate(now=T0)["objectives"]["latency"]
    assert lat["threshold_s"] == 0.5
    # 30% slow against a 1% budget = 30x on every window → fast burn
    assert lat["verdict"] == "fast_burn"
    # availability is clean; overall takes the worst
    assert slo.evaluate(now=T0)["verdict"] == "fast_burn"


def test_buckets_outside_window_are_ignored():
    slo = make_engine()
    slo.record_request(ok=False, n=10, t=T0 - WINDOWS["3d"] - 7200.0)
    ev = slo.evaluate(now=T0)
    assert ev["verdict"] == "no_data"


def test_from_dict_and_validation():
    slo = SLOEngine.from_dict(
        {"availability_objective": 0.99, "latency_threshold_s": 1.5,
         "unknown_key": "ignored"}, clock=lambda: T0)
    assert slo.availability_objective == 0.99
    assert slo.latency_threshold_s == 1.5
    with pytest.raises(ValueError):
        SLOEngine(availability_objective=1.5)
    with pytest.raises(ValueError):
        SLOEngine(latency_threshold_s=0.0)


def test_publish_exports_gauges_and_format_renders():
    slo = make_engine()
    slo.record_request(ok=False, n=5, t=T0)
    reg = MetricsRegistry()
    ev = slo.publish(reg)
    text = reg.dump()
    assert 'dct_slo_objective{objective="availability"}' in text
    assert 'dct_slo_burn_rate{objective="availability",window="5m"}' in text
    assert "dct_slo_burning" in text
    # windows with no traffic export NaN, not 0 (absence, not health):
    # only availability saw requests, so latency burn rates are NaN
    parsed = parse_prometheus_text(text)
    lat_burns = [v for n, lab, v in parsed["samples"]
                 if n == "dct_slo_burn_rate"
                 and lab.get("objective") == "latency"]
    assert lat_burns and all(math.isnan(v) for v in lat_burns)
    rendered = format_slo(ev)
    assert "slo verdict:" in rendered
    assert "availability" in rendered and "latency" in rendered


def test_aggregator_slo_rollup_and_summary():
    agg = ClusterMetricsAggregator()
    assert agg.slo_rollup() is None
    slo = make_engine()
    slo.record_request(ok=True, latency_s=0.01, n=100, t=T0)
    agg.attach_slo(slo)
    roll = agg.slo_rollup()
    assert roll["verdict"] == "ok"
    # the rollup publishes into the aggregator registry → dump carries it
    assert "dct_slo_burn_rate" in agg.dump()
    summary = agg.summary()
    assert summary["slo"]["verdict"] == "ok"
    assert "slo: verdict ok" in format_summary(summary)


def test_inprocess_master_serves_cluster_slo():
    master = InProcessMaster()
    status, payload, _ = master.handle("GET", "/api/v1/cluster/slo")
    assert status == 200 and payload["slo"] is None
    slo = make_engine()
    slo.record_request(ok=False, n=3, t=T0)
    master.aggregator.attach_slo(slo)
    status, payload, _ = master.handle("GET", "/api/v1/cluster/slo")
    assert status == 200
    assert payload["slo"]["objectives"]["availability"]["verdict"] in (
        "fast_burn", "slow_burn", "ok")


# -- histogram exemplars -----------------------------------------------------


def test_histogram_exemplar_tracks_max_and_ring():
    reg = MetricsRegistry()
    h = reg.histogram("serving_request_total_seconds", "test")
    h.observe(0.2, exemplar="req-a")
    h.observe(0.9, exemplar="req-slow")
    h.observe(0.4, exemplar="req-b")
    h.observe(0.1)  # exemplar-less observations don't disturb the ring
    assert h.max_exemplar() == (0.9, "req-slow")
    assert [i for _, i in h.exemplars()] == ["req-a", "req-slow", "req-b"]
    # the ring is bounded: oldest exemplars age out, the max survives
    for k in range(20):
        h.observe(0.01, exemplar=f"req-{k}")
    assert len(h.exemplars()) == h.EXEMPLAR_RING
    assert h.max_exemplar() == (0.9, "req-slow")


def test_exemplar_rides_exposition_and_samples():
    reg = MetricsRegistry()
    h = reg.histogram("serving_request_total_seconds", "test",
                      labels={"component": "serving_replica_r1"})
    h.observe(1.25, exemplar="req-deadbeef")
    text = reg.dump()
    assert "# EXEMPLAR serving_request_total_seconds" in text
    assert 'request_id="req-deadbeef"' in text
    parsed = parse_prometheus_text(text)
    assert any(lab.get("request_id") == "req-deadbeef"
               for _, lab, _ in parsed["exemplars"])
    snap = h.sample()
    assert snap["max_exemplar"] == {"value": 1.25, "id": "req-deadbeef"}
    assert snap["exemplars"][0]["id"] == "req-deadbeef"


def test_fleet_rollup_names_slowest_request():
    agg = ClusterMetricsAggregator()
    reg = MetricsRegistry()
    reg.histogram("serving_request_total_seconds", "t").observe(
        0.8, exemplar="req-slowest")
    reg.counter("serving_spec_tokens_proposed_total", "t").inc(100)
    reg.counter("serving_spec_tokens_accepted_total", "t").inc(60)
    reg.counter("prefix_cache_hit_blocks_total", "t").inc(30)
    reg.counter("prefix_cache_miss_blocks_total", "t").inc(10)
    agg.ingest_component("serving_replica_r1", reg)
    roll = agg.serving_fleet_rollup()
    assert roll["spec_acceptance_rate"] == pytest.approx(0.6)
    assert roll["prefix_hit_rate"] == pytest.approx(0.75)
    assert roll["slowest_request"]["request_id"] == "req-slowest"
    assert roll["slowest_request"]["replica"] == "serving_replica_r1"
    text = format_summary(agg.summary())
    assert "spec acceptance" in text
    assert "req-slowest" in text
    dump = agg.dump()
    assert "dct_fleet_spec_acceptance_rate" in dump
    assert "dct_fleet_slowest_request" in dump
