"""deploy local (devcluster analogue) + Prometheus /metrics endpoint.

≈ the reference's devcluster boot (tools/devcluster.yaml) and
/prom/det-state-metrics (master/internal/core.go:1203).
"""
import subprocess
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"


def build_binaries():
    if (MASTER_DIR / "build" / "dct-master").exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


def test_deploy_local_cluster_lifecycle(tmp_path):
    if not build_binaries():
        pytest.skip("C++ build unavailable")
    from determined_clone_tpu.api.client import MasterSession
    from determined_clone_tpu.deploy import (
        cluster_down,
        cluster_status,
        cluster_up,
    )

    state_path = str(tmp_path / "cluster.json")
    state = cluster_up(n_agents=2, slots_per_agent=1,
                       base_dir=str(tmp_path / "cluster"),
                       state_path=state_path)
    try:
        assert state["came_up"]
        session = MasterSession("127.0.0.1", state["port"], timeout=5,
                                retries=3)
        agents = session.list_agents()
        assert len(agents) == 2
        assert {a["id"] for a in agents} == {"local-agent-0", "local-agent-1"}

        status = cluster_status(state_path=state_path)
        assert status["alive"]
        assert status["agents_alive"] == 2

        # double-up refuses
        with pytest.raises(RuntimeError):
            cluster_up(n_agents=1, state_path=state_path,
                       base_dir=str(tmp_path / "cluster2"))

        # prometheus endpoint on the deployed master
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{state['port']}/metrics", timeout=5
        ).read().decode()
        assert "dct_agents_alive 2" in body
        assert "dct_slots_total 2" in body
        assert "# TYPE dct_queue_depth gauge" in body
    finally:
        out = cluster_down(state_path=state_path)
    assert out["stopped"] >= 1
    assert cluster_status(state_path=state_path)["alive"] is False


def test_metrics_reflect_cluster_state(tmp_path):
    if not build_binaries():
        pytest.skip("C++ build unavailable")
    from determined_clone_tpu.deploy import cluster_down, cluster_up

    state_path = str(tmp_path / "c.json")
    state = cluster_up(n_agents=1, base_dir=str(tmp_path / "c"),
                       state_path=state_path)
    try:
        from determined_clone_tpu.api.client import MasterSession

        session = MasterSession("127.0.0.1", state["port"])
        # queue an unsatisfiable gang: shows up in queue depth
        session.create_experiment({
            "name": "starved", "entrypoint": "x:Y",
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 1}},
            "resources": {"slots_per_trial": 64},
            "hyperparameters": {},
        })
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{state['port']}/metrics", timeout=5
        ).read().decode()
        assert 'dct_experiments{state="RUNNING"} 1' in body
        assert "dct_queue_depth 1" in body
    finally:
        cluster_down(state_path=state_path)
