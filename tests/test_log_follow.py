"""Streaming log follow (VERDICT r3 #3): long-poll follow mode end-to-end.

The reference streams TrialLogs over gRPC with a follow flag
(/root/reference/proto/src/determined/api/v1/api.proto:781). Here the
master holds GET /allocations/:id/logs?follow=N open on a condition
variable pinged by every store append, so a follower sees new lines
within milliseconds of ingestion — no reconnect-per-poll, no tail
re-fetch — and is told end_of_stream when the allocation is terminal
and drained.
"""
import json
import os
import subprocess
import threading
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("follow")
    workdir = tmp / "agent-work"
    workdir.mkdir()

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "follow-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "port": port,
           "master_addr": f"127.0.0.1:{port}"}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


def wait_running(session, tid):
    deadline = time.time() + 30
    while time.time() < deadline:
        if session.get_task(tid)["state"] in ("RUNNING", "PULLING"):
            return
        time.sleep(0.2)
    raise AssertionError(f"task {tid} never started")


def drain_startup_noise(session, port, tid):
    """The shell task logs its own startup line on the agent's shipping
    cadence; settle and consume it so the assertions below are exact."""
    time.sleep(2.5)
    out, _ = follow_get(port, tid, 0, 0)
    return out["next_offset"]


def follow_get(port, alloc_id, offset, follow, timeout=60):
    t0 = time.monotonic()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/v1/allocations/{alloc_id}/logs"
            f"?limit=1000&offset={offset}&follow={follow}",
            timeout=timeout) as resp:
        return json.loads(resp.read()), time.monotonic() - t0


def test_follow_blocks_until_new_line_arrives(cluster):
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="follow-sh")
    tid = task["id"]
    wait_running(session, tid)
    base = drain_startup_noise(session, port, tid)

    session.post(f"/api/v1/allocations/{tid}/logs", {"logs": ["line-0"]})

    # backlog is served instantly, with a cursor
    out, took = follow_get(port, tid, base, 15)
    assert [r["log"] for r in out["logs"]] == ["line-0"]
    assert out["next_offset"] == base + 1
    assert not out["end_of_stream"]
    assert took < 5  # no pointless wait when data is ready

    # an empty cursor BLOCKS until the next line lands, then returns it
    result = {}

    def poll():
        result["out"], result["took"] = follow_get(port, tid,
                                                   out["next_offset"], 20)

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(1.5)
    session.post(f"/api/v1/allocations/{tid}/logs", {"logs": ["line-1"]})
    t.join(timeout=30)
    assert not t.is_alive()
    assert [r["log"] for r in result["out"]["logs"]] == ["line-1"]
    # it genuinely long-polled: waited for the post, woke promptly after
    assert 1.0 < result["took"] < 8.0
    session.kill_task(tid)


def test_follow_reports_end_of_stream_on_terminal(cluster):
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="follow-end")
    tid = task["id"]
    wait_running(session, tid)
    base = drain_startup_noise(session, port, tid)
    session.post(f"/api/v1/allocations/{tid}/logs", {"logs": ["bye"]})
    session.kill_task(tid)
    deadline = time.time() + 30
    while time.time() < deadline:
        if session.get_task(tid)["state"] in ("COMPLETED", "ERRORED",
                                              "CANCELED"):
            break
        time.sleep(0.2)

    # drain: records first (end_of_stream false while lines remain) ...
    out, _ = follow_get(port, tid, base, 10)
    assert "bye" in [r["log"] for r in out["logs"]]
    assert not out["end_of_stream"]
    # ... then a prompt end_of_stream, NOT a 10 s block
    out, took = follow_get(port, tid, out["next_offset"], 10)
    assert out["logs"] == []
    assert out["end_of_stream"]
    assert took < 5


def test_client_follow_generator_and_cli_tail(cluster):
    """session.follow_task_logs streams lines as they land and returns on
    end_of_stream; `det task logs -f` prints them and exits."""
    session = cluster["session"]
    task = session.create_task("shell", name="follow-gen")
    tid = task["id"]
    wait_running(session, tid)
    drain_startup_noise(session, cluster["port"], tid)
    session.post(f"/api/v1/allocations/{tid}/logs", {"logs": ["a", "b"]})

    got = []

    def consume():
        for rec in session.follow_task_logs(tid, follow_seconds=10):
            got.append(rec["log"])

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(1.0)
    session.post(f"/api/v1/allocations/{tid}/logs", {"logs": ["c"]})
    time.sleep(1.0)
    session.kill_task(tid)
    t.join(timeout=45)
    assert not t.is_alive(), "generator did not stop at end_of_stream"

    def subsequence(needles, haystack):
        it = iter(haystack)
        return all(any(n == h for h in it) for n in needles)

    # the task's own startup lines interleave; ours arrive in order
    assert subsequence(["a", "b", "c"], got), got

    # the CLI path over the same records (task already terminal: -f drains
    # and exits — the live blocking path is covered above)
    import contextlib
    import io

    from determined_clone_tpu.cli import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["-m", cluster["master_addr"], "task", "logs", "-f", tid])
    assert rc == 0
    assert subsequence(["a", "b", "c"], buf.getvalue().splitlines())
