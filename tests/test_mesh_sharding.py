"""Mesh + sharding tests, on the virtual 8-device CPU mesh (conftest.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from determined_clone_tpu.parallel import (
    MeshSpec,
    ShardingRules,
    batch_spec,
    constrain,
    data_parallel_submesh_size,
    make_mesh,
    mesh_axis_size,
    shard_put,
    single_device_mesh,
)


def test_eight_devices_available():
    assert jax.device_count() == 8


class TestMeshSpec:
    def test_resolve_wildcard(self):
        spec = MeshSpec(dp=-1, tp=2).resolve(8)
        assert spec.dp == 4 and spec.tp == 2

    def test_resolve_exact(self):
        spec = MeshSpec(dp=2, fsdp=2, tp=2).resolve(8)
        assert spec.axis_sizes() == (2, 2, 1, 1, 1, 2)

    def test_resolve_mismatch(self):
        with pytest.raises(ValueError, match="wants"):
            MeshSpec(dp=3, tp=2).resolve(8)
        with pytest.raises(ValueError, match="does not divide"):
            MeshSpec(dp=-1, tp=3).resolve(8)

    def test_two_wildcards_rejected(self):
        with pytest.raises(ValueError, match="at most one"):
            MeshSpec(dp=-1, fsdp=-1).resolve(8)

    def test_from_dict_unknown_axis(self):
        with pytest.raises(ValueError, match="unknown mesh axes"):
            MeshSpec.from_dict({"mp": 2})

    def test_dict_roundtrip(self):
        spec = MeshSpec(dp=2, fsdp=2, tp=2).resolve(8)
        assert MeshSpec.from_dict(spec.to_dict()) == spec


class TestMakeMesh:
    def test_all_axes_present(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        assert set(mesh.axis_names) == {"dp", "fsdp", "pp", "ep", "sp", "tp"}
        assert mesh_axis_size(mesh, "dp", "fsdp") == 4
        assert data_parallel_submesh_size(mesh) == 4

    def test_single_device_mesh(self):
        mesh = single_device_mesh()
        assert mesh.devices.size == 1

    def test_computation_on_mesh(self):
        mesh = make_mesh(MeshSpec(dp=-1))
        x = jnp.arange(32.0).reshape(8, 4)
        xs = shard_put(x, jax.NamedSharding(mesh, batch_spec(extra_dims=1)))

        @jax.jit
        def double(v):
            return v * 2

        out = double(xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 2)


class TestShardingRules:
    def _params(self):
        return {
            "blocks": {
                "0": {
                    "attn": {"wq": jnp.zeros((64, 64)), "bias": jnp.zeros((64,))},
                    "mlp": {"up": jnp.zeros((64, 256)), "down": jnp.zeros((256, 64))},
                },
            },
            "norm": {"scale": jnp.ones((64,))},
        }

    def test_rule_match_and_fsdp_fallback(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        rules = ShardingRules(rules=[
            (r"attn/wq$", P("fsdp", "tp")),
            (r"mlp/up$", P("fsdp", "tp")),
            (r"mlp/down$", P("tp", "fsdp")),
        ])
        sh = rules.shardings_for(self._params(), mesh)
        assert sh["blocks"]["0"]["attn"]["wq"].spec == P("fsdp", "tp")
        assert sh["blocks"]["0"]["mlp"]["down"].spec == P("tp", "fsdp")
        # bias/norm: unmatched + too small to fsdp-shard → replicated
        assert sh["norm"]["scale"].spec == P()
        # unmatched big leaves got an fsdp axis... none here; wq matched.

    def test_auto_fsdp_on_unmatched(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=4))
        params = {"w": jnp.zeros((128, 96))}
        sh = ShardingRules().shardings_for(params, mesh)
        assert sh["w"].spec == P("fsdp")  # dim 0 = 128 divisible by 4 and largest

    def test_trivial_axes_dropped(self):
        mesh = make_mesh(MeshSpec(dp=-1))  # tp size 1
        rules = ShardingRules(rules=[(r"w$", P("fsdp", "tp"))], fsdp_axis=None)
        sh = rules.shardings_for({"w": jnp.zeros((8, 8))}, mesh)
        assert sh["w"].spec == P()  # both axes trivial on a pure-dp mesh

    def test_sharded_params_math(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        params = {"w": jnp.arange(64.0 * 32).reshape(64, 32)}
        rules = ShardingRules(rules=[(r"w$", P("fsdp", "tp"))])
        sharded = shard_put(params, rules.shardings_for(params, mesh))

        @jax.jit
        def matmul(p, x):
            return x @ p["w"]

        x = jnp.ones((4, 64))
        np.testing.assert_allclose(
            np.asarray(matmul(sharded, x)),
            np.asarray(x @ params["w"]),
            rtol=1e-5,
        )

    def test_tied_leaves_get_per_path_rules(self):
        # weight tying: the same array object at two paths must still get
        # each path's own rule
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        w = jnp.zeros((64, 64))
        params = {"embed": {"w": w}, "head": {"w": w}}
        rules = ShardingRules(rules=[
            (r"embed/w$", P("tp", "fsdp")),
            (r"head/w$", P("fsdp", "tp")),
        ])
        sh = rules.shardings_for(params, mesh)
        assert sh["embed"]["w"].spec == P("tp", "fsdp")
        assert sh["head"]["w"].spec == P("fsdp", "tp")

    def test_constrain_inside_jit(self):
        mesh = make_mesh(MeshSpec(dp=-1))

        @jax.jit
        def f(x):
            h = x * 3
            return constrain(h, mesh, batch_spec(extra_dims=1))

        x = jnp.ones((8, 4))
        np.testing.assert_allclose(np.asarray(f(x)), 3.0)
