"""Core API tests. Multi-rank logic runs as N threads with real
DistributedContext objects — the reference's in-process gang simulation
(harness/tests/parallel.py:15-60)."""
import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from determined_clone_tpu import core
from determined_clone_tpu.config import CheckpointStorageConfig, ExperimentConfig
from determined_clone_tpu.core import (
    DistributedContext,
    LocalMetricsBackend,
    PreemptContext,
    FilePreemptionSource,
    load_pytree,
    save_pytree,
)
from determined_clone_tpu.storage import SharedFSStorageManager, build


def run_gang(size, fn):
    """Run fn(dist_ctx) on `size` threads; return results by rank."""
    ctxs = DistributedContext.make_local_group(size)
    with ThreadPoolExecutor(max_workers=size) as pool:
        return list(pool.map(fn, ctxs))


class TestDistributedContext:
    def test_single(self):
        d = DistributedContext.single()
        assert d.is_chief and d.allgather("x") == ["x"]
        assert d.broadcast("y") == "y"
        assert d.gather("z") == ["z"]

    def test_allgather(self):
        out = run_gang(4, lambda d: d.allgather(d.rank * 10))
        assert all(o == [0, 10, 20, 30] for o in out)

    def test_gather_chief_only(self):
        out = run_gang(3, lambda d: d.gather(f"r{d.rank}"))
        assert out[0] == ["r0", "r1", "r2"]
        assert out[1] is None and out[2] is None

    def test_broadcast(self):
        out = run_gang(4, lambda d: d.broadcast("c" if d.is_chief else None))
        assert out == ["c"] * 4

    def test_multiple_rounds(self):
        def fn(d):
            a = d.allgather(d.rank)
            b = d.broadcast(sum(a) if d.is_chief else None)
            d.barrier()
            return b

        assert run_gang(4, fn) == [6, 6, 6, 6]

    def test_bad_rank(self):
        with pytest.raises(core.DistributedError):
            DistributedContext(rank=5, size=2)

    def test_tcp_transport(self):
        # real sockets on localhost: chief + 2 workers. Retried on fresh
        # ports: a random port can collide with another process, and on
        # this single-core box concurrent suites can starve the threads
        # past any single attempt's timeout — only repeated hangs fail.
        import random
        from concurrent.futures import TimeoutError as FutTimeout

        def attempt(port):
            def fn(rank):
                d = DistributedContext.from_tcp("127.0.0.1", port, rank, 3)
                try:
                    got = d.allgather(f"rank{rank}")
                    bc = d.broadcast("hello" if rank == 0 else None)
                    return got, bc
                finally:
                    d.close()

            with ThreadPoolExecutor(max_workers=3) as pool:
                futs = [pool.submit(fn, r) for r in range(3)]
                return [f.result(timeout=120) for f in futs]

        last_exc = None
        for _ in range(3):
            try:
                results = attempt(random.randint(20000, 40000))
                break
            except (FutTimeout, OSError) as e:
                last_exc = e
        else:
            raise AssertionError(
                f"tcp transport failed 3 attempts: {last_exc!r}")
        for got, bc in results:
            assert got == ["rank0", "rank1", "rank2"]
            assert bc == "hello"


class TestStorage:
    def test_shared_fs_roundtrip(self, tmp_path):
        mgr = SharedFSStorageManager(str(tmp_path / "store"))
        src = tmp_path / "src"
        (src / "sub").mkdir(parents=True)
        (src / "a.txt").write_text("alpha")
        (src / "sub" / "b.txt").write_text("beta")
        mgr.upload(str(src), "ckpt1")
        assert set(mgr.list_files("ckpt1")) == {"a.txt", "sub/b.txt"}
        dst = tmp_path / "dst"
        dst.mkdir()
        mgr.download("ckpt1", str(dst))
        assert (dst / "sub" / "b.txt").read_text() == "beta"
        mgr.delete("ckpt1")
        assert mgr.list_files("ckpt1") == {}

    def test_storage_id_escape_rejected(self, tmp_path):
        mgr = SharedFSStorageManager(str(tmp_path))
        with pytest.raises(ValueError):
            mgr.upload(str(tmp_path), "../escape")

    def test_store_restore_path(self, tmp_path):
        mgr = SharedFSStorageManager(str(tmp_path / "store"))
        with mgr.store_path("cp") as d:
            with open(os.path.join(d, "w.txt"), "w") as f:
                f.write("data")
        with mgr.restore_path("cp") as d:
            assert open(os.path.join(d, "w.txt")).read() == "data"

    def test_build_factory_gates_cloud(self):
        with pytest.raises(RuntimeError, match="gcs"):
            build(CheckpointStorageConfig(type="gcs", bucket="b"))


class TestSerialization:
    def test_roundtrip(self, tmp_path):
        tree = {"a": jnp.arange(6.0).reshape(2, 3), "b": {"c": jnp.ones((4,))}}
        save_pytree(str(tmp_path), tree)
        like = {"a": jnp.zeros((2, 3)), "b": {"c": jnp.zeros((4,))}}
        got = load_pytree(str(tmp_path), like)
        np.testing.assert_allclose(np.asarray(got["a"]), np.asarray(tree["a"]))
        np.testing.assert_allclose(np.asarray(got["b"]["c"]), 1.0)

    def test_shape_mismatch_rejected(self, tmp_path):
        save_pytree(str(tmp_path), {"a": jnp.zeros((2,))})
        with pytest.raises(ValueError, match="shape"):
            load_pytree(str(tmp_path), {"a": jnp.zeros((3,))})

    def test_missing_leaf_rejected(self, tmp_path):
        save_pytree(str(tmp_path), {"a": jnp.zeros((2,))})
        with pytest.raises(KeyError):
            load_pytree(str(tmp_path), {"a": jnp.zeros((2,)), "b": jnp.zeros((1,))})

    def test_restore_onto_shardings(self, tmp_path):
        from determined_clone_tpu.parallel import MeshSpec, ShardingRules, make_mesh

        tree = {"w": jnp.arange(64.0 * 8).reshape(64, 8)}
        save_pytree(str(tmp_path), tree)
        mesh = make_mesh(MeshSpec(dp=2, fsdp=4))
        sh = ShardingRules().shardings_for(tree, mesh)
        got = load_pytree(str(tmp_path), tree, shardings=sh)
        assert got["w"].sharding == sh["w"]
        np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(tree["w"]))


class TestCheckpointContext:
    def test_sharded_upload_merges(self, tmp_path):
        store_dir = str(tmp_path / "store")

        def fn(d):
            mgr = SharedFSStorageManager(store_dir)
            ck = core.CheckpointContext(d, mgr)
            src = tmp_path / f"src{d.rank}"
            src.mkdir()
            (src / f"shard-{d.rank}.bin").write_text(f"data{d.rank}")
            return ck.upload(str(src), {"step": 7}, shard=True)

        ids = run_gang(3, fn)
        assert len(set(ids)) == 1  # same storage_id everywhere
        mgr = SharedFSStorageManager(store_dir)
        files = set(mgr.list_files(ids[0]))
        assert {"shard-0.bin", "shard-1.bin", "shard-2.bin"} <= files
        assert "metadata.json" in files

    def test_sharded_conflict_rejected(self, tmp_path):
        store_dir = str(tmp_path / "store")

        def fn(d):
            mgr = SharedFSStorageManager(store_dir)
            ck = core.CheckpointContext(d, mgr)
            src = tmp_path / f"c{d.rank}"
            src.mkdir()
            (src / "same.bin").write_text("x")  # every rank writes same name
            try:
                ck.upload(str(src), shard=True)
                return None
            except ValueError as e:
                return str(e)

        out = run_gang(2, fn)
        assert any(o and "conflict" in o for o in out)

    def test_registry_and_delete(self, tmp_path):
        d = DistributedContext.single()
        mgr = SharedFSStorageManager(str(tmp_path / "store"))
        reg = core.LocalCheckpointRegistry(str(tmp_path / "reg.jsonl"))
        ck = core.CheckpointContext(d, mgr, reg, trial_id=3)
        src = tmp_path / "src"
        src.mkdir()
        (src / "f.bin").write_text("x")
        sid = ck.upload(str(src), {"acc": 0.9})
        recs = reg.list()
        assert len(recs) == 1 and recs[0]["trial_id"] == 3
        assert ck.get_metadata(sid)["acc"] == 0.9
        ck.delete(sid)
        assert reg.list() == []


class TestPreemption:
    def test_file_source(self, tmp_path):
        flag = tmp_path / "preempt"
        d = DistributedContext.single()
        p = PreemptContext(d, FilePreemptionSource(str(flag)),
                           poll_interval=0.05).start()
        assert not p.should_preempt()
        flag.write_text("")
        import time

        deadline = time.time() + 5
        while not p.should_preempt() and time.time() < deadline:
            time.sleep(0.05)
        assert p.should_preempt()
        p.close()

    def test_chief_decision_broadcast(self, tmp_path):
        flag = tmp_path / "preempt"
        flag.write_text("")

        def fn(d):
            src = FilePreemptionSource(str(flag)) if d.is_chief else None
            p = PreemptContext(d, src, poll_interval=0.05).start()
            try:
                import time

                deadline = time.time() + 5
                while time.time() < deadline:
                    if p.should_preempt():
                        return True
                    time.sleep(0.05)
                return False
            finally:
                p.close()

        assert run_gang(2, fn) == [True, True]

    def test_signal(self):
        d = DistributedContext.single()
        p = PreemptContext(d).start()
        assert not p.should_preempt()
        p.signal()
        assert p.should_preempt()
        p.close()


class TestTrainContext:
    def test_metrics_and_best(self, tmp_path):
        backend = LocalMetricsBackend(str(tmp_path / "metrics.jsonl"))
        t = core.TrainContext(backend, metric="loss", smaller_is_better=True)
        t.report_training_metrics(10, {"loss": jnp.float32(1.5)})
        t.report_validation_metrics(10, {"loss": 1.2})
        t.report_validation_metrics(20, {"loss": 0.8})
        t.report_validation_metrics(30, {"loss": 0.9})
        assert t.get_experiment_best_validation() == 0.8
        lines = open(tmp_path / "metrics.jsonl").read().strip().split("\n")
        assert len(lines) == 4
        rec = json.loads(lines[0])
        assert rec["group"] == "training" and rec["metrics"]["loss"] == 1.5

    def test_nan_metrics_stay_json(self, tmp_path):
        backend = LocalMetricsBackend()
        t = core.TrainContext(backend)
        t.report_training_metrics(1, {"loss": float("nan")})
        assert backend.records[0]["metrics"]["loss"] == "nan"


class TestContextInit:
    def test_local_init_end_to_end(self, tmp_path):
        cfg = ExperimentConfig.from_dict({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 5}},
            "checkpoint_storage": {"type": "shared_fs",
                                   "host_path": str(tmp_path)},
        })
        with core.init(config=cfg, trial_id=1) as ctx:
            assert ctx.distributed.size == 1
            ops = list(ctx.searcher.operations())
            assert len(ops) == 1
            ops[0].complete(0.5)
            assert ops[0].completed
            ctx.train.report_training_metrics(1, {"loss": 1.0})
            assert not ctx.preempt.should_preempt()
