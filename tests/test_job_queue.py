"""Job-queue operator actions: move ahead/behind and reprioritize.

≈ the reference's job queue service over RM GetJobQ/MoveJob/
SetGroupPriority (resource_manager_iface.go:47-51), driven over REST like
e2e_tests/tests/cluster/test_job_queue.py.
"""
import pytest

from tests.test_platform import build_binaries, start_master

from determined_clone_tpu.api.client import MasterError


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("jobq")
    proc, session, port = start_master(tmp)
    yield {"session": session, "port": port, "proc": proc}
    proc.kill()
    proc.wait(timeout=10)


def queued(session):
    return sorted((j for j in session.job_queue() if j["state"] == "QUEUED"),
                  key=lambda j: (j["queued_at"], j["id"]))


def sched_counters(session):
    return session.get("/api/v1/cluster/scheduler")["counters"]


def test_move_and_reprioritize(master):
    session = master["session"]
    base = sched_counters(session)
    # no agents: command tasks stay queued, letting us reorder them
    t1 = session.create_task("command", cmd=["echo", "1"], slots=1)
    t2 = session.create_task("command", cmd=["echo", "2"], slots=1)
    t3 = session.create_task("command", cmd=["echo", "3"], slots=1)
    ids = [t["id"] for t in (t1, t2, t3)]
    assert [j["id"] for j in queued(session)] == ids

    # move t3 ahead of t1 -> order t3, t1, t2; it adopts t1's priority
    moved = session.move_job(t3["id"], ahead_of=t1["id"])
    assert moved["priority"] == t1["priority"]
    assert [j["id"] for j in queued(session)] == [ids[2], ids[0], ids[1]]

    # move t1 behind t2 -> order t3, t2, t1
    session.move_job(t1["id"], behind=t2["id"])
    assert [j["id"] for j in queued(session)] == [ids[2], ids[1], ids[0]]

    # reprioritize
    job = session.set_job_priority(t2["id"], 7)
    assert job["priority"] == 7
    assert next(j for j in session.job_queue()
                if j["id"] == t2["id"])["priority"] == 7

    # every operator action above is reflected in the scheduler's
    # control-plane counters (docs/observability.md): 2 moves + 1
    # reprioritize, each also counting into the reschedules umbrella
    c = sched_counters(session)
    assert c["queue_moves"] - base["queue_moves"] == 2
    assert c["priority_changes"] - base["priority_changes"] == 1
    assert c["reschedules"] - base["reschedules"] == 3

    # validation
    with pytest.raises(MasterError):
        session.move_job(t1["id"])  # no anchor
    with pytest.raises(MasterError):
        session.move_job(t1["id"], ahead_of=t2["id"], behind=t3["id"])
    with pytest.raises(MasterError):
        session.move_job(t1["id"], ahead_of="task-command-999")
    with pytest.raises(MasterError):
        session.set_job_priority("task-command-999", 3)

    # rejected operations must not have counted
    after_rejects = sched_counters(session)
    assert after_rejects["queue_moves"] == c["queue_moves"]
    assert after_rejects["priority_changes"] == c["priority_changes"]

    for tid in ids:
        session.kill_task(tid)


def test_only_queued_jobs_move(master):
    session = master["session"]
    t1 = session.create_task("command", cmd=["echo", "x"], slots=1)
    t2 = session.create_task("command", cmd=["echo", "y"], slots=1)
    # fake the anchor running via the agent surface is overkill; instead
    # kill t2 (terminal) and confirm a terminal job cannot be moved
    session.kill_task(t2["id"])
    with pytest.raises(MasterError) as err:
        session.move_job(t2["id"], ahead_of=t1["id"])
    assert err.value.status == 400
    session.kill_task(t1["id"])
